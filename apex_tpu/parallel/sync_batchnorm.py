"""SyncBatchNorm — batch norm with cross-replica statistics.

Reference: ``apex/parallel/optimized_sync_batchnorm.py:9`` +
``optimized_sync_batchnorm_kernel.py:10-111`` (CUDA welford kernels, stat
all-gather, backward allreduce of ``sum_dy``/``sum_dy_xmu``) and the pure
python fallback ``sync_batchnorm.py``.

TPU-native: local per-channel sums + ``psum`` over the data-parallel axis
(the parallel Welford merge of ``welford.cu:569`` is equivalent to
merging (Σx, Σx², n), which is what XLA's psum does in one fused
reduction).  The backward cross-replica terms arise automatically by
differentiating through ``psum`` — no hand-written backward needed — and
match the reference's allreduce of ``sum_dy``/``sum_dy_xmu``.

Uneven per-rank batches (reference
``two_gpu_test_different_batch_size.py``) are handled by psum-ing the
element *count* rather than multiplying by world size.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS


def sync_batch_norm_stats(x, reduce_axes, axis_name: Optional[str]):
    """Cross-replica per-channel (mean, var, count) for NCHW input."""
    n_local = 1
    for a in reduce_axes:
        n_local *= x.shape[a]
    xf = x.astype(jnp.float32)
    s1 = jnp.sum(xf, axis=reduce_axes)
    s2 = jnp.sum(jnp.square(xf), axis=reduce_axes)
    n = jnp.float32(n_local)
    if axis_name is not None:
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
        n = jax.lax.psum(n, axis_name)
    mean = s1 / n
    var = s2 / n - jnp.square(mean)  # biased (used for normalization)
    return mean, var, n


class SyncBatchNorm(nn.Module):
    """Drop-in for ``apex.parallel.SyncBatchNorm`` (NCHW layout).

    ``process_group`` becomes ``axis_name`` (None = no cross-replica sync,
    e.g. under pure pjit data parallelism where the batch axis is global).
    ``channel_last`` supported as in the reference (:9 options).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    channel_last: bool = False
    axis_name: Optional[str] = DATA_AXIS

    @nn.compact
    def __call__(self, x, use_running_average: bool = False):
        c_axis = x.ndim - 1 if self.channel_last else 1
        reduce_axes = tuple(a for a in range(x.ndim) if a != c_axis)

        ra_mean = self.variable(
            "batch_stats", "running_mean", lambda: jnp.zeros((self.num_features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "running_var", lambda: jnp.ones((self.num_features,), jnp.float32)
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            mean, var, n = sync_batch_norm_stats(x, reduce_axes, self.axis_name)
            if self.track_running_stats and not self.is_initializing():
                # unbiased var for running stats (reference kernel semantics)
                unbiased = var * n / jnp.maximum(n - 1, 1.0)
                ra_mean.value = (1 - self.momentum) * ra_mean.value + self.momentum * mean
                ra_var.value = (1 - self.momentum) * ra_var.value + self.momentum * unbiased

        shape = [1] * x.ndim
        shape[c_axis] = self.num_features
        xf = x.astype(jnp.float32)
        y = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self.eps)
        if self.affine:
            weight = self.param("weight", nn.initializers.ones, (self.num_features,), jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (self.num_features,), jnp.float32)
            y = y * weight.reshape(shape) + bias.reshape(shape)
        return y.astype(x.dtype)


SYNCBN_AXIS = "dp_sync"


def create_syncbn_process_group(group_size: int, world_size: Optional[int] = None):
    """Subgroup BN sync (reference: apex/parallel/__init__.py:60).

    The reference carves ``torch.distributed`` world into consecutive
    groups of ``group_size`` ranks and returns the current rank's group.
    TPU: groups are a mesh-axis split — shape the data-parallel devices
    as ``('dp', SYNCBN_AXIS)`` with sizes ``(world//group_size,
    group_size)`` and pass ``axis_name=SYNCBN_AXIS`` to
    :class:`SyncBatchNorm`; stats then psum only within the subgroup,
    exactly the reference's group semantics but riding ICI neighbors.

    Returns ``(axis_name, (num_groups, group_size))`` — the axis name to
    give SyncBatchNorm and the dp-axis split to build the Mesh with.
    """
    if world_size is None:
        world_size = jax.device_count()
    if group_size <= 0 or world_size % group_size != 0:
        raise ValueError(
            f"group_size {group_size} must evenly divide world size {world_size}"
        )
    return SYNCBN_AXIS, (world_size // group_size, group_size)


def convert_syncbn_model(module, process_group=None, channel_last: bool = False):
    """Reference: apex/parallel/__init__.py:21.  In flax, modules are
    declarative — use :class:`SyncBatchNorm` in the model definition; this
    helper exists for API discovery and raises with guidance."""
    raise NotImplementedError(
        "flax modules are declarative: replace nn.BatchNorm with "
        "apex_tpu.parallel.SyncBatchNorm in the model definition"
    )
