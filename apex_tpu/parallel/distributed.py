"""Data-parallel gradient synchronization.

Reference: ``apex/parallel/distributed.py`` — ``DistributedDataParallel``
(:131) maintains flat fp16/fp32 buckets, hooks every grad accumulator,
overlaps per-bucket NCCL allreduce with backward on side streams, and
optionally predivides / upcasts for the reduction.

TPU-native: **the entire mechanism reduces to a ``psum`` over the ``dp``
mesh axis inside the jitted step.**  Bucketing, stream management, hook
ordering, and comm/compute overlap are all owned by XLA's latency-hiding
scheduler; what remains semantic — and is preserved here — are the
numerics knobs:

- ``gradient_average``: divide by dp world size after the sum
  (distributed.py:458-462).
- ``gradient_predivide_factor``: divide by f before, ``world/f`` after
  (distributed.py:164-177) for large-world overflow control.
- ``allreduce_always_fp32``: upcast half grads to fp32 for the reduction
  (distributed.py:449-456).

``message_size``/``num_allreduce_streams``/``delay_allreduce`` from the
reference configure the overlap engine and have no TPU meaning; the
``DistributedDataParallel`` wrapper accepts and ignores them.

This module is the REPLICATED-state grad sync (every rank applies the
same update).  When optimizer state is ZeRO-sharded over dp, the sync
is owned by the optimizer instead: ``contrib.optimizers.
DistributedFusedAdam``/``DistributedFusedLAMB`` reduce-scatter each
dtype bucket in ``grad_sync_dtype`` (half the allreduce's wire bytes,
and each rank only reads the 1/dp shard it updates), so steps built
with a ZeRO optimizer must NOT also psum their grads — the gpt step
builders skip the dp pmean automatically.
"""


import contextlib

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS


def allreduce_gradients(
    grads,
    axis_name: str = DATA_AXIS,
    gradient_average: bool = True,
    gradient_predivide_factor: float = 1.0,
    allreduce_always_fp32: bool = False,
):
    """psum grads over the data-parallel axis (use inside shard_map/jit).

    The one-call equivalent of the reference's bucketed overlap engine
    (``allreduce_bucket``, distributed.py:429-479).
    """
    world = jax.lax.axis_size(axis_name)

    def prep(g):
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor != 1.0:
            g = g / gradient_predivide_factor
        return g

    def post(g, orig):
        if gradient_average:
            g = g / (world / gradient_predivide_factor)
        elif gradient_predivide_factor != 1.0:
            g = g * gradient_predivide_factor
        return g.astype(orig.dtype)

    pre = jax.tree.map(prep, grads)
    summed = jax.lax.psum(pre, axis_name)
    return jax.tree.map(post, summed, grads)


class Reducer:
    """Reference: apex/parallel/distributed.py:91 — manual allreduce of a
    module's params/grads on demand."""

    def __init__(self, axis_name: str = DATA_AXIS):
        self.axis_name = axis_name

    def reduce(self, tree):
        world = jax.lax.axis_size(self.axis_name)
        return jax.tree.map(lambda x: jax.lax.psum(x, self.axis_name) / world, tree)


class DistributedDataParallel:
    """API-parity wrapper: ``ddp = DistributedDataParallel(...)``,
    ``grads = ddp.sync(grads)`` inside the step.

    Overlap-engine options are accepted for source compatibility and
    ignored (XLA owns scheduling).
    """

    def __init__(
        self,
        module=None,
        message_size: int = 10000000,
        delay_allreduce: bool = False,
        shared_param=None,
        allreduce_trigger_params=None,
        retain_allreduce_buffers: bool = False,
        allreduce_always_fp32: bool = False,
        num_allreduce_streams: int = 1,
        allreduce_communicators=None,
        gradient_average: bool = True,
        gradient_predivide_factor: float = 1.0,
        gradient_average_split_factor=None,
        prof: bool = False,
        axis_name: str = DATA_AXIS,
    ):
        self.module = module
        self.axis_name = axis_name
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.prof = prof

    def sync(self, grads):
        ctx = contextlib.nullcontext()
        if self.prof:  # reference distributed.py:363 nvtx range
            from apex_tpu.utils.profiler import nvtx_range

            ctx = nvtx_range("allreduce_gradients")
        with ctx:
            return allreduce_gradients(
                grads,
                axis_name=self.axis_name,
                gradient_average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                allreduce_always_fp32=self.allreduce_always_fp32,
            )

    def __call__(self, *args, **kwargs):
        if self.module is None:
            raise ValueError("no module wrapped")
        return self.module(*args, **kwargs)
