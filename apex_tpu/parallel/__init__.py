"""Data parallelism (reference: ``apex/parallel``)."""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
)
from apex_tpu.parallel.LARC import LARC
from apex_tpu.parallel.sync_batchnorm import (
    SYNCBN_AXIS,
    SyncBatchNorm,
    convert_syncbn_model,
    create_syncbn_process_group,
    sync_batch_norm_stats,
)

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "allreduce_gradients",
    "LARC",
    "SYNCBN_AXIS",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "create_syncbn_process_group",
    "sync_batch_norm_stats",
]
