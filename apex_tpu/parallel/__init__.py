"""Data parallelism (reference: ``apex/parallel``)."""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
)
from apex_tpu.parallel.LARC import LARC
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    convert_syncbn_model,
    sync_batch_norm_stats,
)

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "allreduce_gradients",
    "LARC",
    "SyncBatchNorm",
    "convert_syncbn_model",
    "sync_batch_norm_stats",
]
