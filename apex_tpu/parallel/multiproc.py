"""Multi-process launcher shim.

Reference: ``apex/parallel/multiproc.py`` — a pre-torchrun process
launcher (superseded even in the reference by
``torch.distributed.launch``).

On TPU pods, process-per-host launch is owned by the infrastructure
(GKE/xmanager/`gcloud compute tpus tpu-vm ssh --worker=all`); inside
each process call :func:`initialize_distributed` —
``jax.distributed.initialize`` + mesh construction — instead of a
python launcher.
"""

from typing import Optional

import jax


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX (the ``init_process_group`` analog)."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def main():  # pragma: no cover - parity stub
    raise SystemExit(
        "apex_tpu has no process launcher: launch one process per host via "
        "your TPU orchestration and call "
        "apex_tpu.parallel.multiproc.initialize_distributed() in each."
    )


if __name__ == "__main__":
    main()
