"""Fused LayerNorm / RMSNorm.

Reference: ``apex/normalization/fused_layer_norm.py`` (autograd Functions
:32-192, modules :230-468, ``manual_rms_norm`` :16) backed by
``csrc/layer_norm_cuda_kernel.cu`` (Welford row stats, affine and
non-affine, mixed input/param dtypes, memory-efficient backward that
recomputes the input from the output).

TPU design: row statistics and the normalize/affine epilogue are one XLA
fusion (stats in fp32 regardless of input dtype, matching the kernels'
accumulation type), wrapped in ``jax.custom_vjp`` so the backward can
implement the *memory-efficient* variant: when ``memory_efficient=True``
the residuals are ``(output, invvar)`` and x̂ is recomputed as
``(y - b)/w`` (LayerNorm) or ``y/w`` (RMSNorm) — the input is never
saved, halving activation memory, exactly as the reference kernels do.
A Pallas kernel path (:mod:`apex_tpu.ops.layer_norm_pallas`) is used on
TPU for long rows; the math here is the specification and fallback.
"""

import numbers
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _canon_shape(normalized_shape):
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(s) for s in normalized_shape)


def _lead_sum(t, dims):
    """Sum ``t`` over its lead (non-normalized) axes as a dot_general
    with a ones vector rather than a ``reduce``: a reduce's summation
    order is a fusion-context choice, but a dot's is fixed by the dot
    kernel — so the dw/db sums associate identically in the shard_map
    program and its GSPMD-partitioned twin (the weight-grad dots
    already match bitwise between the two; this puts the LN param
    grads on the same footing)."""
    lead_axes = tuple(range(t.ndim - len(dims)))
    ones = jnp.ones(tuple(t.shape[a] for a in lead_axes), jnp.float32)
    return jax.lax.dot_general(
        ones, t, ((lead_axes, lead_axes), ((), ())))


def _norm_dims(x, normalized_shape):
    """(reduce axes, lead shape, row length) of ``x`` under
    ``normalized_shape``.  The jnp implementations reduce over these
    AXES instead of flattening to ``(rows, n)``: a reshape that fuses a
    sharded leading dim (the batch of an ``(S, B, H)`` activation)
    forces GSPMD to all-gather and re-associate the dw/db row sums,
    which breaks bitwise parity between the ``spmd="auto"`` train step
    and the shard_map oracle.  Axis-based reductions keep the partial
    sum per device + one all-reduce — the same association shard_map
    spells by hand.  (The Pallas kernels still take the ``(rows, n)``
    view; that reshape lives at their call seam only.)"""
    k = len(normalized_shape)
    dims = tuple(range(x.ndim - k, x.ndim))
    lead = x.shape[: x.ndim - k]
    return dims, lead, int(np.prod(normalized_shape))


def manual_rms_norm(x, normalized_shape, weight, eps):
    """Pure reference (apex/normalization/fused_layer_norm.py:16-29)."""
    dims = tuple(range(-len(_canon_shape(normalized_shape)), 0))
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=dims, keepdims=True)
    out = x * jax.lax.rsqrt(var + eps).astype(x.dtype)
    if weight is None:
        return out
    return out * weight


# =============================================================== layer norm
@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _layer_norm(x, weight, bias, normalized_shape, eps, memory_efficient):
    out, _, _ = _ln_fwd_impl(x, weight, bias, normalized_shape, eps)
    return out


def _ln_fwd_impl(x, weight, bias, normalized_shape, eps):
    """Returns ``(out, mean, invvar)`` with the stats in LEAD shape
    (``x.shape`` minus the normalized trailing dims)."""
    dims, lead, n = _norm_dims(x, normalized_shape)
    from apex_tpu.ops.layer_norm_pallas import layer_norm_fwd_pallas, pallas_available

    def pallas_impl():
        x2 = x.reshape((-1, n))
        w = weight.reshape(n) if weight is not None else None
        b = bias.reshape(n) if bias is not None else None
        y, mean, rstd = layer_norm_fwd_pallas(x2, w, b, eps)
        return y.reshape(x.shape), mean[:, 0].reshape(lead), \
            rstd[:, 0].reshape(lead)

    def jnp_impl():
        # the (rows, n) view here is deliberate — see _norm_dims: the
        # row-stat math is per-row either way, but the 2D view is the
        # one whose shard_map and GSPMD compilations agree bitwise
        xf = x.reshape((-1, n)).astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=1, keepdims=True)
        invvar = jax.lax.rsqrt(var + eps)
        xhat = (xf - mean) * invvar
        y = xhat
        if weight is not None:
            y = y * weight.reshape(1, n).astype(jnp.float32)
        if bias is not None:
            y = y + bias.reshape(1, n).astype(jnp.float32)
        out = y.astype(x.dtype).reshape(x.shape)
        return out, mean[:, 0].reshape(lead), invvar[:, 0].reshape(lead)

    if pallas_available(x, n):
        # no registry_engaged gate (here or in the bwd): both impls are
        # collective-free per-row math, so a per-process degrade cannot
        # desync a pod's collective programs, and there is no forced-
        # impl knob to honor (pallas_available gates by platform)
        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call("layer_norm", pallas_impl, jnp_impl)
    return jnp_impl()


def _ln_fwd(x, weight, bias, normalized_shape, eps, memory_efficient):
    out, mean, invvar = _ln_fwd_impl(x, weight, bias, normalized_shape, eps)
    if memory_efficient:
        res = (out, None, invvar, weight, bias)
    else:
        res = (x, mean, invvar, weight, bias)
    return out, res


def _ln_bwd(normalized_shape, eps, memory_efficient, res, g):
    saved, mean, invvar, weight, bias = res
    dims, lead, n = _norm_dims(g, normalized_shape)

    from apex_tpu.ops.layer_norm_pallas import layer_norm_bwd_pallas, pallas_available

    if not memory_efficient and pallas_available(g, n):
        def pallas_impl():
            x2 = saved.reshape((-1, n))
            g2 = g.reshape((-1, n))
            w = weight.reshape(n) if weight is not None else None
            dx, dw_p, db_p = layer_norm_bwd_pallas(
                x2, w, g2, mean.reshape((-1, 1)), invvar.reshape((-1, 1)),
                with_bias=bias is not None
            )
            dx = dx.reshape(g.shape).astype(g.dtype)
            dw = dw_p.sum(0).reshape(weight.shape).astype(weight.dtype) if weight is not None else None
            db = db_p.sum(0).reshape(bias.shape).astype(bias.dtype) if (bias is not None and db_p is not None) else None
            return dx, dw, db

        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _ln_bwd_jnp(saved, mean, invvar, weight, bias, g,
                                dims, memory_efficient))

    return _ln_bwd_jnp(saved, mean, invvar, weight, bias, g, dims,
                       memory_efficient)


def _ln_bwd_jnp(saved, mean, invvar, weight, bias, g, dims,
                memory_efficient):
    """The jnp composite backward — the specification the Pallas kernel
    is checked against, and the registry's fallback when it trips.
    Axis-based (see :func:`_norm_dims`): the dw/db row sums reduce over
    the LEAD axes in place, so a sharded batch dim stays sharded."""
    gf = g.astype(jnp.float32)
    inv = jnp.expand_dims(invvar, dims)
    norm_shape = tuple(g.shape[a] for a in dims)
    wf = weight.reshape(norm_shape).astype(jnp.float32) \
        if weight is not None else None

    if memory_efficient:
        yf = saved.astype(jnp.float32)
        if bias is not None:
            yf = yf - bias.reshape(norm_shape).astype(jnp.float32)
        xhat = yf / wf if wf is not None else yf
    else:
        xf = saved.astype(jnp.float32)
        xhat = (xf - jnp.expand_dims(mean, dims)) * inv

    gw = gf * wf if wf is not None else gf

    m1 = jnp.mean(gw, axis=dims, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=dims, keepdims=True)
    dx = (gw - m1 - xhat * m2) * inv
    dx = dx.astype(g.dtype)

    if weight is not None:
        dw = _lead_sum(gf * xhat, dims) \
            .reshape(weight.shape).astype(weight.dtype)
    else:
        dw = None
    if bias is not None:
        db = _lead_sum(gf, dims).reshape(bias.shape).astype(bias.dtype)
    else:
        db = None
    return dx, dw, db


_layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ================================================================ rms norm
@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms_norm(x, weight, normalized_shape, eps, memory_efficient):
    out, _ = _rms_fwd_impl(x, weight, normalized_shape, eps)
    return out


def _rms_fwd_impl(x, weight, normalized_shape, eps):
    """Returns ``(out, invvar)`` with the stats in LEAD shape."""
    dims, lead, n = _norm_dims(x, normalized_shape)
    from apex_tpu.ops.layer_norm_pallas import layer_norm_fwd_pallas, pallas_available

    def pallas_impl():
        x2 = x.reshape((-1, n))
        w = weight.reshape(n) if weight is not None else None
        y, _, rstd = layer_norm_fwd_pallas(x2, w, None, eps, rms=True)
        return y.reshape(x.shape), rstd[:, 0].reshape(lead)

    if pallas_available(x, n):
        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _rms_fwd_jnp(x, weight, dims, lead, eps))
    return _rms_fwd_jnp(x, weight, dims, lead, eps)


def _rms_fwd_jnp(x, weight, dims, lead, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=dims, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    y = xf * invvar
    if weight is not None:
        norm_shape = tuple(x.shape[a] for a in dims)
        y = y * weight.reshape(norm_shape).astype(jnp.float32)
    return y.astype(x.dtype), invvar.reshape(lead)


def _rms_fwd(x, weight, normalized_shape, eps, memory_efficient):
    out, invvar = _rms_fwd_impl(x, weight, normalized_shape, eps)
    res = (out if memory_efficient else x, invvar, weight)
    return out, res


def _rms_bwd(normalized_shape, eps, memory_efficient, res, g):
    saved, invvar, weight = res
    dims, lead, n = _norm_dims(g, normalized_shape)

    from apex_tpu.ops.layer_norm_pallas import layer_norm_bwd_pallas, pallas_available

    if not memory_efficient and pallas_available(g, n):
        def pallas_impl():
            x2 = saved.reshape((-1, n))
            g2 = g.reshape((-1, n))
            inv2 = invvar.reshape((-1, 1))
            w = weight.reshape(n) if weight is not None else None
            dx, dw_p, _ = layer_norm_bwd_pallas(
                x2, w, g2, jnp.zeros_like(inv2), inv2,
                rms=True, with_bias=False,
            )
            dx = dx.reshape(g.shape).astype(g.dtype)
            dw = dw_p.sum(0).reshape(weight.shape).astype(weight.dtype) if weight is not None else None
            return dx, dw

        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _rms_bwd_jnp(saved, invvar, weight, g, dims,
                                 memory_efficient))

    return _rms_bwd_jnp(saved, invvar, weight, g, dims, memory_efficient)


def _rms_bwd_jnp(saved, invvar, weight, g, dims, memory_efficient):
    gf = g.astype(jnp.float32)
    inv = jnp.expand_dims(invvar, dims)
    norm_shape = tuple(g.shape[a] for a in dims)
    wf = weight.reshape(norm_shape).astype(jnp.float32) \
        if weight is not None else None

    if memory_efficient:
        yf = saved.astype(jnp.float32)
        xhat = yf / wf if wf is not None else yf
    else:
        xhat = saved.astype(jnp.float32) * inv

    gw = gf * wf if wf is not None else gf
    m2 = jnp.mean(gw * xhat, axis=dims, keepdims=True)
    dx = (gw - xhat * m2) * inv
    dx = dx.astype(g.dtype)

    if weight is not None:
        dw = _lead_sum(gf * xhat, dims) \
            .reshape(weight.shape).astype(weight.dtype)
    else:
        dw = None
    return dx, dw


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


# ======================================================== public functions
def fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedLayerNormAffineFunction (fused_layer_norm.py:32)."""
    return _layer_norm(input, weight, bias, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_layer_norm(input, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedLayerNormFunction (non-affine)."""
    return _layer_norm(input, None, None, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedRMSNormAffineFunction (fused_layer_norm.py:64)."""
    return _rms_norm(input, weight, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_rms_norm(input, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedRMSNormFunction."""
    return _rms_norm(input, None, _canon_shape(normalized_shape), eps, memory_efficient)


def mixed_dtype_fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6, memory_efficient=False):
    """Mixed input/param dtype variant (fused_layer_norm.py:94) — params may
    be fp32 while the input is half; output keeps the input dtype."""
    return fused_layer_norm_affine(input, weight, bias, normalized_shape, eps, memory_efficient)


def mixed_dtype_fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6, memory_efficient=False):
    """Mixed dtype RMSNorm (fused_layer_norm.py:117)."""
    return fused_rms_norm_affine(input, weight, normalized_shape, eps, memory_efficient)


# ================================================================= modules
import flax.linen as nn


class FusedLayerNorm(nn.Module):
    """Module parity with ``apex.normalization.FusedLayerNorm``
    (fused_layer_norm.py:230).  Param dtype is fp32 (the "mixed" behavior
    is the TPU default — inputs may be bf16)."""

    normalized_shape: Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, shape, jnp.float32)
            return fused_layer_norm_affine(x, weight, bias, shape, self.eps, self.memory_efficient)
        return fused_layer_norm(x, shape, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """Module parity with ``apex.normalization.FusedRMSNorm``
    (fused_layer_norm.py:329)."""

    normalized_shape: Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, jnp.float32)
            return fused_rms_norm_affine(x, weight, shape, self.eps, self.memory_efficient)
        return fused_rms_norm(x, shape, self.eps, self.memory_efficient)


# Mixed variants are the same computation on TPU (params already fp32).
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
