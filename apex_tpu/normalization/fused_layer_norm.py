"""Fused LayerNorm / RMSNorm.

Reference: ``apex/normalization/fused_layer_norm.py`` (autograd Functions
:32-192, modules :230-468, ``manual_rms_norm`` :16) backed by
``csrc/layer_norm_cuda_kernel.cu`` (Welford row stats, affine and
non-affine, mixed input/param dtypes, memory-efficient backward that
recomputes the input from the output).

TPU design: row statistics and the normalize/affine epilogue are one XLA
fusion (stats in fp32 regardless of input dtype, matching the kernels'
accumulation type), wrapped in ``jax.custom_vjp`` so the backward can
implement the *memory-efficient* variant: when ``memory_efficient=True``
the residuals are ``(output, invvar)`` and x̂ is recomputed as
``(y - b)/w`` (LayerNorm) or ``y/w`` (RMSNorm) — the input is never
saved, halving activation memory, exactly as the reference kernels do.
A Pallas kernel path (:mod:`apex_tpu.ops.layer_norm_pallas`) is used on
TPU for long rows; the math here is the specification and fallback.
"""

import numbers
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _canon_shape(normalized_shape):
    if isinstance(normalized_shape, numbers.Integral):
        return (int(normalized_shape),)
    return tuple(int(s) for s in normalized_shape)


def _rows_view(x, normalized_shape):
    n = int(np.prod(normalized_shape))
    lead = x.shape[: x.ndim - len(normalized_shape)]
    return x.reshape((-1, n)), lead, n


def manual_rms_norm(x, normalized_shape, weight, eps):
    """Pure reference (apex/normalization/fused_layer_norm.py:16-29)."""
    dims = tuple(range(-len(_canon_shape(normalized_shape)), 0))
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=dims, keepdims=True)
    out = x * jax.lax.rsqrt(var + eps).astype(x.dtype)
    if weight is None:
        return out
    return out * weight


# =============================================================== layer norm
@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _layer_norm(x, weight, bias, normalized_shape, eps, memory_efficient):
    out, _, _ = _ln_fwd_impl(x, weight, bias, normalized_shape, eps)
    return out


def _ln_fwd_impl(x, weight, bias, normalized_shape, eps):
    x2, lead, n = _rows_view(x, normalized_shape)
    from apex_tpu.ops.layer_norm_pallas import layer_norm_fwd_pallas, pallas_available

    def pallas_impl():
        w = weight.reshape(n) if weight is not None else None
        b = bias.reshape(n) if bias is not None else None
        y, mean, rstd = layer_norm_fwd_pallas(x2, w, b, eps)
        return y.reshape(x.shape), mean[:, 0], rstd[:, 0]

    def jnp_impl():
        xf = x2.astype(jnp.float32)
        mean = jnp.mean(xf, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=1, keepdims=True)
        invvar = jax.lax.rsqrt(var + eps)
        xhat = (xf - mean) * invvar
        y = xhat
        if weight is not None:
            y = y * weight.reshape(1, n).astype(jnp.float32)
        if bias is not None:
            y = y + bias.reshape(1, n).astype(jnp.float32)
        out = y.astype(x.dtype).reshape(x.shape)
        return out, mean[:, 0], invvar[:, 0]

    if pallas_available(x2, n):
        # no registry_engaged gate (here or in the bwd): both impls are
        # collective-free per-row math, so a per-process degrade cannot
        # desync a pod's collective programs, and there is no forced-
        # impl knob to honor (pallas_available gates by platform)
        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call("layer_norm", pallas_impl, jnp_impl)
    return jnp_impl()


def _ln_fwd(x, weight, bias, normalized_shape, eps, memory_efficient):
    out, mean, invvar = _ln_fwd_impl(x, weight, bias, normalized_shape, eps)
    if memory_efficient:
        res = (out, None, invvar, weight, bias)
    else:
        res = (x, mean, invvar, weight, bias)
    return out, res


def _ln_bwd(normalized_shape, eps, memory_efficient, res, g):
    saved, mean, invvar, weight, bias = res
    g2, lead, n = _rows_view(g, normalized_shape)

    from apex_tpu.ops.layer_norm_pallas import layer_norm_bwd_pallas, pallas_available

    if not memory_efficient and pallas_available(g2, n):
        def pallas_impl():
            x2 = saved.reshape((-1, n))
            w = weight.reshape(n) if weight is not None else None
            dx, dw_p, db_p = layer_norm_bwd_pallas(
                x2, w, g2, mean[:, None], invvar[:, None], with_bias=bias is not None
            )
            dx = dx.reshape(g.shape).astype(g.dtype)
            dw = dw_p.sum(0).reshape(weight.shape).astype(weight.dtype) if weight is not None else None
            db = db_p.sum(0).reshape(bias.shape).astype(bias.dtype) if (bias is not None and db_p is not None) else None
            return dx, dw, db

        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _ln_bwd_jnp(saved, mean, invvar, weight, bias, g2, g,
                                n, memory_efficient))

    return _ln_bwd_jnp(saved, mean, invvar, weight, bias, g2, g, n,
                       memory_efficient)


def _ln_bwd_jnp(saved, mean, invvar, weight, bias, g2, g, n,
                memory_efficient):
    """The jnp composite backward — the specification the Pallas kernel
    is checked against, and the registry's fallback when it trips."""
    gf = g2.astype(jnp.float32)
    inv = invvar[:, None]

    if memory_efficient:
        yf = saved.reshape((-1, n)).astype(jnp.float32)
        if bias is not None:
            yf = yf - bias.reshape(1, n).astype(jnp.float32)
        if weight is not None:
            xhat = yf / weight.reshape(1, n).astype(jnp.float32)
        else:
            xhat = yf
    else:
        xf = saved.reshape((-1, n)).astype(jnp.float32)
        xhat = (xf - mean[:, None]) * inv

    if weight is not None:
        gw = gf * weight.reshape(1, n).astype(jnp.float32)
    else:
        gw = gf

    m1 = jnp.mean(gw, axis=1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=1, keepdims=True)
    dx = (gw - m1 - xhat * m2) * inv
    dx = dx.astype(g.dtype).reshape(g.shape)

    if weight is not None:
        dw = jnp.sum(gf * xhat, axis=0).reshape(weight.shape).astype(weight.dtype)
    else:
        dw = None
    if bias is not None:
        db = jnp.sum(gf, axis=0).reshape(bias.shape).astype(bias.dtype)
    else:
        db = None
    return dx, dw, db


_layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ================================================================ rms norm
@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _rms_norm(x, weight, normalized_shape, eps, memory_efficient):
    out, _ = _rms_fwd_impl(x, weight, normalized_shape, eps)
    return out


def _rms_fwd_impl(x, weight, normalized_shape, eps):
    x2, lead, n = _rows_view(x, normalized_shape)
    from apex_tpu.ops.layer_norm_pallas import layer_norm_fwd_pallas, pallas_available

    def pallas_impl():
        w = weight.reshape(n) if weight is not None else None
        y, _, rstd = layer_norm_fwd_pallas(x2, w, None, eps, rms=True)
        return y.reshape(x.shape), rstd[:, 0]

    if pallas_available(x2, n):
        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _rms_fwd_jnp(x, x2, weight, n, eps))
    return _rms_fwd_jnp(x, x2, weight, n, eps)


def _rms_fwd_jnp(x, x2, weight, n, eps):
    xf = x2.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=1, keepdims=True)
    invvar = jax.lax.rsqrt(var + eps)
    y = xf * invvar
    if weight is not None:
        y = y * weight.reshape(1, n).astype(jnp.float32)
    return y.astype(x.dtype).reshape(x.shape), invvar[:, 0]


def _rms_fwd(x, weight, normalized_shape, eps, memory_efficient):
    out, invvar = _rms_fwd_impl(x, weight, normalized_shape, eps)
    res = (out if memory_efficient else x, invvar, weight)
    return out, res


def _rms_bwd(normalized_shape, eps, memory_efficient, res, g):
    saved, invvar, weight = res
    g2, lead, n = _rows_view(g, normalized_shape)

    from apex_tpu.ops.layer_norm_pallas import layer_norm_bwd_pallas, pallas_available

    if not memory_efficient and pallas_available(g2, n):
        def pallas_impl():
            x2 = saved.reshape((-1, n))
            w = weight.reshape(n) if weight is not None else None
            dx, dw_p, _ = layer_norm_bwd_pallas(
                x2, w, g2, jnp.zeros_like(invvar)[:, None], invvar[:, None],
                rms=True, with_bias=False,
            )
            dx = dx.reshape(g.shape).astype(g.dtype)
            dw = dw_p.sum(0).reshape(weight.shape).astype(weight.dtype) if weight is not None else None
            return dx, dw

        from apex_tpu.resilience.fallback import get_registry

        return get_registry().call(
            "layer_norm", pallas_impl,
            lambda: _rms_bwd_jnp(saved, invvar, weight, g2, g, n,
                                 memory_efficient))

    return _rms_bwd_jnp(saved, invvar, weight, g2, g, n, memory_efficient)


def _rms_bwd_jnp(saved, invvar, weight, g2, g, n, memory_efficient):
    gf = g2.astype(jnp.float32)
    inv = invvar[:, None]

    if memory_efficient:
        yf = saved.reshape((-1, n)).astype(jnp.float32)
        xhat = yf / weight.reshape(1, n).astype(jnp.float32) if weight is not None else yf
    else:
        xhat = saved.reshape((-1, n)).astype(jnp.float32) * inv

    gw = gf * weight.reshape(1, n).astype(jnp.float32) if weight is not None else gf
    m2 = jnp.mean(gw * xhat, axis=1, keepdims=True)
    dx = (gw - xhat * m2) * inv
    dx = dx.astype(g.dtype).reshape(g.shape)

    if weight is not None:
        dw = jnp.sum(gf * xhat, axis=0).reshape(weight.shape).astype(weight.dtype)
    else:
        dw = None
    return dx, dw


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


# ======================================================== public functions
def fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedLayerNormAffineFunction (fused_layer_norm.py:32)."""
    return _layer_norm(input, weight, bias, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_layer_norm(input, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedLayerNormFunction (non-affine)."""
    return _layer_norm(input, None, None, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedRMSNormAffineFunction (fused_layer_norm.py:64)."""
    return _rms_norm(input, weight, _canon_shape(normalized_shape), eps, memory_efficient)


def fused_rms_norm(input, normalized_shape, eps=1e-6, memory_efficient=False):
    """Reference: FusedRMSNormFunction."""
    return _rms_norm(input, None, _canon_shape(normalized_shape), eps, memory_efficient)


def mixed_dtype_fused_layer_norm_affine(input, weight, bias, normalized_shape, eps=1e-6, memory_efficient=False):
    """Mixed input/param dtype variant (fused_layer_norm.py:94) — params may
    be fp32 while the input is half; output keeps the input dtype."""
    return fused_layer_norm_affine(input, weight, bias, normalized_shape, eps, memory_efficient)


def mixed_dtype_fused_rms_norm_affine(input, weight, normalized_shape, eps=1e-6, memory_efficient=False):
    """Mixed dtype RMSNorm (fused_layer_norm.py:117)."""
    return fused_rms_norm_affine(input, weight, normalized_shape, eps, memory_efficient)


# ================================================================= modules
import flax.linen as nn


class FusedLayerNorm(nn.Module):
    """Module parity with ``apex.normalization.FusedLayerNorm``
    (fused_layer_norm.py:230).  Param dtype is fp32 (the "mixed" behavior
    is the TPU default — inputs may be bf16)."""

    normalized_shape: Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, shape, jnp.float32)
            return fused_layer_norm_affine(x, weight, bias, shape, self.eps, self.memory_efficient)
        return fused_layer_norm(x, shape, self.eps, self.memory_efficient)


class FusedRMSNorm(nn.Module):
    """Module parity with ``apex.normalization.FusedRMSNorm``
    (fused_layer_norm.py:329)."""

    normalized_shape: Sequence[int]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x):
        shape = _canon_shape(self.normalized_shape)
        if self.elementwise_affine:
            weight = self.param("weight", nn.initializers.ones, shape, jnp.float32)
            return fused_rms_norm_affine(x, weight, shape, self.eps, self.memory_efficient)
        return fused_rms_norm(x, shape, self.eps, self.memory_efficient)


# Mixed variants are the same computation on TPU (params already fp32).
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
