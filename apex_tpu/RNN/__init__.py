"""RNN (deprecated in the reference: ``apex/RNN`` — fp16-able
RNN/LSTM/GRU reimplementations from the pre-amp era).

On TPU use ``flax.linen`` recurrent cells under ``nn.scan``; thin
factories with the reference's names are provided for discovery.
"""

import warnings

import flax.linen as nn


def _deprecated(name):
    warnings.warn(
        f"apex_tpu.RNN.{name} mirrors the deprecated apex.RNN API; prefer "
        "flax.linen recurrent cells directly",
        DeprecationWarning,
        stacklevel=3,
    )


def LSTM(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("LSTM")
    return nn.RNN(nn.LSTMCell(features=hidden_size))


def GRU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("GRU")
    return nn.RNN(nn.GRUCell(features=hidden_size))


def ReLU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("ReLU")
    return nn.RNN(nn.SimpleCell(features=hidden_size, activation_fn=nn.relu))


def Tanh(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("Tanh")
    return nn.RNN(nn.SimpleCell(features=hidden_size))


def mLSTM(input_size, hidden_size, num_layers=1, **kw):
    raise NotImplementedError(
        "mLSTM (multiplicative LSTM) was deprecated in the reference; "
        "no TPU port is provided"
    )
