"""RNN (deprecated in the reference: ``apex/RNN`` — fp16-able
RNN/LSTM/GRU/mLSTM reimplementations from the pre-amp era).

Real scan-based implementations live in :mod:`apex_tpu.RNN.backend`;
the factories here mirror ``apex/RNN/models.py:21-49`` signatures.
They emit the same deprecation warning the reference does.
"""

import warnings

from apex_tpu.RNN.backend import RNNBackend


def _deprecated(name):
    warnings.warn(
        f"apex_tpu.RNN.{name} mirrors the deprecated apex.RNN API "
        "(apex removed it in 2023); kept for parity",
        DeprecationWarning,
        stacklevel=3,
    )


def _make(kind, input_size, hidden_size, num_layers=1, bias=True,
          batch_first=False, dropout=0, bidirectional=False, output_size=None):
    if batch_first:
        raise NotImplementedError("seq-first (T, B, F) only, like the reference")
    return RNNBackend(kind, input_size, hidden_size, num_layers=num_layers,
                      bias=bias, bidirectional=bidirectional, dropout=dropout,
                      output_size=output_size)


def LSTM(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("LSTM")
    return _make("lstm", input_size, hidden_size, num_layers, **kw)


def GRU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("GRU")
    return _make("gru", input_size, hidden_size, num_layers, **kw)


def ReLU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("ReLU")
    return _make("relu", input_size, hidden_size, num_layers, **kw)


def Tanh(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("Tanh")
    return _make("tanh", input_size, hidden_size, num_layers, **kw)


def mLSTM(input_size, hidden_size, num_layers=1, **kw):
    _deprecated("mLSTM")
    return _make("mlstm", input_size, hidden_size, num_layers, **kw)
