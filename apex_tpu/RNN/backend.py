"""Functional RNN backend (LSTM/GRU/ReLU/Tanh/mLSTM, stacked + bidirectional).

Reference: ``apex/RNN/RNNBackend.py`` (``stackedRNN`` :90,
``bidirectionalRNN`` :25, ``RNNCell`` :232) and ``apex/RNN/cells.py``
(``mLSTMRNNCell``/``mLSTMCell``) — fp16-able pure-PyTorch RNNs from the
pre-amp era, kept for API parity.

TPU form: pure functions.  The time loop is one ``lax.scan`` per layer
(static shapes, fused pointwise gate math — the role of the reference's
``rnnFusedPointwise`` kernels falls out of XLA fusion), layers stack in
a Python loop, and the bidirectional variant runs the reverse stack on
``x[::-1]``.  Gate orders and formulas match ``torch.nn`` exactly so
parity tests can load identical weights.

Layout is seq-first ``(T, B, F)`` like the reference.
"""

import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

sigmoid = jax.nn.sigmoid


# ------------------------------------------------------------------ cells
def lstm_cell(p, x, hidden):
    """torch.nn.LSTMCell: gates i,f,g,o."""
    h, c = hidden
    gates = x @ p["w_ih"].T + h @ p["w_hh"].T
    if "b_ih" in p:
        gates = gates + p["b_ih"] + p["b_hh"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    cy = sigmoid(f) * c + sigmoid(i) * jnp.tanh(g)
    hy = sigmoid(o) * jnp.tanh(cy)
    return (hy, cy)


def gru_cell(p, x, hidden):
    """torch.nn.GRUCell: gates r,z,n with the r-gated hidden branch."""
    (h,) = hidden
    gi = x @ p["w_ih"].T
    gh = h @ p["w_hh"].T
    if "b_ih" in p:
        gi = gi + p["b_ih"]
        gh = gh + p["b_hh"]
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = sigmoid(i_r + h_r)
    z = sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return ((1.0 - z) * n + z * h,)


def _simple_cell(act):
    def cell(p, x, hidden):
        (h,) = hidden
        g = x @ p["w_ih"].T + h @ p["w_hh"].T
        if "b_ih" in p:
            g = g + p["b_ih"] + p["b_hh"]
        return (act(g),)

    return cell


relu_cell = _simple_cell(jax.nn.relu)
tanh_cell = _simple_cell(jnp.tanh)


def mlstm_cell(p, x, hidden):
    """Multiplicative LSTM (reference cells.py ``mLSTMCell``):
    m = (x·Wmihᵀ) ∘ (h·Wmhhᵀ); LSTM gates over (x, m)."""
    h, c = hidden
    m = (x @ p["w_mih"].T) * (h @ p["w_mhh"].T)
    gates = x @ p["w_ih"].T + m @ p["w_hh"].T
    if "b_ih" in p:
        gates = gates + p["b_ih"] + p["b_hh"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    cy = sigmoid(f) * c + sigmoid(i) * jnp.tanh(g)
    hy = sigmoid(o) * jnp.tanh(cy)
    return (hy, cy)


_CELLS = {
    "lstm": (lstm_cell, 4, 2),
    "gru": (gru_cell, 3, 1),
    "relu": (relu_cell, 1, 1),
    "tanh": (tanh_cell, 1, 1),
    "mlstm": (mlstm_cell, 4, 2),
}


# ------------------------------------------------------------- the backend
class RNNBackend:
    """Stacked (optionally bidirectional) RNN over one of the cells.

    Functional flax-style usage::

        rnn = LSTM(input_size, hidden_size, num_layers, bidirectional=True)
        params = rnn.init(jax.random.PRNGKey(0))
        out, hiddens = rnn.apply(params, x)        # x: (T, B, input_size)

    ``out`` is ``(T, B, D·out_size)`` (D = 2 if bidirectional); ``hiddens``
    is a tuple of per-state arrays ``(num_layers, B, D·hidden)`` — h (and c
    for LSTM kinds), matching the reference's collect order.
    ``collect_hidden=True`` returns every timestep's states
    ``(T, num_layers, B, D·hidden)`` (reference ``collect_hidden``).
    """

    def __init__(self, kind: str, input_size: int, hidden_size: int,
                 num_layers: int = 1, bias: bool = True,
                 bidirectional: bool = False, dropout: float = 0.0,
                 output_size: Optional[int] = None):
        if dropout:
            raise NotImplementedError(
                "inter-layer dropout needs an rng; pass dropout=0 and apply "
                "dropout outside (the reference defaults to 0 as well)"
            )
        self.kind = kind
        self.cell, self.gate_mult, self.n_states = _CELLS[kind]
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bias = bias
        self.bidirectional = bidirectional
        self.output_size = output_size if output_size is not None else hidden_size

    # -------------------------------------------------------------- params
    def _init_layer(self, key, in_size) -> Dict[str, Any]:
        H, G = self.hidden_size, self.gate_mult
        k = 1.0 / math.sqrt(H)
        keys = jax.random.split(key, 6)
        u = lambda kk, *s: jax.random.uniform(kk, s, jnp.float32, -k, k)
        p = {"w_ih": u(keys[0], G * H, in_size), "w_hh": u(keys[1], G * H, self.output_size)}
        if self.bias:
            p["b_ih"] = u(keys[2], G * H)
            p["b_hh"] = u(keys[3], G * H)
        if self.kind == "mlstm":
            p["w_mih"] = u(keys[4], self.output_size, in_size)
            p["w_mhh"] = u(keys[5], self.output_size, self.output_size)
        if self.output_size != self.hidden_size:
            p["w_ho"] = u(keys[4 if self.kind != "mlstm" else 5], self.output_size, H)
        return p

    def init(self, key) -> List:
        """Layer list (doubled pairwise for bidirectional: [fwd, bwd] per
        stack, reference bidirectionalRNN builds two stackedRNNs)."""
        dirs = 2 if self.bidirectional else 1
        keys = jax.random.split(key, self.num_layers * dirs)
        params = []
        for d in range(dirs):
            stack = []
            for layer in range(self.num_layers):
                in_size = self.input_size if layer == 0 else self.output_size * dirs
                stack.append(self._init_layer(keys[d * self.num_layers + layer], in_size))
            params.append(stack)
        return params if self.bidirectional else params[0]

    # ------------------------------------------------------------- forward
    def _run_stack(self, stack, x, reverse, collect_hidden):
        T, B = x.shape[0], x.shape[1]
        H = self.hidden_size
        outs = x[::-1] if reverse else x
        all_states = []
        for p in stack:
            h0 = tuple(jnp.zeros((B, self.output_size if i == 0 else H), x.dtype)
                       for i in range(self.n_states))

            def step(hidden, xt, p=p):
                new = self.cell(p, xt, hidden)
                if "w_ho" in p:
                    new = (new[0] @ p["w_ho"].T,) + new[1:]
                return new, (new if collect_hidden else new[0])

            hidden, scanned = jax.lax.scan(step, h0, outs)
            outs = scanned[0] if collect_hidden else scanned
            all_states.append(scanned if collect_hidden else hidden)
        if reverse:
            outs = outs[::-1]
        return outs, all_states

    def apply(self, params, x, collect_hidden: bool = False):
        if not self.bidirectional:
            outs, states = self._run_stack(params, x, False, collect_hidden)
            return outs, self._stack_states(states, collect_hidden)
        f_out, f_states = self._run_stack(params[0], x, False, collect_hidden)
        b_out, b_states = self._run_stack(params[1], x, True, collect_hidden)
        out = jnp.concatenate([f_out, b_out], axis=-1)
        fs = self._stack_states(f_states, collect_hidden)
        bs = self._stack_states(b_states, collect_hidden)
        return out, tuple(jnp.concatenate([a, b], axis=-1) for a, b in zip(fs, bs))

    __call__ = apply

    def _stack_states(self, states, collect_hidden):
        # states: per-layer tuples → tuple over state kinds, stacked on layers
        if collect_hidden:
            # each element: tuple of (T, B, H) per state
            return tuple(
                jnp.stack([layer[i] for layer in states], axis=1)
                for i in range(self.n_states)
            )
        return tuple(
            jnp.stack([layer[i] for layer in states], axis=0)
            for i in range(self.n_states)
        )
