"""Autocast helpers.

Reference: ``apex/_autocast_utils.py:22`` (``_cast_if_autocast_enabled``
— custom autograd Functions respect ``torch.cuda.amp.autocast`` by
casting their inputs to the autocast dtype).

JAX has no ambient autocast state; the functional analog is an explicit
policy-scoped cast applied at a function boundary.
"""

from typing import Callable

import jax
import jax.numpy as jnp

_SUPPORTED = (jnp.float16, jnp.bfloat16, jnp.float32)

# Global cast kill-switch, toggled by apex_tpu.amp.handle.disable_casts
# (reference: apex/amp/handle.py disable_casts — temporarily suspends the
# O1 patched-function casting).  Checked at *trace* time, so use it
# around a traced region, not inside jit.
_casts_disabled = False


def _cast_if_autocast_enabled(*args, dtype=jnp.bfloat16):
    """Cast floating args to ``dtype`` (parity helper)."""
    if dtype not in _SUPPORTED:
        raise RuntimeError(f"Unsupported autocast dtype: {dtype}")
    return tuple(
        a.astype(dtype)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a
        for a in args
    )


def autocast(fn: Callable, dtype=jnp.bfloat16, output_dtype=None) -> Callable:
    """Wrap ``fn`` so floating inputs are cast to ``dtype`` and floating
    outputs to ``output_dtype`` (the O1 cast-at-op-boundaries pattern,
    reference apex/amp/wrap.py cached_cast, made explicit)."""

    def wrapped(*args, **kwargs):
        if _casts_disabled:
            return fn(*args, **kwargs)
        args = _cast_if_autocast_enabled(*args, dtype=dtype)
        out = fn(*args, **kwargs)
        if output_dtype is not None:
            out = jax.tree.map(
                lambda x: x.astype(output_dtype)
                if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                else x,
                out,
            )
        return out

    return wrapped
