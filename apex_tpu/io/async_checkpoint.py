"""Async (non-blocking) checkpointing.

Beyond the reference (apex saves synchronously via ``torch.save``): on
TPU pods the step cadence matters more than on one GPU box, and a
synchronous multi-GB save stalls every chip in the mesh.  The standard
TPU recipe (orbax's AsyncCheckpointer) is: snapshot device arrays to
host memory *synchronously* (cheap — bounded by HBM→host bandwidth),
then write to disk on a background thread while training continues.

This module implements that recipe over the native single-blob format
(:mod:`apex_tpu.io.checkpoint`), dependency-free:

    ckpt = AsyncCheckpointer()
    for step in range(...):
        params, state = train_step(params, state)
        if step % 1000 == 0:
            ckpt.save(f"/ckpt/step_{step}.apex", {"params": params})
    ckpt.wait_until_finished()           # or: with AsyncCheckpointer()

Guarantees:
- ``save`` returns after the host snapshot (a real copy): the trees
  handed over can keep training — or be donated — immediately; the
  bytes written are the values at call time.
- writes happen in submission order on one worker thread; the queue is
  bounded (2 pending snapshots), so a save cadence faster than the
  disk backpressures instead of growing host RAM without bound.
- write failures are collected and re-raised (all of them) from the
  next ``save``/``wait_until_finished``; a failed write unlinks its
  partial temp file and the checkpointer stays usable.
- atomic + durable publish: data is written to ``<path>.tmp``,
  fsync'd, renamed onto ``<path>``, and the directory entry fsync'd —
  a crash mid-save never leaves a truncated file under the final name.
"""

import queue
import threading
from pathlib import Path
from typing import Any, List

import jax
import numpy as np

from apex_tpu.io.checkpoint import (
    _atomic_write,
    _distributed_payload,
    _shard_name,
    _write_index,
)

__all__ = ["AsyncCheckpointer"]

_STOP = object()


class AsyncCheckpointer:
    """Background checkpoint writer: host snapshot now, disk later."""

    def __init__(self, max_pending: int = 2):
        self._q: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._errors: List[BaseException] = []
        self._lock = threading.Lock()
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------- api
    def save(self, path, tree: Any) -> None:
        """Snapshot ``tree`` to host (copied) and queue the disk write.

        Blocks only when ``max_pending`` snapshots are already waiting
        for the disk (backpressure instead of unbounded host RAM)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._reraise()
        # device → host with a guaranteed copy: device_get may return a
        # zero-copy view (numpy leaves, CPU backend) that the caller
        # could mutate or donate while the write is still queued
        host_tree = jax.tree.map(
            lambda x: np.array(jax.device_get(x), copy=True), tree
        )
        self._q.put(lambda: _atomic_write(str(path), host_tree))

    def save_distributed(self, dir_path, tree: Any) -> None:
        """Non-blocking multi-host save: snapshot THIS process's
        addressable shards now (real copies), write its per-process
        shard file on the background thread
        (:func:`apex_tpu.io.save_distributed_checkpoint` semantics —
        call from every process; the pod-scale version of ``save``).

        Callers coordinating a restore barrier across hosts should
        ``wait_until_finished()`` before signalling (e.g. via
        ``multihost_utils.sync_global_devices``) that the checkpoint is
        complete."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._reraise()
        payload, pid, nprocs = _distributed_payload(tree, copy=True)
        d = Path(dir_path)

        def write():
            d.mkdir(parents=True, exist_ok=True)
            if pid == 0:
                _write_index(d, nprocs)
            _atomic_write(str(d / _shard_name(pid, nprocs)), payload)

        self._q.put(write)

    def wait_until_finished(self) -> None:
        """Block until every queued save is on disk (then re-raise any
        write failures)."""
        self._q.join()
        self._reraise()

    def close(self) -> None:
        """Drain the queue, stop and join the worker thread."""
        if self._closed:
            return
        self._q.join()
        self._closed = True
        self._q.put(_STOP)
        self._worker.join()
        self._reraise()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------------- worker
    def _reraise(self):
        with self._lock:
            if self._errors:
                errs, self._errors = self._errors, []
                msg = "; ".join(f"{type(e).__name__}: {e}" for e in errs)
                raise RuntimeError(f"async checkpoint write(s) failed: {msg}") from errs[0]

    def _run(self):
        while True:
            item = self._q.get()
            if item is _STOP:
                self._q.task_done()
                return
            try:
                item()
            except BaseException as e:  # noqa: BLE001 — collected, re-raised on the caller's thread
                with self._lock:
                    self._errors.append(e)
            finally:
                self._q.task_done()
