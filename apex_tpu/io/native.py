"""ctypes loader for the native runtime library.

Builds ``native/apex_tpu_native.cpp`` with g++ on first use (cached in
``native/build/``) and exposes flatten/unflatten/gather_rows.  Falls
back to NumPy loops when no compiler is available — all callers must
work either way (the reference's lazy-and-tolerant extension import
pattern, ``apex/multi_tensor_apply/multi_tensor_apply.py:8-14``).
"""

import contextlib
import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

import numpy as np


@contextlib.contextmanager
def atomic_output(path):
    """THE atomic write/rename helper for checkpoint bytes: yields a
    binary file open on ``<path>.tmp``; on clean exit the data is
    fsync'd, renamed onto ``path``, and the directory entry fsync'd —
    a crash or power loss mid-write can never leave a truncated file
    under the final name, and the published bytes are durable.  On any
    exception the temp file is unlinked and nothing is published.

    Every checkpoint-path write in the tree must route through here (or
    a wrapper of it): analyzer rule APX104 flags direct
    ``open(..., "wb")`` calls on checkpoint paths, because a direct
    write IS the torn-file class ``io.validate_checkpoint`` exists to
    detect after the fact."""
    tmp = str(path) + ".tmp"
    f = open(tmp, "wb")
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())  # data durable before the rename publishes it
        f.close()
        os.replace(tmp, str(path))
        dfd = os.open(os.path.dirname(str(path)) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)  # the rename itself durable
        finally:
            os.close(dfd)
    except BaseException:
        try:
            f.close()
        except OSError:
            pass
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass
        raise

_REPO = Path(__file__).resolve().parents[2]
_SRC = _REPO / "native" / "apex_tpu_native.cpp"
_SO = _REPO / "native" / "build" / "libapex_tpu_native.so"

_lock = threading.Lock()
_lib = None
_tried = False

DEFAULT_THREADS = min(8, os.cpu_count() or 1)


def _build() -> Optional[ctypes.CDLL]:
    _SO.parent.mkdir(parents=True, exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(_SO),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        return None
    return ctypes.CDLL(str(_SO))


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if _SO.exists():
            try:
                _lib = ctypes.CDLL(str(_SO))
            except OSError:
                _lib = _build()
        else:
            _lib = _build()
        if _lib is not None:
            _lib.apex_tpu_native_abi_version.restype = ctypes.c_int
            if _lib.apex_tpu_native_abi_version() != 1:
                _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


def flatten(arrays: List[np.ndarray], threads: int = DEFAULT_THREADS) -> np.ndarray:
    """Concatenate arbitrary-dtype arrays into one byte buffer
    (apex_C.flatten, csrc/flatten_unflatten.cpp:16)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    sizes = np.array([a.nbytes for a in arrays], np.int64)
    total = int(sizes.sum())
    out = np.empty(total, np.uint8)
    lib = get_lib()
    if lib is None:
        off = 0
        for a, s in zip(arrays, sizes):
            out[off : off + s] = a.view(np.uint8).reshape(-1)
            off += int(s)
        return out
    srcs = (ctypes.c_void_p * len(arrays))(
        *[a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
    )
    lib.apex_tpu_flatten(
        srcs,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(arrays)),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(threads),
    )
    return out


def unflatten(buf: np.ndarray, shapes, dtypes, threads: int = DEFAULT_THREADS) -> List[np.ndarray]:
    """Split a flat byte buffer back into arrays (apex_C.unflatten)."""
    outs = [np.empty(s, d) for s, d in zip(shapes, dtypes)]
    sizes = np.array([o.nbytes for o in outs], np.int64)
    lib = get_lib()
    if lib is None:
        off = 0
        for o, s in zip(outs, sizes):
            o.view(np.uint8).reshape(-1)[:] = buf[off : off + s]
            off += int(s)
        return outs
    buf = np.ascontiguousarray(buf)
    dsts = (ctypes.c_void_p * len(outs))(
        *[o.ctypes.data_as(ctypes.c_void_p) for o in outs]
    )
    lib.apex_tpu_unflatten(
        buf.ctypes.data_as(ctypes.c_void_p),
        dsts,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(len(outs)),
        ctypes.c_int(threads),
    )
    return outs


def gather_rows(src: np.ndarray, indices: np.ndarray, threads: int = DEFAULT_THREADS) -> np.ndarray:
    """dst[i] = src[indices[i]] — batch assembly for input pipelines."""
    src = np.ascontiguousarray(src)
    indices = np.ascontiguousarray(indices.astype(np.int64))
    n = len(indices)
    out = np.empty((n,) + src.shape[1:], src.dtype)
    lib = get_lib()
    if lib is None:
        np.take(src, indices, axis=0, out=out)
        return out
    row_bytes = src[0].nbytes if src.shape[0] else 0
    lib.apex_tpu_gather_rows(
        src.ctypes.data_as(ctypes.c_void_p),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        ctypes.c_int64(row_bytes),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(threads),
    )
    return out
