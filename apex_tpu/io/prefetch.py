"""Input prefetch: overlap host batch assembly with device compute.

The role DALI/torch DataLoader workers play for the reference's imagenet
example (``examples/imagenet/main_amp.py`` uses torch DataLoader +
prefetcher).  Here: a background thread assembles batches (optionally
with the native ``gather_rows``) and keeps a bounded queue ahead of the
training loop; ``jax.device_put`` on the consumer side overlaps H2D with
the previous step's compute (XLA dispatch is async).
"""

import queue
import threading
from typing import Callable, Iterator, Optional


class PrefetchIterator:
    """Wrap any iterator with a depth-``size`` background prefetch queue."""

    _SENTINEL = object()

    def __init__(self, it: Iterator, size: int = 2, transform: Optional[Callable] = None):
        self._q: "queue.Queue" = queue.Queue(maxsize=size)
        self._transform = transform
        self._err = None

        def worker():
            try:
                for item in it:
                    self._q.put(self._transform(item) if self._transform else item)
            except BaseException as e:  # surface errors on the consumer side
                self._err = e
            finally:
                self._q.put(self._SENTINEL)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._SENTINEL:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
