"""Fast single-blob checkpointing for param/state pytrees.

Reference: apex's checkpoint story (``apex/amp/frontend.py:365-404``
scaler state, ``fp16_utils/fp16_optimizer.py`` optimizer state,
``DistributedFusedAdam`` sharded state dicts :2527) plus the recommended
save/load recipe in the reference README.

TPU-native addition: the pytree's leaves are gathered into ONE
contiguous blob with the native multithreaded flatten
(:mod:`apex_tpu.io.native`) — one write() syscall, no per-leaf pickle
overhead — with a JSON header carrying structure/shapes/dtypes.  Orbax
remains the right answer for multi-host async checkpointing; this is
the dependency-free fast path the reference's users had with
``torch.save``.
"""

import json
import struct
from pathlib import Path
from typing import Any

import jax
import numpy as np

from apex_tpu.io import native

_MAGIC = b"APEXTPU1"


def save_checkpoint(path, tree: Any) -> None:
    """Serialize a pytree of arrays (+ scalars/None) to ``path``."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = []
    meta = []
    for leaf in leaves:
        a = np.asarray(leaf)
        arrays.append(np.ascontiguousarray(a))
        meta.append({"shape": list(a.shape), "dtype": a.dtype.str})
    blob = native.flatten(arrays) if arrays else np.empty(0, np.uint8)
    header = json.dumps(
        {"treedef": str(treedef), "leaves": meta}
    ).encode()
    # structure is rebuilt from an example tree on load; the treedef
    # string is stored for sanity checking only
    import pickle

    treedef_bytes = pickle.dumps(treedef)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<QQ", len(header), len(treedef_bytes)))
        f.write(header)
        f.write(treedef_bytes)
        f.write(blob.tobytes())


def load_checkpoint(path) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves)."""
    import pickle

    with open(path, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise ValueError(f"{path} is not an apex_tpu checkpoint")
        hlen, tlen = struct.unpack("<QQ", f.read(16))
        header = json.loads(f.read(hlen))
        treedef = pickle.loads(f.read(tlen))
        blob = np.frombuffer(f.read(), np.uint8)
    shapes = [tuple(m["shape"]) for m in header["leaves"]]
    dtypes = [np.dtype(m["dtype"]) for m in header["leaves"]]
    leaves = native.unflatten(blob, shapes, dtypes)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ------------------------------------------------------- sharded checkpoints
def _shard_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}-of-{world:05d}.ckpt"


def save_sharded_checkpoint(dir_path, tree: Any, rank: int, world_size: int) -> str:
    """Save this rank's piece of a distributed checkpoint (the per-rank
    protocol of reference ``DistributedFusedAdam.state_dict``, :2527).

    ``tree`` is whatever this rank owns — e.g. the dict from
    :meth:`DistributedFusedAdam.sharded_state_dict`, a tp-local param
    shard, or any pytree.  One file per rank, plus an index file written
    by rank 0 recording the world size.  Reassembly/resharding semantics
    belong to the consumer (``load_sharded_state_dicts`` for ZeRO).
    """
    d = Path(dir_path)
    d.mkdir(parents=True, exist_ok=True)
    if rank == 0:
        (d / "index.json").write_text(
            json.dumps({"format": "apex_tpu_sharded_v1", "world_size": world_size})
        )
    path = d / _shard_name(rank, world_size)
    save_checkpoint(path, tree)
    return str(path)


def load_sharded_checkpoint(dir_path, rank=None) -> Any:
    """Load one rank's shard (``rank=``) or the full list of shard trees
    (``rank=None``) from a directory written by
    :func:`save_sharded_checkpoint`.  Validates completeness against the
    index."""
    d = Path(dir_path)
    index = json.loads((d / "index.json").read_text())
    if index.get("format") != "apex_tpu_sharded_v1":
        raise ValueError(f"{dir_path} is not a sharded apex_tpu checkpoint")
    world = index["world_size"]
    if rank is not None:
        return load_checkpoint(d / _shard_name(rank, world))
    trees = []
    for r in range(world):
        p = d / _shard_name(r, world)
        if not p.exists():
            raise FileNotFoundError(f"missing shard {r} of {world}: {p}")
        trees.append(load_checkpoint(p))
    return trees
