"""Fast single-blob checkpointing for param/state pytrees.

Reference: apex's checkpoint story (``apex/amp/frontend.py:365-404``
scaler state, ``fp16_utils/fp16_optimizer.py`` optimizer state,
``DistributedFusedAdam`` sharded state dicts :2527) plus the recommended
save/load recipe in the reference README.

TPU-native addition: the pytree's leaves are gathered into ONE
contiguous blob with the native multithreaded flatten
(:mod:`apex_tpu.io.native`) — one write() syscall, no per-leaf pickle
overhead — with a JSON header carrying structure/shapes/dtypes.  Orbax
remains the right answer for multi-host async checkpointing; this is
the dependency-free fast path the reference's users had with
``torch.save``.
"""

import json
import os
import struct
import time
import zlib
from pathlib import Path
from typing import Any, NamedTuple, Optional

import jax
import numpy as np

from apex_tpu.io import native
from apex_tpu.observability import metrics as _metrics

_MAGIC = b"APEXTPU1"

#: Bounded retry budget for one checkpoint read/write against transient
#: filesystem errors (NFS hiccups, GCS fuse EIO, the chaos harness's
#: injected ``ChaosIOError``).  Deterministic failures repeat
#: identically, so the budget is small; delays are jittered so a pod's
#: worth of ranks retrying the same dying fileserver don't re-land in
#: lockstep.
_IO_RETRIES = 3
_IO_BACKOFF_BASE = 0.05
_IO_BACKOFF_CAP = 2.0

#: OSError subclasses that are DETERMINISTIC, not transient: a missing
#: file, a permission wall, or a path that is a directory repeats
#: identically — retrying only adds sleeps and three spurious
#: "transient" warnings in front of the real error.
_IO_NO_RETRY = (FileNotFoundError, PermissionError, IsADirectoryError,
                NotADirectoryError)


def _chaos_io(site: str) -> None:
    """Chaos seam: the fault-injection hook for slow/failing checkpoint
    I/O (:func:`apex_tpu.resilience.chaos.check_io`).  Sits INSIDE the
    retried operation so each retry re-consults the armed plan — an
    injected-failure budget burns down across attempts exactly like a
    recovering filesystem."""
    from apex_tpu.resilience.chaos import check_io

    check_io(site)


def _with_io_retries(fn, op: str, path, retries=None):
    """Run one checkpoint I/O operation with bounded, jittered,
    structured-logged retry-with-backoff on transient ``OSError``s
    (NFS hiccups, EIO).  Never retried: deterministic OSErrors
    (``_IO_NO_RETRY`` — a typo'd path repeats identically) and
    ``ValueError`` (short reads / bad headers — corrupt bytes don't
    heal).  The final attempt's error propagates unwrapped."""
    import logging
    import random

    from apex_tpu.utils.logging import get_logger, log_structured

    n = _IO_RETRIES if retries is None else int(retries)
    for attempt in range(n + 1):
        try:
            return fn()
        except OSError as e:
            if isinstance(e, _IO_NO_RETRY) or attempt >= n:
                raise
            delay = min(_IO_BACKOFF_CAP, _IO_BACKOFF_BASE * (2 ** attempt))
            delay *= random.uniform(0.5, 1.5)
            log_structured(
                get_logger("apex_tpu.io"), logging.WARNING,
                "checkpoint.io_retry", op=op, path=str(path),
                attempt=attempt + 1, retries=n, delay_s=round(delay, 4),
                error=f"{type(e).__name__}: {e}")
            _metrics.inc("apex_checkpoint_io_retries_total",
                         help="transient checkpoint I/O errors retried",
                         op=op)
            time.sleep(delay)


def _dtype_str(dt) -> str:
    """Serializable dtype tag.  ``dtype.str`` of ml_dtypes extended
    types (bfloat16, float8_*) is an anonymous ``'<V2'`` that loads back
    as raw void — use the registered NAME for those instead."""
    dt = np.dtype(dt)
    if dt.kind == "V" and dt.names is None:
        return dt.name
    return dt.str


def _resolve_dtype(s) -> np.dtype:
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def save_checkpoint(path, tree: Any) -> None:
    """Serialize a pytree of arrays (+ scalars/None) to ``path``.

    The publish is ATOMIC and durable (:func:`apex_tpu.io.native
    .atomic_output`: tmp + fsync + rename + dir-fsync) and retried with
    backoff on transient FS errors — a crash mid-save never leaves a
    truncated file under the final name."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = []
    meta = []
    for leaf in leaves:
        a = np.asarray(leaf)
        arrays.append(np.ascontiguousarray(a))
        meta.append({"shape": list(a.shape), "dtype": _dtype_str(a.dtype)})
    blob = native.flatten(arrays) if arrays else np.empty(0, np.uint8)
    # blob checksum: the torn-write class is caught by the size check,
    # but a bit-flipped blob (dying disk, cosmic ray, a fault injector)
    # is SIZE-preserving — the crc is what the corruption probe and the
    # load-time verify key on.  Old checkpoints without the key still
    # load (and probe shallowly).
    header = json.dumps(
        {"treedef": str(treedef), "leaves": meta,
         "crc32": zlib.crc32(memoryview(blob))}
    ).encode()
    # structure is rebuilt from an example tree on load; the treedef
    # string is stored for sanity checking only
    import pickle

    treedef_bytes = pickle.dumps(treedef)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)

    def write():
        _chaos_io("ckpt.write")
        with native.atomic_output(p) as f:
            f.write(_MAGIC)
            f.write(struct.pack("<QQ", len(header), len(treedef_bytes)))
            f.write(header)
            f.write(treedef_bytes)
            f.write(blob.tobytes())

    _with_io_retries(write, "write", p)


def _read_header(f, path):
    """Parse the checkpoint preamble from an open file: returns
    ``(header, treedef)`` and leaves ``f`` positioned at the first blob
    byte — the ONE definition of the byte layout both the eager and
    lazy readers depend on."""
    import pickle

    magic = f.read(8)
    if magic != _MAGIC:
        raise ValueError(f"{path} is not an apex_tpu checkpoint")
    hlen, tlen = struct.unpack("<QQ", f.read(16))
    header = json.loads(f.read(hlen))
    treedef = pickle.loads(f.read(tlen))
    return header, treedef


def load_checkpoint(path) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves).
    Transient FS errors are retried with backoff (the chaos harness's
    slow/failing-I/O seam rides the same path)."""
    def read():
        _chaos_io("ckpt.read")
        with open(path, "rb") as f:
            header, treedef = _read_header(f, path)
            blob = np.frombuffer(f.read(), np.uint8)
        return header, treedef, blob

    header, treedef, blob = _with_io_retries(read, "read", path)
    shapes = [tuple(m["shape"]) for m in header["leaves"]]
    dtypes = [_resolve_dtype(m["dtype"]) for m in header["leaves"]]
    need = sum(int(np.prod(s, dtype=np.int64)) * d.itemsize
               for s, d in zip(shapes, dtypes))
    if blob.size != need:
        # the torn-blob check validate_checkpoint does by stat, applied
        # at load: the native unflatten is an unchecked memcpy and must
        # never read past (or zero-fill) a truncated buffer silently
        raise ValueError(
            f"{path} is torn: header promises a {need}-byte blob, file "
            f"holds {blob.size} (interrupted write?)")
    crc = header.get("crc32")
    if crc is not None and zlib.crc32(memoryview(blob)) != int(crc):
        # size-preserving corruption (bit-flips): the class the
        # supervisor's quarantine path exists for — restoring garbage
        # state silently would be strictly worse than failing here
        raise ValueError(
            f"{path} is corrupt: blob crc32 does not match the header "
            "(size-preserving corruption — bit flips, not a torn write)")
    leaves = native.unflatten(blob, shapes, dtypes)
    return jax.tree_util.tree_unflatten(treedef, leaves)


class _LazyLeaf:
    """Handle to one leaf's bytes inside a checkpoint file.

    The blob is a plain concatenation of the leaves' bytes
    (:func:`apex_tpu.io.native.flatten`), so each leaf lives at a fixed
    offset computable from the header alone — materializing one leaf is
    a seek + read of exactly its bytes, never the whole file."""

    __slots__ = ("path", "offset", "shape", "dtype")

    def __init__(self, path, offset, shape, dtype):
        self.path = path
        self.offset = offset
        self.shape = shape
        self.dtype = dtype

    def read_from(self, f) -> np.ndarray:
        """Read this leaf's bytes from an already-open file object."""
        n = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        f.seek(self.offset)
        buf = f.read(n)
        if len(buf) != n:
            raise ValueError(
                f"checkpoint {self.path} truncated: leaf at offset "
                f"{self.offset} wants {n} bytes, got {len(buf)}"
            )
        return np.frombuffer(buf, self.dtype).reshape(self.shape)

    def load(self) -> np.ndarray:
        def read():
            _chaos_io("ckpt.read")
            with open(self.path, "rb") as f:
                return self.read_from(f)

        return _with_io_retries(read, "read", self.path)

    def __array__(self, dtype=None, copy=None):
        a = self.load()
        return a.astype(dtype) if dtype is not None else a


def open_checkpoint_lazy(path) -> Any:
    """Like :func:`load_checkpoint`, but leaves are :class:`_LazyLeaf`
    handles — only the header and treedef are read now; each leaf's
    bytes are read on demand via ``np.asarray(leaf)``.  This is how a
    pod-scale restore avoids materializing every rank's full shard file
    on every host (see :func:`load_distributed_checkpoint`)."""
    def read():
        _chaos_io("ckpt.read")
        with open(path, "rb") as f:
            h, t = _read_header(f, path)
            return h, t, f.tell()

    header, treedef, base = _with_io_retries(read, "read", path)
    leaves = []
    off = base
    for m in header["leaves"]:
        shape = tuple(m["shape"])
        dtype = _resolve_dtype(m["dtype"])
        leaves.append(_LazyLeaf(str(path), off, shape, dtype))
        off += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return jax.tree_util.tree_unflatten(treedef, leaves)


def validate_checkpoint(path) -> dict:
    """Cheap integrity check of a single-blob checkpoint: magic, header
    JSON, treedef pickle, and — the torn-write case an interrupted
    writer or dying filesystem actually produces — that the blob holds
    EXACTLY the bytes the header promises.  Raises ``ValueError`` with
    the reason on any mismatch; returns the parsed header.  Reads only
    the preamble + ``stat`` — never the blob itself."""
    p = Path(path)
    try:
        size = p.stat().st_size
        with open(p, "rb") as f:
            header, _ = _read_header(f, path)
            base = f.tell()
        need = 0
        # inside the try: a header whose JSON parses but holds garbage
        # leaf metadata (bit-flipped dtype string, missing keys) is just
        # as torn as a short preamble and must stay skippable
        for m in header.get("leaves", ()):
            dt = _resolve_dtype(m["dtype"])
            need += int(np.prod(m["shape"], dtype=np.int64)) * dt.itemsize
    except Exception as e:  # noqa: BLE001 — short preamble, truncated
        # pickle, bad JSON, OS errors: all mean "torn/corrupt file".
        # Only our own already-formatted message (bad magic, which names
        # the path) passes through unwrapped — json.JSONDecodeError IS a
        # ValueError subclass and must not escape context-free
        if type(e) is ValueError and str(path) in str(e):
            raise
        raise ValueError(f"{path} is torn or corrupt: "
                         f"{type(e).__name__}: {e}") from e
    got = size - base
    if got != need:
        raise ValueError(
            f"{path} is torn: header promises a {need}-byte blob, file "
            f"holds {got} (interrupted write?)")
    return header


def probe_checkpoint(path) -> dict:
    """Deep integrity probe of one checkpoint file: everything
    :func:`validate_checkpoint` checks (magic, header, exact blob size)
    PLUS the blob crc when the header carries one — so size-preserving
    corruption (bit flips from a dying disk or the chaos injector) is
    caught here instead of deep inside a restore.  Reads the whole blob
    (unlike ``validate_checkpoint``); raises ``ValueError`` with the
    reason, returns the parsed header.  Checkpoints written before the
    crc existed probe shallowly (no false corruption on old files)."""
    header = validate_checkpoint(path)
    crc = header.get("crc32")
    if crc is None:
        return header

    def read():
        _chaos_io("ckpt.read")
        got = 0
        with open(path, "rb") as f:
            _read_header(f, path)
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    return got
                got = zlib.crc32(chunk, got)

    if _with_io_retries(read, "read", path) != int(crc):
        raise ValueError(
            f"{path} is corrupt: blob crc32 does not match the header "
            "(size-preserving corruption — bit flips, not a torn write)")
    return header


class CorruptCheckpoint(NamedTuple):
    """What :func:`probe_checkpoint_dir` reports: the newest restore
    candidate failed its deep probe — the supervisor quarantines it."""

    path: str      # the step dir (or single-file checkpoint) at fault
    reason: str    # the probe's ValueError text


def probe_checkpoint_dir(dir_path) -> Optional[CorruptCheckpoint]:
    """Deep-probe the checkpoint the NEXT restore would load: the
    newest COMPLETE ``step_*`` dir (every shard through
    :func:`probe_checkpoint`) or, in a single-file layout, the newest
    validating ``.ckpt``/``.apex`` file.

    Returns ``None`` when the candidate is healthy or there is nothing
    to probe (missing/empty dir), and a :class:`CorruptCheckpoint`
    naming the candidate when its bytes are corrupt beyond what the
    completeness/torn-size seams can see — or when step dirs exist but
    NONE is complete (a hard-killed first publish), the one state the
    resume side can only refuse loudly.  This is the supervisor's
    quarantine trigger: one corrupt-or-interrupted newest save must
    cost one save interval, never a crash loop."""
    d = Path(dir_path)
    if not d.is_dir():
        return None
    if any(p.is_dir() for p in d.glob("step_*")):
        try:
            step = latest_distributed_step(d)
        except AllCheckpointsTornError as e:
            # step dirs exist but NONE is complete: the resume side
            # refuses loudly by design (it cannot tell an interrupted
            # FIRST publish from destroyed progress), which under a
            # supervisor is a guaranteed crash loop.  Report the newest
            # incomplete dir for quarantine instead: the bytes survive
            # for the postmortem, and the relaunch resumes from an
            # older dir once one is exposed — or starts fresh, losing
            # only what was never durably published anyway.
            dirs = sorted((p for p in d.glob("step_*") if p.is_dir()),
                          key=checkpoint_step)
            return CorruptCheckpoint(
                str(dirs[-1]), f"incomplete publish (hard-killed "
                f"writer?): {e}")
        if step < 0:
            return None
        sd = d / f"step_{step:08d}"
        try:
            world = int(read_index(sd)["world_size"])
            for r in range(world):
                probe_checkpoint(sd / _shard_name(r, world))
        except (OSError, ValueError, KeyError, TypeError) as e:
            return CorruptCheckpoint(str(sd), f"{type(e).__name__}: {e}")
        return None
    cands = sorted(
        (p for p in d.iterdir()
         if p.is_file() and p.suffix in (".ckpt", ".apex")),
        key=checkpoint_step, reverse=True)
    for p in cands:
        try:
            validate_checkpoint(p)
        except ValueError:
            continue  # torn: latest_checkpoint already skips it
        try:
            probe_checkpoint(p)
        except (OSError, ValueError) as e:
            return CorruptCheckpoint(str(p), f"{type(e).__name__}: {e}")
        return None  # the file the next restore loads is healthy
    return None


def quarantine_checkpoint(dir_path, target, reason: str) -> str:
    """Atomically move a corrupt checkpoint (a ``step_*`` dir or a
    single ``.ckpt`` file) into a ``quarantine/`` sibling with a reason
    file, so the next restore resumes from the previous complete step
    and the bad bytes stay available for the postmortem.

    The move is one same-filesystem ``os.replace`` — no restore can
    ever observe a half-quarantined dir.  A same-named earlier
    quarantine entry is replaced (elastic restarts can re-save a step
    number).  Returns the quarantined path."""
    import logging

    from apex_tpu.utils.logging import get_logger, log_structured

    t = Path(target)
    q = Path(dir_path) / "quarantine"
    q.mkdir(parents=True, exist_ok=True)
    dest = q / t.name
    if dest.is_dir():
        import shutil

        shutil.rmtree(dest, ignore_errors=True)
    os.replace(str(t), str(dest))
    payload = json.dumps({
        "path": str(t), "quarantined_to": str(dest),
        "reason": str(reason), "time": time.time(),
    }, sort_keys=True).encode()
    with native.atomic_output(q / f"{t.name}.reason.json") as f:
        f.write(payload)
    log_structured(get_logger("apex_tpu.io"), logging.ERROR,
                   "checkpoint.quarantined", path=str(t),
                   quarantined_to=str(dest), reason=str(reason))
    _metrics.inc("apex_checkpoint_quarantines_total",
                 help="corrupt checkpoints moved aside by the supervisor")
    return str(dest)


def checkpoint_step(path) -> int:
    """Step number encoded in a ``step_<N>*`` file/dir name, or -1."""
    import re

    m = re.match(r"step_(\d+)", Path(path).name)
    return int(m.group(1)) if m else -1


class AllCheckpointsTornError(FileNotFoundError):
    """Every candidate file in the directory failed validation.

    Distinct from the plain ``FileNotFoundError`` of a missing/empty
    directory: prior progress EXISTED here, so treating this like a
    first launch would silently discard it — even an auto-resuming
    caller must fail loudly on this, never train from scratch
    pretending it resumed."""


def latest_checkpoint(dir_path, suffixes=(".ckpt", ".apex")) -> str:
    """Newest VALID single-file checkpoint under ``dir_path`` — the
    restart side of preemption safety.

    Candidates are ``*.ckpt``/``*.apex`` files (``.tmp`` leftovers of an
    interrupted atomic publish are never candidates), ordered newest
    first by the ``step_<N>`` number in the name when present, else by
    mtime.  Each is validated (:func:`validate_checkpoint`); torn or
    half-written files are SKIPPED with a structured warning so a kill
    mid-write costs one save interval, not the run.  Raises
    ``FileNotFoundError`` when the directory is missing or empty, and
    its subclass :class:`AllCheckpointsTornError` when candidates exist
    but ALL fail validation — a resume pointed at nothing must fail
    loudly, never train from scratch pretending it resumed, and a
    caller that auto-starts fresh on the former must still fail on the
    latter."""
    import logging

    from apex_tpu.utils.logging import get_logger, log_structured

    d = Path(dir_path)
    if not d.is_dir():
        raise FileNotFoundError(f"checkpoint dir {dir_path} does not exist")
    cands = [p for p in d.iterdir()
             if p.is_file() and p.suffix in suffixes]
    if not cands:
        raise FileNotFoundError(
            f"no checkpoint files ({'/'.join(suffixes)}) under {dir_path}"
            " — empty or not a checkpoint directory")
    def _mtime(p):
        try:
            return p.stat().st_mtime
        except OSError:
            # pruned by a concurrent writer between listing and sort
            # (two runs sharing a dir): sort last, validation skips it
            return 0.0

    cands.sort(key=lambda p: (checkpoint_step(p), _mtime(p)),
               reverse=True)
    skipped = []
    for p in cands:
        try:
            validate_checkpoint(p)
        except ValueError as e:
            skipped.append((str(p), str(e)))
            log_structured(
                get_logger("apex_tpu.io"), logging.WARNING,
                "checkpoint.torn_file_skipped", path=str(p), error=str(e))
            continue
        return str(p)
    raise AllCheckpointsTornError(
        f"no valid checkpoint under {dir_path}: all {len(skipped)} "
        f"candidate(s) torn/corrupt — " +
        "; ".join(f"{p}: {e}" for p, e in skipped))


def latest_distributed_step(dir_path) -> int:
    """Newest fully-published ``step_*`` directory under ``dir_path`` —
    the pod-scale sibling of :func:`latest_checkpoint`.

    A complete directory holds an ``index.json`` and EVERY one of its
    ``world_size`` named ``shard_<r>-of-<world>.ckpt`` files (per-step
    dirs mean an interrupted save can only leave an INCOMPLETE newest
    dir, never a torn mix of steps).  The check is by exact per-rank
    NAME, not file count: rank 0 publishes ``index.json`` before the
    shard data lands, so a crash in that window leaves an indexed dir
    with missing ranks — and under elastic restarts the same step
    number can be re-saved at a DIFFERENT world size into one dir,
    where stale other-world ``shard_*`` files would satisfy a mere
    count.  Incomplete dirs are skipped with a structured warning.
    Returns the step number; returns ``-1`` when no ``step_*`` dirs
    exist at all (a legitimate fresh start); raises
    :class:`AllCheckpointsTornError` when dirs EXIST but none is
    complete — prior progress would be silently discarded by starting
    fresh, so even an auto-resuming caller must fail loudly."""
    import logging

    from apex_tpu.utils.logging import get_logger, log_structured

    d = Path(dir_path)
    dirs = sorted(d.glob("step_*"), reverse=True) if d.is_dir() else []
    for sd in dirs:
        idx = sd / "index.json"
        if not idx.exists():
            continue
        try:
            # the read rides the retry/chaos seam like every shard read
            # (a transient EIO must not skip the newest COMPLETE dir);
            # int() inside the try: a parseable index.json whose
            # world_size is null/garbage is just as torn as no index
            world = int(json.loads(_read_index_text(idx))["world_size"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        missing = [r for r in range(world)
                   if not (sd / _shard_name(r, world)).exists()]
        if not missing:
            return checkpoint_step(sd)
        log_structured(
            get_logger("apex_tpu.io"), logging.WARNING,
            "checkpoint.incomplete_step_dir_skipped", path=str(sd),
            world_size=world, missing_ranks=missing[:8],
            missing=len(missing))
    if dirs:
        raise AllCheckpointsTornError(
            f"no complete checkpoint under {dir_path}: {len(dirs)} "
            f"step_* dir(s) exist but none is fully published "
            f"(interrupted save?)")
    return -1


def _atomic_write(path: str, tree: Any) -> None:
    """Alias kept for the async checkpointer and older call sites:
    :func:`save_checkpoint` itself now publishes atomically + durably
    through :func:`apex_tpu.io.native.atomic_output` (tmp + fsync +
    rename + dir-fsync) with bounded retry on transient FS errors."""
    save_checkpoint(path, tree)


# ------------------------------------------------------- sharded checkpoints
def _shard_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}-of-{world:05d}.ckpt"


def _read_index_text(path) -> str:
    """One index.json read through the same retry/chaos seam as the
    shard reads — the index is as load-bearing as any shard."""
    def read():
        _chaos_io("ckpt.read")
        return Path(path).read_text()

    return _with_io_retries(read, "read", path)


def read_index(dir_path) -> dict:
    """Parse + format-check a sharded checkpoint dir's ``index.json``
    (world size plus any ``index_extra`` metadata the saver recorded —
    the elastic controller's saved-world-layout record).  Transient FS
    errors retry like any shard read."""
    index = json.loads(_read_index_text(Path(dir_path) / "index.json"))
    if index.get("format") != "apex_tpu_sharded_v1":
        raise ValueError(f"{dir_path} is not a sharded apex_tpu checkpoint")
    return index


def _write_index(dir_path, world_size: int, extra: Optional[dict] = None) -> None:
    """Durably publish the sharded-checkpoint index through
    :func:`apex_tpu.io.native.atomic_output` (a crash or power loss
    mid-write must not leave a truncated or missing index.json while
    the shard data survives), with bounded retry.  ``extra`` merges
    additional metadata keys into the index (the elastic controller
    records the saved world layout here); ``format``/``world_size``
    stay authoritative."""
    d = Path(dir_path)
    d.mkdir(parents=True, exist_ok=True)
    payload = dict(extra or {})
    payload.update(
        {"format": "apex_tpu_sharded_v1", "world_size": world_size})

    def write():
        _chaos_io("ckpt.write")
        with native.atomic_output(d / "index.json") as f:
            f.write(json.dumps(payload).encode())

    _with_io_retries(write, "write", d / "index.json")


def save_sharded_checkpoint(dir_path, tree: Any, rank: int, world_size: int,
                            index_extra: Optional[dict] = None) -> str:
    """Save this rank's piece of a distributed checkpoint (the per-rank
    protocol of reference ``DistributedFusedAdam.state_dict``, :2527).

    ``tree`` is whatever this rank owns — e.g. the dict from
    :meth:`DistributedFusedAdam.sharded_state_dict`, a tp-local param
    shard, or any pytree.  One file per rank, plus an index file written
    by rank 0 recording the world size (``index_extra`` merges
    additional metadata into it — see :mod:`apex_tpu.resilience
    .elastic`).  Reassembly/resharding semantics belong to the consumer
    (``load_sharded_state_dicts`` for ZeRO).
    """
    d = Path(dir_path)
    d.mkdir(parents=True, exist_ok=True)
    if rank == 0:
        _write_index(d, world_size, extra=index_extra)
    path = d / _shard_name(rank, world_size)
    _atomic_write(str(path), tree)
    return str(path)


def save_distributed_checkpoint(dir_path, tree: Any) -> str:
    """Multi-host checkpoint: each process writes ONLY the array shards
    it can address (reference: the per-rank protocol of
    ``DistributedFusedAdam.state_dict(gather_on_root=False)``,
    distributed_fused_adam.py:2527 — generalized to any pytree of
    ``jax.Array``s under any sharding).

    Works for global arrays that no single process can materialize
    (e.g. a tp-sharded param replicated over dp spans every host).
    Shards with ``replica_id != 0`` are skipped, so each distinct piece
    of data is written exactly once across the fleet.  Call from EVERY
    process; reassemble with :func:`load_distributed_checkpoint`.
    For a non-blocking save use
    :meth:`apex_tpu.io.AsyncCheckpointer.save_distributed`.
    """
    payload, pid, nprocs = _distributed_payload(tree)
    return save_sharded_checkpoint(dir_path, payload, pid, nprocs)


def _distributed_payload(tree: Any, copy: bool = False):
    """(payload, process_index, process_count): this process's
    addressable, replica-deduped shards of ``tree`` as host arrays.
    ``copy=True`` forces real copies (the async checkpointer's snapshot
    guarantee — on the CPU backend ``np.asarray`` of a shard can be a
    zero-copy view the caller could donate mid-write)."""
    to_host = (lambda x: np.array(x, copy=True)) if copy else np.asarray
    pid, nprocs = jax.process_index(), jax.process_count()
    payload = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        shards = []
        for s in getattr(leaf, "addressable_shards", ()):
            if s.replica_id != 0:
                continue
            starts = [sl.start if sl.start is not None else 0 for sl in s.index]
            stops = [
                sl.stop if sl.stop is not None else dim
                for sl, dim in zip(s.index, leaf.shape)
            ]
            shards.append({
                "start": np.asarray(starts, np.int64),
                "stop": np.asarray(stops, np.int64),
                "data": to_host(s.data),
            })
        if not hasattr(leaf, "addressable_shards"):
            # plain numpy / python scalar: process 0 owns it
            if pid == 0:
                a = np.asarray(leaf)
                shards.append({
                    "start": np.zeros(a.ndim, np.int64),
                    "stop": np.asarray(a.shape, np.int64),
                    "data": np.array(a, copy=True) if copy else a,
                })
        payload[key] = shards
    return payload, pid, nprocs


def _materialize_lazy(items) -> None:
    """Replace :class:`_LazyLeaf` values with their bytes, in place:
    ``items`` yields ``(container, key)`` pairs.  One file open per
    shard file, reads in offset order — per-leaf opens would cost
    O(leaves × world) round trips on network filesystems."""
    by_file = {}
    for c, k in items:
        if isinstance(c[k], _LazyLeaf):
            by_file.setdefault(c[k].path, []).append((c, k))
    for path, group in by_file.items():
        with open(path, "rb") as f:
            for c, k in sorted(group, key=lambda it: it[0][it[1]].offset):
                c[k] = c[k].read_from(f)


def _assemble_slice(pieces, leaf_shape, leaf_dtype, idx, key):
    """Fill the region ``idx`` (tuple of slices into a ``leaf_shape``
    array) from saved shard ``pieces``; raise unless every element of
    the region is covered — partial coverage must not come back as
    silent zeros."""
    bounds = [
        (sl.start or 0, sl.stop if sl.stop is not None else dim)
        for sl, dim in zip(idx, leaf_shape)
    ]
    out_shape = tuple(b - a for a, b in bounds)
    hits = []
    for s in pieces:
        lo = [max(int(a), ra) for a, (ra, _) in zip(s["start"], bounds)]
        hi = [min(int(b), rb) for b, (_, rb) in zip(s["stop"], bounds)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue  # no overlap with the requested region
        hits.append((s, lo, hi))
    # materialize lazy pieces: only overlapping ones, at most once each
    # (cached in place, so a piece spanning several device regions is
    # read exactly once)
    _materialize_lazy((s, "data") for s, _, _ in hits)
    arr = np.zeros(out_shape, leaf_dtype)
    covered = 0
    for s, lo, hi in hits:
        dst = tuple(
            slice(l - ra, h - ra) for l, h, (ra, _) in zip(lo, hi, bounds)
        )
        data = np.asarray(s["data"]).reshape(
            tuple(int(b) - int(a) for a, b in zip(s["start"], s["stop"]))
        )
        src = tuple(
            slice(l - int(a), h - int(a))
            for l, h, a in zip(lo, hi, s["start"])
        )
        arr[dst] = data[src]
        covered += int(np.prod([h - l for l, h in zip(lo, hi)]))
    want = int(np.prod(out_shape))
    if covered != want:
        raise ValueError(
            f"checkpoint shards cover {covered}/{want} elements of leaf "
            f"{key} region {bounds} — shape mismatch between the saved "
            "state and the template (sharding partitions are disjoint, so "
            "coverage must be exact)"
        )
    return arr


def load_distributed_checkpoint(dir_path, template: Any, mesh=None,
                                spec_tree: Any = None) -> Any:
    """Reassemble a :func:`save_distributed_checkpoint` directory.

    ``template``: abstract or concrete pytree supplying
    structure/shape/dtype.  With ``mesh`` + ``spec_tree``, returns
    GLOBAL ``jax.Array``s directly: each process opens every shard file
    LAZILY (header only) and reads from disk exactly the pieces that
    overlap the slices its own devices need (via
    ``jax.make_array_from_callback``) — a state too big for any one
    host restores without any host ever holding more than its own
    addressable bytes.  Without them, returns host numpy arrays (every
    process materializes the full tree — fine for states that fit one
    host).  Raises if the shards don't exactly cover a requested region
    (a save/template shape mismatch)."""
    from jax.sharding import NamedSharding

    if (mesh is None) != (spec_tree is None):
        raise ValueError("pass mesh and spec_tree together")
    lazy = mesh is not None
    payloads = load_sharded_checkpoint(dir_path, lazy=lazy)
    if lazy:
        # start/stop bounds are needed up front for overlap tests and
        # are tiny (ndim int64 each); only "data" stays on disk
        _materialize_lazy(
            (s, k)
            for p in payloads
            for pieces in p.values()
            for s in pieces
            for k in ("start", "stop")
        )
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    spec_leaves = treedef.flatten_up_to(spec_tree) if spec_tree is not None else None
    out = []
    for i, (path, leaf) in enumerate(flat):
        key = jax.tree_util.keystr(path)
        pieces = [s for p in payloads for s in p.get(key, ())]
        if not pieces:
            raise KeyError(f"checkpoint has no shards for leaf {key}")
        if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
            # Python scalar template leaf (save-side _distributed_payload
            # stored it via np.asarray, so mirror that here)
            leaf = np.asarray(leaf)
        shape, dtype = tuple(leaf.shape), leaf.dtype
        if spec_leaves is None:
            full = tuple(slice(0, d) for d in shape)
            out.append(_assemble_slice(pieces, shape, dtype, full, key))
        else:
            sh = NamedSharding(mesh, spec_leaves[i])
            out.append(jax.make_array_from_callback(
                shape, sh,
                lambda idx, pieces=pieces, shape=shape, dtype=dtype, key=key:
                    _assemble_slice(pieces, shape, dtype, idx, key),
            ))
    return jax.tree_util.tree_unflatten(treedef, out)


def make_global_array_tree(tree: Any, mesh, spec_tree: Any) -> Any:
    """Turn a pytree of host (numpy) arrays into GLOBAL ``jax.Array``s
    sharded over ``mesh`` per ``spec_tree`` — each process contributes
    only its addressable pieces (``jax.make_array_from_callback``).
    This is the multi-host analog of ``device_put``: the standard way to
    feed params/optimizer state into a ``jit(shard_map(...))`` train
    step on a pod."""
    from jax.sharding import NamedSharding

    def one(x, spec):
        x = np.asarray(x)
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(x.shape, sh, lambda idx, x=x: x[idx])

    return jax.tree.map(one, tree, spec_tree)


def load_sharded_checkpoint(dir_path, rank=None, lazy: bool = False) -> Any:
    """Load one rank's shard (``rank=``) or the full list of shard trees
    (``rank=None``) from a directory written by
    :func:`save_sharded_checkpoint`.  Validates completeness against the
    index.  ``lazy=True`` returns trees of :class:`_LazyLeaf` handles
    (headers read now, bytes on demand) so callers that need only a
    fraction of each shard never pull whole files into RAM."""
    d = Path(dir_path)
    index = read_index(d)
    world = index["world_size"]
    reader = open_checkpoint_lazy if lazy else load_checkpoint
    if rank is not None:
        return reader(d / _shard_name(rank, world))
    trees = []
    for r in range(world):
        p = d / _shard_name(r, world)
        if not p.exists():
            raise FileNotFoundError(f"missing shard {r} of {world}: {p}")
        trees.append(reader(p))
    return trees
