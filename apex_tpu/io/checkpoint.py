"""Fast single-blob checkpointing for param/state pytrees.

Reference: apex's checkpoint story (``apex/amp/frontend.py:365-404``
scaler state, ``fp16_utils/fp16_optimizer.py`` optimizer state,
``DistributedFusedAdam`` sharded state dicts :2527) plus the recommended
save/load recipe in the reference README.

TPU-native addition: the pytree's leaves are gathered into ONE
contiguous blob with the native multithreaded flatten
(:mod:`apex_tpu.io.native`) — one write() syscall, no per-leaf pickle
overhead — with a JSON header carrying structure/shapes/dtypes.  Orbax
remains the right answer for multi-host async checkpointing; this is
the dependency-free fast path the reference's users had with
``torch.save``.
"""

import json
import struct
from pathlib import Path
from typing import Any

import jax
import numpy as np

from apex_tpu.io import native

_MAGIC = b"APEXTPU1"


def save_checkpoint(path, tree: Any) -> None:
    """Serialize a pytree of arrays (+ scalars/None) to ``path``."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = []
    meta = []
    for leaf in leaves:
        a = np.asarray(leaf)
        arrays.append(np.ascontiguousarray(a))
        meta.append({"shape": list(a.shape), "dtype": a.dtype.str})
    blob = native.flatten(arrays) if arrays else np.empty(0, np.uint8)
    header = json.dumps(
        {"treedef": str(treedef), "leaves": meta}
    ).encode()
    # structure is rebuilt from an example tree on load; the treedef
    # string is stored for sanity checking only
    import pickle

    treedef_bytes = pickle.dumps(treedef)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<QQ", len(header), len(treedef_bytes)))
        f.write(header)
        f.write(treedef_bytes)
        f.write(blob.tobytes())


def load_checkpoint(path) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves)."""
    import pickle

    with open(path, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise ValueError(f"{path} is not an apex_tpu checkpoint")
        hlen, tlen = struct.unpack("<QQ", f.read(16))
        header = json.loads(f.read(hlen))
        treedef = pickle.loads(f.read(tlen))
        blob = np.frombuffer(f.read(), np.uint8)
    shapes = [tuple(m["shape"]) for m in header["leaves"]]
    dtypes = [np.dtype(m["dtype"]) for m in header["leaves"]]
    leaves = native.unflatten(blob, shapes, dtypes)
    return jax.tree_util.tree_unflatten(treedef, leaves)
