"""Fast single-blob checkpointing for param/state pytrees.

Reference: apex's checkpoint story (``apex/amp/frontend.py:365-404``
scaler state, ``fp16_utils/fp16_optimizer.py`` optimizer state,
``DistributedFusedAdam`` sharded state dicts :2527) plus the recommended
save/load recipe in the reference README.

TPU-native addition: the pytree's leaves are gathered into ONE
contiguous blob with the native multithreaded flatten
(:mod:`apex_tpu.io.native`) — one write() syscall, no per-leaf pickle
overhead — with a JSON header carrying structure/shapes/dtypes.  Orbax
remains the right answer for multi-host async checkpointing; this is
the dependency-free fast path the reference's users had with
``torch.save``.
"""

import json
import os
import struct
from pathlib import Path
from typing import Any

import jax
import numpy as np

from apex_tpu.io import native

_MAGIC = b"APEXTPU1"


def _dtype_str(dt) -> str:
    """Serializable dtype tag.  ``dtype.str`` of ml_dtypes extended
    types (bfloat16, float8_*) is an anonymous ``'<V2'`` that loads back
    as raw void — use the registered NAME for those instead."""
    dt = np.dtype(dt)
    if dt.kind == "V" and dt.names is None:
        return dt.name
    return dt.str


def _resolve_dtype(s) -> np.dtype:
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def save_checkpoint(path, tree: Any) -> None:
    """Serialize a pytree of arrays (+ scalars/None) to ``path``."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = []
    meta = []
    for leaf in leaves:
        a = np.asarray(leaf)
        arrays.append(np.ascontiguousarray(a))
        meta.append({"shape": list(a.shape), "dtype": _dtype_str(a.dtype)})
    blob = native.flatten(arrays) if arrays else np.empty(0, np.uint8)
    header = json.dumps(
        {"treedef": str(treedef), "leaves": meta}
    ).encode()
    # structure is rebuilt from an example tree on load; the treedef
    # string is stored for sanity checking only
    import pickle

    treedef_bytes = pickle.dumps(treedef)
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    with open(p, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<QQ", len(header), len(treedef_bytes)))
        f.write(header)
        f.write(treedef_bytes)
        f.write(blob.tobytes())


def load_checkpoint(path) -> Any:
    """Load a pytree saved by :func:`save_checkpoint` (numpy leaves)."""
    import pickle

    with open(path, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise ValueError(f"{path} is not an apex_tpu checkpoint")
        hlen, tlen = struct.unpack("<QQ", f.read(16))
        header = json.loads(f.read(hlen))
        treedef = pickle.loads(f.read(tlen))
        blob = np.frombuffer(f.read(), np.uint8)
    shapes = [tuple(m["shape"]) for m in header["leaves"]]
    dtypes = [_resolve_dtype(m["dtype"]) for m in header["leaves"]]
    leaves = native.unflatten(blob, shapes, dtypes)
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ------------------------------------------------------- sharded checkpoints
def _shard_name(rank: int, world: int) -> str:
    return f"shard_{rank:05d}-of-{world:05d}.ckpt"


def _write_index(dir_path, world_size: int) -> None:
    """Atomically publish the sharded-checkpoint index (tmp + rename —
    a crash mid-write must not leave a truncated index.json under the
    final name)."""
    d = Path(dir_path)
    d.mkdir(parents=True, exist_ok=True)
    tmp = d / "index.json.tmp"
    tmp.write_text(
        json.dumps({"format": "apex_tpu_sharded_v1", "world_size": world_size})
    )
    os.replace(tmp, d / "index.json")


def save_sharded_checkpoint(dir_path, tree: Any, rank: int, world_size: int) -> str:
    """Save this rank's piece of a distributed checkpoint (the per-rank
    protocol of reference ``DistributedFusedAdam.state_dict``, :2527).

    ``tree`` is whatever this rank owns — e.g. the dict from
    :meth:`DistributedFusedAdam.sharded_state_dict`, a tp-local param
    shard, or any pytree.  One file per rank, plus an index file written
    by rank 0 recording the world size.  Reassembly/resharding semantics
    belong to the consumer (``load_sharded_state_dicts`` for ZeRO).
    """
    d = Path(dir_path)
    d.mkdir(parents=True, exist_ok=True)
    if rank == 0:
        _write_index(d, world_size)
    path = d / _shard_name(rank, world_size)
    save_checkpoint(path, tree)
    return str(path)


def save_distributed_checkpoint(dir_path, tree: Any) -> str:
    """Multi-host checkpoint: each process writes ONLY the array shards
    it can address (reference: the per-rank protocol of
    ``DistributedFusedAdam.state_dict(gather_on_root=False)``,
    distributed_fused_adam.py:2527 — generalized to any pytree of
    ``jax.Array``s under any sharding).

    Works for global arrays that no single process can materialize
    (e.g. a tp-sharded param replicated over dp spans every host).
    Shards with ``replica_id != 0`` are skipped, so each distinct piece
    of data is written exactly once across the fleet.  Call from EVERY
    process; reassemble with :func:`load_distributed_checkpoint`.
    For a non-blocking save use
    :meth:`apex_tpu.io.AsyncCheckpointer.save_distributed`.
    """
    payload, pid, nprocs = _distributed_payload(tree)
    return save_sharded_checkpoint(dir_path, payload, pid, nprocs)


def _distributed_payload(tree: Any, copy: bool = False):
    """(payload, process_index, process_count): this process's
    addressable, replica-deduped shards of ``tree`` as host arrays.
    ``copy=True`` forces real copies (the async checkpointer's snapshot
    guarantee — on the CPU backend ``np.asarray`` of a shard can be a
    zero-copy view the caller could donate mid-write)."""
    to_host = (lambda x: np.array(x, copy=True)) if copy else np.asarray
    pid, nprocs = jax.process_index(), jax.process_count()
    payload = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        shards = []
        for s in getattr(leaf, "addressable_shards", ()):
            if s.replica_id != 0:
                continue
            starts = [sl.start if sl.start is not None else 0 for sl in s.index]
            stops = [
                sl.stop if sl.stop is not None else dim
                for sl, dim in zip(s.index, leaf.shape)
            ]
            shards.append({
                "start": np.asarray(starts, np.int64),
                "stop": np.asarray(stops, np.int64),
                "data": to_host(s.data),
            })
        if not hasattr(leaf, "addressable_shards"):
            # plain numpy / python scalar: process 0 owns it
            if pid == 0:
                a = np.asarray(leaf)
                shards.append({
                    "start": np.zeros(a.ndim, np.int64),
                    "stop": np.asarray(a.shape, np.int64),
                    "data": np.array(a, copy=True) if copy else a,
                })
        payload[key] = shards
    return payload, pid, nprocs


def _assemble_slice(pieces, leaf_shape, leaf_dtype, idx, key):
    """Fill the region ``idx`` (tuple of slices into a ``leaf_shape``
    array) from saved shard ``pieces``; raise unless every element of
    the region is covered — partial coverage must not come back as
    silent zeros."""
    bounds = [
        (sl.start or 0, sl.stop if sl.stop is not None else dim)
        for sl, dim in zip(idx, leaf_shape)
    ]
    out_shape = tuple(b - a for a, b in bounds)
    arr = np.zeros(out_shape, leaf_dtype)
    covered = 0
    for s in pieces:
        lo = [max(int(a), ra) for a, (ra, _) in zip(s["start"], bounds)]
        hi = [min(int(b), rb) for b, (_, rb) in zip(s["stop"], bounds)]
        if any(l >= h for l, h in zip(lo, hi)):
            continue  # no overlap with the requested region
        dst = tuple(
            slice(l - ra, h - ra) for l, h, (ra, _) in zip(lo, hi, bounds)
        )
        data = s["data"].reshape(
            tuple(int(b) - int(a) for a, b in zip(s["start"], s["stop"]))
        )
        src = tuple(
            slice(l - int(a), h - int(a))
            for l, h, a in zip(lo, hi, s["start"])
        )
        arr[dst] = data[src]
        covered += int(np.prod([h - l for l, h in zip(lo, hi)]))
    want = int(np.prod(out_shape))
    if covered != want:
        raise ValueError(
            f"checkpoint shards cover {covered}/{want} elements of leaf "
            f"{key} region {bounds} — shape mismatch between the saved "
            "state and the template (sharding partitions are disjoint, so "
            "coverage must be exact)"
        )
    return arr


def load_distributed_checkpoint(dir_path, template: Any, mesh=None,
                                spec_tree: Any = None) -> Any:
    """Reassemble a :func:`save_distributed_checkpoint` directory.

    ``template``: abstract or concrete pytree supplying
    structure/shape/dtype.  With ``mesh`` + ``spec_tree``, returns
    GLOBAL ``jax.Array``s directly: each process assembles only the
    slices its own devices need (via ``jax.make_array_from_callback``),
    so no full-size array is materialized on any host beyond what the
    shard files themselves hold.  Without them, returns host numpy
    arrays (every process materializes the full tree — fine for states
    that fit one host).  Raises if the shards don't exactly cover a
    requested region (a save/template shape mismatch)."""
    from jax.sharding import NamedSharding

    payloads = load_sharded_checkpoint(dir_path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    if (mesh is None) != (spec_tree is None):
        raise ValueError("pass mesh and spec_tree together")
    spec_leaves = treedef.flatten_up_to(spec_tree) if spec_tree is not None else None
    out = []
    for i, (path, leaf) in enumerate(flat):
        key = jax.tree_util.keystr(path)
        pieces = [s for p in payloads for s in p.get(key, ())]
        if not pieces:
            raise KeyError(f"checkpoint has no shards for leaf {key}")
        shape, dtype = tuple(leaf.shape), leaf.dtype
        if spec_leaves is None:
            full = tuple(slice(0, d) for d in shape)
            out.append(_assemble_slice(pieces, shape, dtype, full, key))
        else:
            sh = NamedSharding(mesh, spec_leaves[i])
            out.append(jax.make_array_from_callback(
                shape, sh,
                lambda idx, pieces=pieces, shape=shape, dtype=dtype, key=key:
                    _assemble_slice(pieces, shape, dtype, idx, key),
            ))
    return jax.tree_util.tree_unflatten(treedef, out)


def make_global_array_tree(tree: Any, mesh, spec_tree: Any) -> Any:
    """Turn a pytree of host (numpy) arrays into GLOBAL ``jax.Array``s
    sharded over ``mesh`` per ``spec_tree`` — each process contributes
    only its addressable pieces (``jax.make_array_from_callback``).
    This is the multi-host analog of ``device_put``: the standard way to
    feed params/optimizer state into a ``jit(shard_map(...))`` train
    step on a pod."""
    from jax.sharding import NamedSharding

    def one(x, spec):
        x = np.asarray(x)
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(x.shape, sh, lambda idx, x=x: x[idx])

    return jax.tree.map(one, tree, spec_tree)


def load_sharded_checkpoint(dir_path, rank=None) -> Any:
    """Load one rank's shard (``rank=``) or the full list of shard trees
    (``rank=None``) from a directory written by
    :func:`save_sharded_checkpoint`.  Validates completeness against the
    index."""
    d = Path(dir_path)
    index = json.loads((d / "index.json").read_text())
    if index.get("format") != "apex_tpu_sharded_v1":
        raise ValueError(f"{dir_path} is not a sharded apex_tpu checkpoint")
    world = index["world_size"]
    if rank is not None:
        return load_checkpoint(d / _shard_name(rank, world))
    trees = []
    for r in range(world):
        p = d / _shard_name(r, world)
        if not p.exists():
            raise FileNotFoundError(f"missing shard {r} of {world}: {p}")
        trees.append(load_checkpoint(p))
    return trees
