"""Host-side I/O runtime: native flatten/unflatten, fast checkpointing,
input prefetch."""

from apex_tpu.io import native
from apex_tpu.io.checkpoint import (
    AllCheckpointsTornError,
    CorruptCheckpoint,
    checkpoint_step,
    latest_checkpoint,
    latest_distributed_step,
    load_checkpoint,
    load_distributed_checkpoint,
    load_sharded_checkpoint,
    make_global_array_tree,
    probe_checkpoint,
    probe_checkpoint_dir,
    quarantine_checkpoint,
    read_index,
    save_checkpoint,
    save_distributed_checkpoint,
    save_sharded_checkpoint,
    validate_checkpoint,
)
from apex_tpu.io.async_checkpoint import AsyncCheckpointer
from apex_tpu.io.prefetch import PrefetchIterator

__all__ = [
    "AllCheckpointsTornError",
    "AsyncCheckpointer",
    "CorruptCheckpoint",
    "native",
    "save_checkpoint",
    "load_checkpoint",
    "save_sharded_checkpoint",
    "load_sharded_checkpoint",
    "save_distributed_checkpoint",
    "load_distributed_checkpoint",
    "make_global_array_tree",
    "latest_checkpoint",
    "latest_distributed_step",
    "probe_checkpoint",
    "probe_checkpoint_dir",
    "quarantine_checkpoint",
    "read_index",
    "validate_checkpoint",
    "checkpoint_step",
    "PrefetchIterator",
]
