"""Host-side I/O runtime: native flatten/unflatten, fast checkpointing,
input prefetch."""

from apex_tpu.io import native
from apex_tpu.io.checkpoint import (
    load_checkpoint,
    load_distributed_checkpoint,
    load_sharded_checkpoint,
    make_global_array_tree,
    save_checkpoint,
    save_distributed_checkpoint,
    save_sharded_checkpoint,
)
from apex_tpu.io.async_checkpoint import AsyncCheckpointer
from apex_tpu.io.prefetch import PrefetchIterator

__all__ = [
    "AsyncCheckpointer",
    "native",
    "save_checkpoint",
    "load_checkpoint",
    "save_sharded_checkpoint",
    "load_sharded_checkpoint",
    "save_distributed_checkpoint",
    "load_distributed_checkpoint",
    "make_global_array_tree",
    "PrefetchIterator",
]
