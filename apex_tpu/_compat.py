"""JAX version compatibility aliases.

The chip image runs a newer jax than some dev/CI environments (0.4.x).
Rather than pinning, alias the small set of renamed APIs this codebase
uses onto their old names so both environments import and run:

- ``jax.shard_map`` — promoted from ``jax.experimental.shard_map`` with
  the ``check_rep`` kwarg renamed to ``check_vma``.
- ``jax.lax.axis_size`` — new accessor; ``psum(1, axis)`` of a static
  unit is the long-standing equivalent (constant-folded, no collective).
- ``pltpu.CompilerParams`` — renamed from ``TPUCompilerParams``
  (importing any submodule runs this first, so the Pallas modules can
  use the new name unconditionally).
- ``Lowered.as_text(debug_info=True)`` — old jax exposes location
  metadata (named_scope names) only through the MLIR printer's debug
  flag; the wrapper routes the kwarg there.

Each alias installs only when the new name is missing, so on current
jax this module is a no-op.  Imported for its side effects by
``apex_tpu/__init__.py`` before any submodule can hit the new names.
"""

def _install() -> None:
    try:
        import jax
        import jax.lax
    except Exception:  # noqa: BLE001 — no/broken jax: nothing to alias.
        # The one consumer that must still work here is the jax-free
        # static analyzer (`python -m apex_tpu.analysis`), whose import
        # of the parent package runs this module.
        return
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f=None, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            if f is None:  # decorator form: jax.shard_map(mesh=...)(f)
                return lambda g: shard_map(g, **kwargs)
            return _shard_map(f, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    try:
        from jax.experimental.pallas import tpu as pltpu

        if not hasattr(pltpu, "CompilerParams"):
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pallas optional at import time
        pass

    import inspect

    try:
        from jax._src import stages
    except Exception:  # noqa: BLE001 — private path; never break import
        return

    if "debug_info" not in inspect.signature(
            stages.Lowered.as_text).parameters:
        _orig_as_text = stages.Lowered.as_text

        def as_text(self, dialect=None, *, debug_info=False):
            if debug_info:
                # old jax prints location metadata (named_scope names
                # etc.) only through the MLIR printer's debug flag
                import io

                ir = self.compiler_ir(dialect) if dialect \
                    else self.compiler_ir()
                buf = io.StringIO()
                ir.operation.print(file=buf, enable_debug_info=True)
                return buf.getvalue()
            return _orig_as_text(self, dialect)

        stages.Lowered.as_text = as_text


_install()
