"""FusedAdagrad.

Reference: ``apex/optimizers/fused_adagrad.py`` and
``csrc/multi_tensor_adagrad.cu`` (AdagradFunctor:24-84).

Elementwise (fp32):
- L2 mode (default, ADAGRAD_MODE_0): ``g += wd·p``; ``h += g²``;
  ``p -= lr·g/(√h + eps)``.
- adagrad_w mode (ADAGRAD_MODE_1): ``h += g²``;
  ``p -= lr·(g/(√h+eps) + wd·p)``.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum: Any  # h accumulator, fp32
    master: Optional[Any] = None


class FusedAdagrad(base.OptimizerBase):
    def __init__(
        self,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
    ):
        super().__init__(lr, weight_decay, master_weights)
        self.eps = eps
        self.adagrad_w_mode = adagrad_w_mode
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params) -> AdagradState:
        return AdagradState(
            step=jnp.int32(0),
            sum=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            master=base.make_master(params, self.master_weights),
        )

    def update(self, grads, state: AdagradState, params, grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        wd, eps = self.weight_decay, self.eps

        step = base.predicate_step(grads_finite, state.step)
        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers)

        def one(g, p, h, hyp):
            wd_i = hyp.get("weight_decay", wd)
            lr_i = base.leaf_lr(hyp, lr)
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adagrad_w_mode:
                g = g + wd_i * p32
                h_new = h + g * g
                p_out = p32 - lr_i * (g / (jnp.sqrt(h_new) + eps))
            else:
                h_new = h + g * g
                p_out = p32 - lr_i * (g / (jnp.sqrt(h_new) + eps) + wd_i * p32)
            return p_out, h_new

        out = jax.tree.map(one, grads, p_math, state.sum, hypers)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        h_new = jax.tree.unflatten(treedef, [x[1] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        h_new = base.select(grads_finite, h_new, state.sum)
        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, AdagradState(step, h_new, new_master)
