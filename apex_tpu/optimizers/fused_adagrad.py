"""FusedAdagrad.

Reference: ``apex/optimizers/fused_adagrad.py`` and
``csrc/multi_tensor_adagrad.cu`` (AdagradFunctor:24-84).

Elementwise (fp32):
- L2 mode (default, ADAGRAD_MODE_0): ``g += wd·p``; ``h += g²``;
  ``p -= lr·g/(√h + eps)``.
- adagrad_w mode (ADAGRAD_MODE_1): ``h += g²``;
  ``p -= lr·(g/(√h+eps) + wd·p)``.

Runs on the bucketed multi-tensor engine by default (see
:mod:`apex_tpu.optimizers.base`).
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base, bucketing


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum: Any  # h accumulator, fp32 (tree or Buckets)
    master: Optional[Any] = None


class FusedAdagrad(base.OptimizerBase):

    _BUCKET_SLOT = "sum"

    def __init__(
        self,
        lr: float = 1e-2,
        eps: float = 1e-10,
        weight_decay: float = 0.0,
        adagrad_w_mode: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
        use_buckets: bool = True,
    ):
        super().__init__(lr, weight_decay, master_weights,
                         use_buckets=use_buckets)
        self.eps = eps
        self.adagrad_w_mode = adagrad_w_mode
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params, bucketed: bool = False) -> AdagradState:
        if bucketed:
            (h,), master = self._init_bucket_slots(params, 1)
            return AdagradState(jnp.int32(0), h, master)
        return AdagradState(
            step=jnp.int32(0),
            sum=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            master=base.make_master(params, self.master_weights),
        )

    def _adagrad_math(self, g, p32, h, wd_i, lr_i):
        """The shared Adagrad expression tree (per-leaf == bucket)."""
        eps = self.eps
        if not self.adagrad_w_mode:
            g = g + wd_i * p32
            h_new = h + g * g
            p_out = p32 - lr_i * (g / (jnp.sqrt(h_new) + eps))
        else:
            h_new = h + g * g
            p_out = p32 - lr_i * (g / (jnp.sqrt(h_new) + eps) + wd_i * p32)
        return p_out, h_new

    # ------------------------------------------------------- per-leaf path
    def _leaf_update(self, grads, state: AdagradState, params,
                     grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay

        step = base.predicate_step(grads_finite, state.step)
        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers)

        def one(g, p, h, hyp):
            return self._adagrad_math(
                g.astype(jnp.float32), p.astype(jnp.float32), h,
                hyp.get("weight_decay", wd), base.leaf_lr(hyp, lr))

        out = jax.tree.map(one, grads, p_math, state.sum, hypers)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        h_new = jax.tree.unflatten(treedef, [x[1] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        h_new = base.select(grads_finite, h_new, state.sum)
        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, AdagradState(step, h_new, new_master)

    # --------------------------------------------------------- bucket path
    def _bucket_update(self, prep: base.PreparedGrads, state: AdagradState,
                       params, pred, lr=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = prep.plan

        step = base.predicate_step(pred, state.step)
        h_b, resident = self._slot_buckets(plan, state.sum)
        has_master = state.master is not None
        if has_master:
            p_b, _ = self._slot_buckets(plan, state.master)
        else:
            p_b = bucketing.pack(plan, params)
        hl = self._hyper_leaves(
            base.leaf_hypers(params, self.param_group_fn, self.group_hypers))
        wd_leaf = [h.get("weight_decay", wd) for h in hl]

        new_p, new_h = [], []
        for bi, b in enumerate(plan.buckets):
            p_out, h_out = self._adagrad_math(
                prep.g[bi], p_b[bi], h_b[bi],
                bucketing.seg_values(b, wd_leaf),
                self._bucket_lr(b, hl, lr))
            new_p.append(p_out)
            new_h.append(h_out)

        new_p = base.bucket_select(pred, new_p, p_b)
        new_h = base.bucket_select(pred, new_h, h_b)
        new_params = bucketing.unpack(plan, new_p)
        new_master = (self._emit_slot(plan, new_p, resident)
                      if has_master else None)
        return new_params, AdagradState(
            step, self._emit_slot(plan, new_h, resident), new_master)
