"""FusedSGD — SGD with momentum/nesterov/dampening.

Reference: ``apex/optimizers/fused_sgd.py:6-225`` and
``csrc/multi_tensor_sgd_kernel.cu`` (SGDFunctor:31-150).

Per-element semantics (fp32 math):
- optional grad scale (``1/most_recent_scale``) folded into the load;
- ``wd_after_momentum=False`` (default): ``g += wd·p`` before momentum;
- momentum: first step initializes the buffer to ``g`` (``first_run``),
  otherwise ``buf = μ·buf + (1-dampening)·g``;
- nesterov: ``g += μ·buf`` else ``g = buf``;
- ``wd_after_momentum=True``: ``g += wd·p`` here;
- ``p -= lr·g``.

The first-run distinction is handled branch-free with the step counter
(step==0 ⇒ buf := g), keeping the whole step jit-compatible.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buffer: Any
    master: Optional[Any] = None


class FusedSGD(base.OptimizerBase):

    #: group-override keys beyond the base lr/lr_scale/weight_decay set
    _HYPER_KEYS = ("momentum",)

    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
    ):
        """``param_group_fn``/``group_hypers``: functional param_groups
        (see :class:`~apex_tpu.optimizers.FusedAdam`); per-group keys
        here: ``lr``/``lr_scale``, ``weight_decay``, ``momentum``."""
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        super().__init__(lr, weight_decay, master_weights)
        self.momentum = momentum
        self.dampening = dampening
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params) -> SGDState:
        return SGDState(
            step=jnp.int32(0),
            momentum_buffer=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
            master=base.make_master(params, self.master_weights),
        )

    def update(self, grads, state: SGDState, params, grads_finite=None, lr=None, scale=1.0):
        lr = self.lr if lr is None else lr
        wd, mu, damp = self.weight_decay, self.momentum, self.dampening
        first_run = state.step == 0

        step = base.predicate_step(grads_finite, state.step)
        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers,
                                  extra_keys=self._HYPER_KEYS)

        def one(g, p, buf, h):
            wd_i = h.get("weight_decay", wd)
            lr_i = base.leaf_lr(h, lr)
            mu_i = h.get("momentum", mu)
            g = g.astype(jnp.float32) * (1.0 / scale)
            p32 = p.astype(jnp.float32)
            if not self.wd_after_momentum and wd_i != 0.0:
                g = g + wd_i * p32
            if mu_i != 0.0:
                steady = mu_i * buf + (1.0 - damp) * g
                buf_new = jnp.where(first_run, g, steady)
                if self.nesterov:
                    g = g + mu_i * buf_new
                else:
                    g = buf_new
            else:
                buf_new = buf
            if self.wd_after_momentum and wd_i != 0.0:
                g = g + wd_i * p32
            return p32 - lr_i * g, buf_new

        out = jax.tree.map(one, grads, p_math, state.momentum_buffer, hypers)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        buf_new = jax.tree.unflatten(treedef, [x[1] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        buf_new = base.select(grads_finite, buf_new, state.momentum_buffer)
        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, SGDState(step, buf_new, new_master)
