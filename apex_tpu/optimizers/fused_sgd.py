"""FusedSGD — SGD with momentum/nesterov/dampening.

Reference: ``apex/optimizers/fused_sgd.py:6-225`` and
``csrc/multi_tensor_sgd_kernel.cu`` (SGDFunctor:31-150).

Per-element semantics (fp32 math):
- optional grad scale (``1/most_recent_scale``) folded into the load;
- ``wd_after_momentum=False`` (default): ``g += wd·p`` before momentum;
- momentum: first step initializes the buffer to ``g`` (``first_run``),
  otherwise ``buf = μ·buf + (1-dampening)·g``;
- nesterov: ``g += μ·buf`` else ``g = buf``;
- ``wd_after_momentum=True``: ``g += wd·p`` here;
- ``p -= lr·g``.

The first-run distinction is handled branch-free with the step counter
(step==0 ⇒ buf := g), keeping the whole step jit-compatible.  Runs on
the bucketed multi-tensor engine by default (see
:mod:`apex_tpu.optimizers.base`); per-group ``momentum`` overrides
become a per-element select on the bucket, reproducing the per-leaf
"momentum-free group" semantics exactly.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base, bucketing


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buffer: Any
    master: Optional[Any] = None


class FusedSGD(base.OptimizerBase):

    #: group-override keys beyond the base lr/lr_scale/weight_decay set
    _HYPER_KEYS = ("momentum",)

    _BUCKET_SLOT = "momentum_buffer"

    def __init__(
        self,
        lr: float,
        momentum: float = 0.0,
        dampening: float = 0.0,
        weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_after_momentum: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
        use_buckets: bool = True,
    ):
        """``param_group_fn``/``group_hypers``: functional param_groups
        (see :class:`~apex_tpu.optimizers.FusedAdam`); per-group keys
        here: ``lr``/``lr_scale``, ``weight_decay``, ``momentum``."""
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError("Nesterov momentum requires a momentum and zero dampening")
        super().__init__(lr, weight_decay, master_weights,
                         use_buckets=use_buckets)
        self.momentum = momentum
        self.dampening = dampening
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params, bucketed: bool = False) -> SGDState:
        if bucketed:
            (buf,), master = self._init_bucket_slots(params, 1)
            return SGDState(jnp.int32(0), buf, master)
        return SGDState(
            step=jnp.int32(0),
            momentum_buffer=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
            master=base.make_master(params, self.master_weights),
        )

    def update(self, grads, state, params, grads_finite=None, lr=None,
               scale=1.0, clip_norm=None, sumsq_reduce=None, **kw):
        """``scale``: the reference's ``1/most_recent_scale`` grad
        prescale, folded into the one grad read."""
        p, s, _ = self._dispatch(grads, state, params,
                                 grads_finite=grads_finite, lr=lr,
                                 clip_norm=clip_norm,
                                 sumsq_reduce=sumsq_reduce,
                                 prescale=1.0 / scale, **kw)
        return p, s

    # ------------------------------------------------------- per-leaf path
    def _leaf_update(self, grads, state: SGDState, params,
                     grads_finite=None, lr=None):
        # grads arrive f32 with the prescale already applied (_dispatch)
        lr = self.lr if lr is None else lr
        wd, mu, damp = self.weight_decay, self.momentum, self.dampening
        first_run = state.step == 0

        step = base.predicate_step(grads_finite, state.step)
        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers,
                                  extra_keys=self._HYPER_KEYS)

        def one(g, p, buf, h):
            wd_i = h.get("weight_decay", wd)
            lr_i = base.leaf_lr(h, lr)
            mu_i = h.get("momentum", mu)
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.wd_after_momentum and wd_i != 0.0:
                g = g + wd_i * p32
            if mu_i != 0.0:
                steady = mu_i * buf + (1.0 - damp) * g
                buf_new = jnp.where(first_run, g, steady)
                if self.nesterov:
                    g = g + mu_i * buf_new
                else:
                    g = buf_new
            else:
                buf_new = buf
            if self.wd_after_momentum and wd_i != 0.0:
                g = g + wd_i * p32
            return p32 - lr_i * g, buf_new

        out = jax.tree.map(one, grads, p_math, state.momentum_buffer, hypers)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        buf_new = jax.tree.unflatten(treedef, [x[1] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        buf_new = base.select(grads_finite, buf_new, state.momentum_buffer)
        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, SGDState(step, buf_new, new_master)

    # --------------------------------------------------------- bucket path
    def _bucket_update(self, prep: base.PreparedGrads, state: SGDState,
                       params, pred, lr=None):
        lr = self.lr if lr is None else lr
        wd, mu, damp = self.weight_decay, self.momentum, self.dampening
        plan = prep.plan
        first_run = state.step == 0

        step = base.predicate_step(pred, state.step)
        buf_b, resident = self._slot_buckets(plan, state.momentum_buffer)
        has_master = state.master is not None
        if has_master:
            p_b, _ = self._slot_buckets(plan, state.master)
        else:
            p_b = bucketing.pack(plan, params)
        hl = self._hyper_leaves(base.leaf_hypers(
            params, self.param_group_fn, self.group_hypers,
            extra_keys=self._HYPER_KEYS))
        wd_leaf = [h.get("weight_decay", wd) for h in hl]
        mu_leaf = [h.get("momentum", mu) for h in hl]

        new_p, new_buf = [], []
        for bi, b in enumerate(plan.buckets):
            g, p32, buf = prep.g[bi], p_b[bi], buf_b[bi]
            wd_i = bucketing.seg_values(b, wd_leaf)
            mu_i = bucketing.seg_values(b, mu_leaf)
            lr_i = self._bucket_lr(b, hl, lr)
            mu_scalar = isinstance(mu_i, float)
            wd_scalar = isinstance(wd_i, float)
            if not self.wd_after_momentum and not (wd_scalar and wd_i == 0.0):
                g = g + wd_i * p32
            if mu_scalar and mu_i == 0.0:
                buf_new = buf
            else:
                steady = mu_i * buf + (1.0 - damp) * g
                mom_buf = jnp.where(first_run, g, steady)
                g_mom = g + mu_i * mom_buf if self.nesterov else mom_buf
                if mu_scalar:
                    buf_new, g = mom_buf, g_mom
                else:
                    # per-group momentum: μ=0 leaves keep their buffer
                    # untouched and step on the raw grad, exactly like
                    # the per-leaf momentum-free branch
                    live = mu_i != 0.0
                    buf_new = jnp.where(live, mom_buf, buf)
                    g = jnp.where(live, g_mom, g)
            if self.wd_after_momentum and not (wd_scalar and wd_i == 0.0):
                g = g + wd_i * p32
            new_p.append(p32 - lr_i * g)
            new_buf.append(buf_new)

        new_p = base.bucket_select(pred, new_p, p_b)
        new_buf = base.bucket_select(pred, new_buf, buf_b)
        new_params = bucketing.unpack(plan, new_p)
        new_master = (self._emit_slot(plan, new_p, resident)
                      if has_master else None)
        return new_params, SGDState(
            step, self._emit_slot(plan, new_buf, resident), new_master)
