"""FusedLAMB — layerwise-adaptive large-batch optimizer.

Reference: ``apex/optimizers/fused_lamb.py`` (driver: global grad norm
blended across dtype groups, :120-183) and ``csrc/multi_tensor_lamb.cu``
(LAMBStage1Functor :41, LAMBStage2Functor :233, host :330-410).

Two-phase semantics reproduced exactly:

1. Global grad-norm clipping: ``clip = gn/max_grad_norm if gn > max else 1``;
   every grad is divided by ``clip``.
2. Stage 1 per element: Adam-style moments on the clipped grad
   (``m = β1·m + β3·g`` with ``β3 = 1-β1`` if ``grad_averaging``), update
   ``u = m̂/(sqrt(v̂)+eps) (+ wd·p)`` (L2 mode folds wd into g instead).
3. Stage 2 per tensor: trust ratio ``r = ‖p‖/‖u‖`` applied when
   ``use_nvlamb or wd != 0`` and both norms are nonzero;
   ``p -= lr·r·u``.

Runs on the bucketed multi-tensor engine by default (see
:mod:`apex_tpu.optimizers.base`): stage 1 is one fused pass per dtype
bucket; the per-tensor norms of stage 2 read the buckets through the
plan's static offset table, and the trust ratios broadcast back as one
per-element gather.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base, bucketing


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    master: Optional[Any] = None


def lamb_stage1_math(g, p32, m, v, wd_i, bc1, bc2, *, beta1, beta2, eps,
                     adam_w_mode, grad_averaging):
    """Stage-1 LAMB per element (LAMBStage1Functor) — module-level so
    the ZeRO-sharded :class:`~apex_tpu.contrib.optimizers.
    DistributedFusedLAMB` evaluates the identical expression tree on
    its dp shards."""
    b3 = (1.0 - beta1) if grad_averaging else 1.0
    if not adam_w_mode:  # MOMENT_MODE_0: L2 on scaled grad
        g = g + wd_i * p32
    m_new = beta1 * m + b3 * g
    v_new = beta2 * v + (1.0 - beta2) * (g * g)
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:  # MOMENT_MODE_1: decoupled
        u = u + wd_i * p32
    return u, m_new, v_new


def lamb_trust_ratio(lr_i, p_norm, u_norm, *, apply_ratio):
    """Stage-2 per-tensor ratio (multi_tensor_lamb.cu:255-262)."""
    if apply_ratio:
        return jnp.where((p_norm != 0.0) & (u_norm != 0.0),
                         lr_i * (p_norm / u_norm), lr_i)
    return jnp.asarray(lr_i, jnp.float32)


def lamb_grad_clip(global_grad_norm, max_grad_norm):
    """fused_lamb.py:121-136: the divide-every-grad-by factor when the
    global norm exceeds the max."""
    return jnp.where(global_grad_norm > max_grad_norm,
                     global_grad_norm / max_grad_norm, jnp.float32(1.0))


class FusedLAMB(base.OptimizerBase):

    #: group-override keys beyond the base lr/lr_scale/weight_decay set
    _HYPER_KEYS = ("use_trust_ratio",)

    _BUCKET_SLOT = "exp_avg"

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        amsgrad: bool = False,
        adam_w_mode: bool = True,
        grad_averaging: bool = True,
        max_grad_norm: float = 1.0,
        use_nvlamb: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
        use_buckets: bool = True,
    ):
        """``param_group_fn``/``group_hypers``: functional param_groups
        (see :class:`~apex_tpu.optimizers.FusedAdam`).  LAMB additionally
        honors the per-group key ``use_trust_ratio`` (False → plain lr
        step, the BERT recipe's exclude_from_layer_adaptation for
        norms/biases)."""
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        super().__init__(lr, weight_decay, master_weights,
                         use_buckets=use_buckets)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params, bucketed: bool = False) -> LambState:
        if bucketed:
            (m, v), master = self._init_bucket_slots(params, 2)
            return LambState(jnp.int32(0), m, v, master)
        zeros = lambda t: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), t)
        return LambState(
            step=jnp.int32(0),
            exp_avg=zeros(params),
            exp_avg_sq=zeros(params),
            master=base.make_master(params, self.master_weights),
        )

    def _grad_clip(self, global_grad_norm):
        """fused_lamb.py:121-136: divide every grad by
        ``gn/max_grad_norm`` when the global norm exceeds the max."""
        return lamb_grad_clip(global_grad_norm, self.max_grad_norm)

    def _stage1_math(self, g, p32, m, v, wd_i, bc1, bc2):
        """Shared stage-1 expression tree (per-leaf == bucket)."""
        return lamb_stage1_math(
            g, p32, m, v, wd_i, bc1, bc2, beta1=self.beta1,
            beta2=self.beta2, eps=self.eps, adam_w_mode=self.adam_w_mode,
            grad_averaging=self.grad_averaging)

    def _trust_ratio(self, h, wd_i, lr_i, p_norm, u_norm):
        """Stage-2 per-tensor ratio (multi_tensor_lamb.cu:255-262)."""
        apply = h.get("use_trust_ratio", True) and (
            self.use_nvlamb or wd_i != 0.0)
        return lamb_trust_ratio(lr_i, p_norm, u_norm, apply_ratio=apply)

    # ------------------------------------------------------- per-leaf path
    def _leaf_update(self, grads, state: LambState, params,
                     grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay

        step = base.predicate_step(grads_finite, state.step)
        bc1, bc2 = self._bias_corrections(step)

        # Global grad norm over every param (fused_lamb.py:121-136).
        g32 = base.f32(grads)
        sq = [jnp.sum(jnp.square(g)) for g in jax.tree.leaves(g32)]
        clip = self._grad_clip(jnp.sqrt(jnp.stack(sq).sum()))

        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers,
                                  extra_keys=self._HYPER_KEYS)
        treedef = jax.tree.structure(grads)

        def stage1(g, p, m, v, h):
            return self._stage1_math(
                g.astype(jnp.float32) / clip, p.astype(jnp.float32), m, v,
                h.get("weight_decay", wd), bc1, bc2)

        out = jax.tree.map(stage1, grads, p_math, state.exp_avg, state.exp_avg_sq, hypers)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        updates = jax.tree.unflatten(treedef, [x[0] for x in flat])
        m_new = jax.tree.unflatten(treedef, [x[1] for x in flat])
        v_new = jax.tree.unflatten(treedef, [x[2] for x in flat])

        # Stage 2: per-tensor trust ratio (multi_tensor_lamb.cu:255-262).
        def stage2(p, u, h):
            wd_i = h.get("weight_decay", wd)
            lr_i = base.leaf_lr(h, lr)
            p32 = p.astype(jnp.float32)
            ratio = self._trust_ratio(
                h, wd_i, lr_i,
                jnp.sqrt(jnp.sum(jnp.square(p32))),
                jnp.sqrt(jnp.sum(jnp.square(u))))
            return p32 - ratio * u

        p_new = jax.tree.map(stage2, p_math, updates, hypers)

        p_new = base.select(grads_finite, p_new, p_math)
        m_new = base.select(grads_finite, m_new, state.exp_avg)
        v_new = base.select(grads_finite, v_new, state.exp_avg_sq)

        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, LambState(step, m_new, v_new, new_master)

    # --------------------------------------------------------- bucket path
    def _bucket_update(self, prep: base.PreparedGrads, state: LambState,
                       params, pred, lr=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = prep.plan

        step = base.predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)

        # global grad norm through the offset table: per-leaf Σg² in
        # flat order, combined exactly like the per-leaf path
        sq = bucketing.per_leaf_reduce(
            plan, prep.g, lambda x: jnp.sum(jnp.square(x)))
        clip = self._grad_clip(jnp.sqrt(jnp.stack(sq).sum()))

        m_b, resident = self._slot_buckets(plan, state.exp_avg)
        v_b, _ = self._slot_buckets(plan, state.exp_avg_sq)
        has_master = state.master is not None
        if has_master:
            p_b, _ = self._slot_buckets(plan, state.master)
        else:
            p_b = bucketing.pack(plan, params)
        hl = self._hyper_leaves(base.leaf_hypers(
            params, self.param_group_fn, self.group_hypers,
            extra_keys=self._HYPER_KEYS))
        wd_leaf = [h.get("weight_decay", wd) for h in hl]

        # stage 1: one fused pass per bucket
        u_b, new_m, new_v = [], [], []
        for bi, b in enumerate(plan.buckets):
            u, m_out, v_out = self._stage1_math(
                prep.g[bi] / clip, p_b[bi], m_b[bi], v_b[bi],
                bucketing.seg_values(b, wd_leaf), bc1, bc2)
            u_b.append(u)
            new_m.append(m_out)
            new_v.append(v_out)

        # stage 2: per-tensor trust ratios from the offset table
        p_sq = bucketing.per_leaf_reduce(
            plan, p_b, lambda x: jnp.sum(jnp.square(x)))
        u_sq = bucketing.per_leaf_reduce(
            plan, u_b, lambda x: jnp.sum(jnp.square(x)))
        ratios = [
            self._trust_ratio(
                h, h.get("weight_decay", wd), base.leaf_lr(h, lr),
                jnp.sqrt(p_sq[i]), jnp.sqrt(u_sq[i]))
            for i, h in enumerate(hl)
        ]
        new_p = [
            p_b[bi] - bucketing.seg_broadcast(b, ratios) * u_b[bi]
            for bi, b in enumerate(plan.buckets)
        ]

        new_p = base.bucket_select(pred, new_p, p_b)
        new_m = base.bucket_select(pred, new_m, m_b)
        new_v = base.bucket_select(pred, new_v, v_b)

        new_params = bucketing.unpack(plan, new_p)
        new_master = (self._emit_slot(plan, new_p, resident)
                      if has_master else None)
        return new_params, LambState(
            step,
            self._emit_slot(plan, new_m, resident),
            self._emit_slot(plan, new_v, resident),
            new_master,
        )
