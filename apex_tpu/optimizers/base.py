"""Shared machinery for the fused optimizers.

Reference: ``apex/optimizers/*`` — each optimizer gathers params into
dtype-grouped flat lists and fires one multi-tensor CUDA kernel.  On TPU
the whole step is one XLA program, so each optimizer here is a pure
function over pytrees; "fused" survives as (a) math done in fp32 regardless
of storage dtype, exactly as the kernels' ``MATH_T=float``, (b) a single
jit region with no host sync, and (c) the capturable design: the update is
*predicated* on a device-resident ``grads_finite`` flag instead of a host
decision (``fused_adam.py:199-263``, ``multi_tensor_adam.cu:130``).

Master weights: when params are stored in half precision and
``master_weights=True``, an fp32 master copy lives in the optimizer state;
math reads/writes the master and the returned params are the master cast
back to storage dtype (reference: ``AdamCapturableMasterFunctor``,
``multi_tensor_adam.cu:243``; ``fp16_utils/fp16_optimizer.py``).

Multi-tensor engine: each optimizer's ``update`` dispatches through
:meth:`OptimizerBase._dispatch` — by default onto the **bucketed
engine** (``use_buckets=True``): the param pytree flattens into a few
dtype-homogeneous 1-D buckets (:mod:`apex_tpu.optimizers.bucketing`)
and the whole step is one fused elementwise pass per bucket, with the
loss-scale unscale, the global-l2-norm grad clip, and the all-finite
vote folded into the same pass (``update_scaled``) so grads are read
once instead of once per sweep.  The per-leaf path remains as the
numerics specification and the fallback: the engine routes through the
``resilience.fallback`` registry, so an engine surprise degrades once
to per-leaf instead of crashing a run.  Both paths are bit-exact in
fp32 (same elementwise expression trees; ``tests/test_bucketed_engine``
pins it).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.observability import stepstats as _stepstats
from apex_tpu.optimizers import bucketing

Tree = Any


def is_half(x) -> bool:
    return x.dtype in (jnp.float16, jnp.bfloat16)


def make_master(params: Tree, master_weights: bool) -> Optional[Tree]:
    """fp32 master COPY of the params.  ``copy=True`` is load-bearing:
    ``astype`` on an already-fp32 leaf returns the same buffer, and a
    master that aliases its param makes ``donate_argnums`` over
    (params, state) donate one buffer twice — an Execute()-time crash
    (caught by ``bench.py --smoke`` on the resnet amp-O2 step)."""
    if not master_weights:
        return None
    return jax.tree.map(lambda p: jnp.array(p, jnp.float32, copy=True),
                        params)


def math_params(params: Tree, master: Optional[Tree]) -> Tree:
    """The tree the optimizer math should read (master if present)."""
    return master if master is not None else params


def emit_params(new_math_params: Tree, params: Tree, master: Optional[Tree]):
    """Return (new_params_in_storage_dtype, new_master)."""
    if master is None:
        return jax.tree.map(lambda n, p: n.astype(p.dtype), new_math_params, params), None
    new_params = jax.tree.map(lambda m, p: m.astype(p.dtype), new_math_params, params)
    return new_params, new_math_params


def predicate_step(grads_finite, step: jnp.ndarray) -> jnp.ndarray:
    """step advances only on finite grads (fused_adam.py:262:
    ``group['step'] += (_dummy_overflow_buf != 1)``)."""
    if grads_finite is None:
        return step + 1
    return step + jnp.asarray(grads_finite).astype(step.dtype)


def select(grads_finite, new: Tree, old: Tree) -> Tree:
    """Predicated commit: keep old values on overflow (noop_flag set)."""
    if grads_finite is None:
        return new
    pred = jnp.asarray(grads_finite)
    return jax.tree.map(lambda n, o: jnp.where(pred, n, o.astype(n.dtype)), new, old)


def f32(tree: Tree) -> Tree:
    return jax.tree.map(lambda x: x.astype(jnp.float32), tree)


def bias_corrections(step, bias_correction: bool, beta1, beta2):
    """Adam-family ``(1-β1^t, 1-β2^t)`` — module-level so the ZeRO
    optimizers (which are not :class:`OptimizerBase` subclasses) share
    the exact expression the per-leaf oracle evaluates."""
    if not bias_correction:
        return jnp.float32(1.0), jnp.float32(1.0)
    t = step.astype(jnp.float32)
    return (1.0 - jnp.power(beta1, t), 1.0 - jnp.power(beta2, t))


class HyperLeaf(dict):
    """An override dict that is a pytree *leaf* (unregistered dict
    subclass), so a tree of them can ride through ``jax.tree.map``
    alongside array trees."""


#: override keys every optimizer understands
_BASE_HYPER_KEYS = frozenset({"lr", "lr_scale", "weight_decay"})


def leaf_hypers(params: Tree, param_group_fn, group_hypers,
                extra_keys=()) -> Optional[Tree]:
    """Per-leaf hyperparameter overrides — the functional form of torch
    ``param_groups`` (reference optimizers iterate
    ``self.param_groups`` with per-group lr/weight_decay,
    fused_adam.py:127+).

    ``param_group_fn(path_str, leaf) -> group_name`` assigns each param
    leaf to a named group at trace time (paths are static);
    ``group_hypers[group_name]`` is a dict of overrides (``lr``
    (absolute — replaces any runtime schedule for that group),
    ``lr_scale`` (multiplies the runtime lr), ``weight_decay``,
    optimizer-specific keys).  Returns a tree of :class:`HyperLeaf`
    matching ``params``, or None when no grouping is configured.
    Raises if a ``group_hypers`` key names a group no param maps to
    (a typo'd group name must not silently disable its overrides), and
    if any override key inside a group is not one the calling optimizer
    reads (``lr``/``lr_scale``/``weight_decay`` plus ``extra_keys``) —
    a typo like ``weight_dacay`` must not be silently ignored.
    When no grouping is configured, returns a tree of empty overrides
    (so optimizers have one code path).
    """
    allowed = _BASE_HYPER_KEYS | set(extra_keys)
    for gname, overrides in (group_hypers or {}).items():
        unknown = set(overrides) - allowed
        if unknown:
            raise ValueError(
                f"group_hypers[{gname!r}] has unknown override keys "
                f"{sorted(unknown)}; this optimizer supports {sorted(allowed)}"
            )
    if param_group_fn is None:
        if group_hypers:
            raise ValueError(
                "group_hypers given without param_group_fn — no param can "
                "map to any group, so the overrides would be silently ignored"
            )
        return jax.tree.map(lambda _: HyperLeaf(), params)
    group_hypers = group_hypers or {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    seen = set()
    out = []
    for kp, leaf in flat:
        g = param_group_fn(jax.tree_util.keystr(kp), leaf)
        seen.add(g)
        out.append(HyperLeaf(group_hypers.get(g, {})))
    unused = set(group_hypers) - seen
    if unused:
        raise ValueError(
            f"group_hypers keys {sorted(unused)} match no param group "
            f"(param_group_fn produced {sorted(seen)})"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def leaf_lr(h: dict, lr):
    """Resolve a leaf's lr: absolute ``lr`` override wins, else the
    runtime lr scaled by ``lr_scale``."""
    if "lr" in h:
        return h["lr"]
    return lr * h.get("lr_scale", 1.0)


class PreparedGrads(NamedTuple):
    """Grads after the fused prepare pass: packed into ``plan``'s f32
    buckets with loss-scale unscale and global-norm clip folded in, plus
    the (synced) all-finite vote — the one read of the grad tree."""

    plan: Any
    g: Tuple
    finite: Optional[jnp.ndarray]


def _bucket_all_finite(bucket_arrays) -> jnp.ndarray:
    """All-finite vote over packed buckets (pad regions are zero-filled
    by :func:`bucketing.pack`, so they never mask a leaf's inf/nan).
    ONE vote implementation — the amp scaler's (a list of arrays is a
    tree), so the engine's step predicate and the scaler's found-inf
    decision can never diverge."""
    from apex_tpu.amp.scaler import all_finite

    return all_finite(list(bucket_arrays))


def _clip_coef(total_norm, clip_norm):
    """torch ``clip_grad_norm_`` semantics (contrib/clip_grad):
    ``min(max_norm / (total_norm + 1e-6), 1.0)``."""
    return jnp.minimum(clip_norm / (total_norm + 1e-6), jnp.float32(1.0))


def prepare_grads_bucketed(params, grads, scale=None, clip_norm=None,
                           finite_sync=None, want_finite=False,
                           prescale=None, sumsq_reduce=None) -> PreparedGrads:
    """The fused prepare pass: one read of the grad tree produces the
    unscaled (``scale``), clipped (``clip_norm``) f32 buckets and the
    agreed all-finite predicate — replacing the reference's three
    separate ``multi_tensor_scale`` / ``multi_tensor_l2norm`` /
    noop-flag sweeps (``apex/amp/scaler.py:94-119``,
    ``contrib/clip_grad``).

    ``sumsq_reduce(per_leaf_sumsq) -> total_sumsq``: overrides the
    plain stack-and-sum for sharded steps — inside a shard_map a
    tp/pp/ep-sharded leaf's grads are LOCAL shards, so the true global
    norm needs a psum of those leaves' Σx² across their sharding axes
    (:func:`apex_tpu.models.gpt.clip_sumsq_reduce` builds this from
    the param PartitionSpecs)."""
    plan = bucketing.plan_of(params)
    mult = None
    if scale is not None:
        mult = 1.0 / scale
    if prescale is not None:
        mult = prescale if mult is None else mult * prescale
    g = bucketing.pack(plan, grads, scale=mult)
    finite = None
    if want_finite:
        finite = _bucket_all_finite(g)
        if finite_sync is not None:
            finite = finite_sync(finite)
    if clip_norm is not None:
        sq = bucketing.per_leaf_reduce(
            plan, g, lambda x: jnp.sum(jnp.square(x)))
        total_sq = (jnp.stack(sq).sum() if sumsq_reduce is None
                    else sumsq_reduce(sq))
        # the telemetry seam reuses the clip's (globally agreed) norm —
        # the "no new HBM pass" contract of observability.stepstats
        _stepstats.offer("grad_norm", jnp.sqrt(total_sq))
        coef = _clip_coef(jnp.sqrt(total_sq), clip_norm)
        g = [a * coef for a in g]
    else:
        # no clip to reuse: the shared rank-local fold (no-op unless a
        # telemetry wrapper captures; docs/observability.md)
        _stepstats.offer_local_grad_norm(g)
    return PreparedGrads(plan=plan, g=tuple(g), finite=finite)


class OptimizerBase:
    """Common constructor plumbing + the engine dispatch.  Subclasses
    implement ``init``, ``_leaf_update`` (the per-leaf numerics
    specification), and ``_bucket_update`` (the fused engine)."""

    #: state field holding the slot that is a :class:`bucketing.Buckets`
    #: when the state is bucket-resident (subclasses override)
    _BUCKET_SLOT: Optional[str] = None

    #: True when :meth:`update_scaled` covers this optimizer's FULL
    #: step semantics.  A subclass whose ``update`` override maintains
    #: extra state the fused tail doesn't know about (e.g. contrib
    #: ``FusedAdamSWA``'s SWA average) must set this False so train
    #: steps route through its ``update`` with the explicit sweep
    #: composition instead of bypassing the override.
    supports_update_scaled: bool = True

    def __init__(self, lr: float, weight_decay: float = 0.0,
                 master_weights: bool = False, use_buckets: bool = True):
        self.lr = lr
        self.weight_decay = weight_decay
        self.master_weights = master_weights
        self.use_buckets = use_buckets

    # ------------------------------------------------------------ engine
    def _state_is_bucketed(self, state) -> bool:
        if self._BUCKET_SLOT is None:
            return False
        return isinstance(getattr(state, self._BUCKET_SLOT, None),
                          bucketing.Buckets)

    def _leaf_update(self, grads, state, params, grads_finite=None,
                     lr=None, **kw):  # pragma: no cover - abstract
        raise NotImplementedError

    def _bucket_update(self, prep: PreparedGrads, state, params, pred,
                       lr=None, **kw):  # pragma: no cover - abstract
        raise NotImplementedError

    def _dispatch(self, grads, state, params, grads_finite=None, lr=None,
                  scale=None, clip_norm=None, finite_sync=None,
                  want_finite=False, prescale=None, sumsq_reduce=None,
                  **kw):
        """Route one step: bucket-resident state → engine (no fallback
        possible: the per-leaf path cannot read flat slots); tree state
        → engine through the resilience fallback registry (an engine
        failure degrades once to per-leaf); ``use_buckets=False`` →
        per-leaf.  Returns ``(new_params, new_state, finite)``."""

        def leaf_path():
            g, finite = grads, grads_finite
            if scale is not None or prescale is not None:
                mult = 1.0 if scale is None else 1.0 / scale
                if prescale is not None:
                    mult = mult * prescale
                g = jax.tree.map(
                    lambda x: x.astype(jnp.float32) * mult, g)
            if want_finite:
                from apex_tpu.amp.scaler import all_finite

                finite = all_finite(g)
                if finite_sync is not None:
                    finite = finite_sync(finite)
            if clip_norm is not None:
                sq = [jnp.sum(jnp.square(x.astype(jnp.float32)))
                      for x in jax.tree.leaves(g)]
                total_sq = (jnp.stack(sq).sum() if sumsq_reduce is None
                            else sumsq_reduce(sq))
                _stepstats.offer("grad_norm", jnp.sqrt(total_sq))
                coef = _clip_coef(jnp.sqrt(total_sq), clip_norm)
                g = jax.tree.map(
                    lambda x: x.astype(jnp.float32) * coef, g)
            else:
                _stepstats.offer_local_grad_norm(jax.tree.leaves(g))
            p, s = self._leaf_update(g, state, params,
                                     grads_finite=finite, lr=lr, **kw)
            return p, s, finite

        def bucket_path():
            prep = prepare_grads_bucketed(
                params, grads, scale=scale, clip_norm=clip_norm,
                finite_sync=finite_sync, want_finite=want_finite,
                prescale=prescale, sumsq_reduce=sumsq_reduce)
            pred = prep.finite if want_finite else grads_finite
            p, s = self._bucket_update(prep, state, params, pred, lr=lr,
                                       **kw)
            return p, s, pred

        if self._state_is_bucketed(state):
            return bucket_path()
        if self.use_buckets and self._BUCKET_SLOT is not None:
            from apex_tpu.resilience.fallback import (
                get_registry,
                registry_engaged,
            )

            if registry_engaged(forced=False):
                return get_registry().call(
                    "multi_tensor_engine", bucket_path, leaf_path)
            # multi-process runs never engage the registry (fallback.py:
            # a per-process degrade-once would lower DIVERGENT programs
            # of one SPMD step — with the clip psums and the finite-vote
            # collectives inside): run the engine directly, fail fast
            return bucket_path()
        return leaf_path()

    def _init_bucket_slots(self, params, n_slots):
        """The shared resident-state constructor: ``n_slots`` zeroed
        f32 bucket slots for ``params``' plan, plus the packed fp32
        master when ``master_weights`` — ONE place to change the
        resident layout (e.g. future sharded buckets)."""
        plan = bucketing.plan_of(params)
        slots = [
            bucketing.Buckets(plan, [jnp.zeros((b.total,), jnp.float32)
                                     for b in plan.buckets])
            for _ in range(n_slots)
        ]
        master = (bucketing.Buckets(plan, bucketing.pack(plan, params))
                  if self.master_weights else None)
        return slots, master

    def _bias_corrections(self, step):
        """Adam-family ``(1-β1^t, 1-β2^t)`` — reads the subclass's
        ``bias_correction``/``beta1``/``beta2`` attributes (NovoGrad
        overrides: its second correction is the sqrt form)."""
        return bias_corrections(step, self.bias_correction,
                                self.beta1, self.beta2)

    # --------------------------------------------------------- public API
    def init(self, params, bucketed: bool = False):  # pragma: no cover
        raise NotImplementedError

    def update(self, grads, state, params, grads_finite=None, lr=None,
               clip_norm=None, sumsq_reduce=None, **kw):
        """One optimizer step (optax-style signature).  ``grads_finite``
        predicates the whole commit device-side (the capturable
        noop_flag design); ``clip_norm`` folds a global-l2 grad clip
        (torch ``clip_grad_norm_`` semantics) into the grad read, with
        ``sumsq_reduce`` supplying the cross-rank Σx² agreement inside
        sharded steps (see :func:`prepare_grads_bucketed`)."""
        p, s, _ = self._dispatch(grads, state, params,
                                 grads_finite=grads_finite, lr=lr,
                                 clip_norm=clip_norm,
                                 sumsq_reduce=sumsq_reduce, **kw)
        return p, s

    def update_scaled(self, grads, state, params, scale=None,
                      clip_norm=None, finite_sync=None, lr=None,
                      sumsq_reduce=None, **kw):
        """The fused amp step: unscale by ``1/scale``, (optionally) clip
        to ``clip_norm`` (global l2, torch semantics), vote all-finite,
        agree the vote via ``finite_sync`` (the model-parallel pmax),
        and commit the update predicated on it — one pass over the
        grads instead of the reference's four separate sweeps
        (``apex/amp/handle.py:119-158``).  Returns
        ``(new_params, new_state, all_finite)``; feed ``all_finite`` to
        :meth:`apex_tpu.amp.DynamicLossScaler.update` and the step
        guard.  ``scale=None`` skips the unscale (the bf16/fp32 guarded
        path) but still folds the finite vote into the pass."""
        return self._dispatch(grads, state, params, lr=lr, scale=scale,
                              clip_norm=clip_norm, finite_sync=finite_sync,
                              want_finite=True, sumsq_reduce=sumsq_reduce,
                              **kw)

    def step(self, grads, state, params, **kw):
        """Alias matching the reference's ``optimizer.step()`` naming."""
        return self.update(grads, state, params, **kw)

    # ------------------------------------------------- bucket-side helpers
    @staticmethod
    def _hyper_leaves(hypers):
        """The static per-leaf override dicts in tree_flatten order."""
        return jax.tree.leaves(
            hypers, is_leaf=lambda x: isinstance(x, HyperLeaf))

    @staticmethod
    def _bucket_lr(bucket, hyper_leaves, lr):
        """Per-element lr operand for one bucket: the runtime scalar
        when no group overrides it, else a broadcast per-leaf vector
        (absolute ``lr`` wins; ``lr_scale`` multiplies — exactly
        :func:`leaf_lr`)."""
        if not any(("lr" in h or "lr_scale" in h) for h in hyper_leaves):
            return lr
        per = [leaf_lr(h, lr) for h in hyper_leaves]
        return bucketing.seg_broadcast(bucket, per)

    @staticmethod
    def _slot_buckets(plan, slot):
        """A state slot as bucket arrays: pass-through when resident,
        packed (f32) when tree-shaped."""
        if isinstance(slot, bucketing.Buckets):
            return slot.arrays, True
        return tuple(bucketing.pack(plan, slot)), False

    @staticmethod
    def _emit_slot(plan, arrays, resident):
        """A new state slot: stays flat when resident (the donated
        buffers), unpacks to the fp32 per-leaf tree otherwise."""
        if resident:
            return bucketing.Buckets(plan, arrays)
        return bucketing.unpack(plan, arrays, dtype=jnp.float32)


def bucket_select(pred, new_arrays, old_arrays):
    """Predicated commit on bucket buffers (the flat form of
    :func:`select`)."""
    if pred is None:
        return list(new_arrays)
    p = jnp.asarray(pred)
    return [jnp.where(p, n, o) for n, o in zip(new_arrays, old_arrays)]
