"""Shared machinery for the fused optimizers.

Reference: ``apex/optimizers/*`` — each optimizer gathers params into
dtype-grouped flat lists and fires one multi-tensor CUDA kernel.  On TPU
the whole step is one XLA program, so each optimizer here is a pure
function over pytrees; "fused" survives as (a) math done in fp32 regardless
of storage dtype, exactly as the kernels' ``MATH_T=float``, (b) a single
jit region with no host sync, and (c) the capturable design: the update is
*predicated* on a device-resident ``grads_finite`` flag instead of a host
decision (``fused_adam.py:199-263``, ``multi_tensor_adam.cu:130``).

Master weights: when params are stored in half precision and
``master_weights=True``, an fp32 master copy lives in the optimizer state;
math reads/writes the master and the returned params are the master cast
back to storage dtype (reference: ``AdamCapturableMasterFunctor``,
``multi_tensor_adam.cu:243``; ``fp16_utils/fp16_optimizer.py``).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

Tree = Any


def is_half(x) -> bool:
    return x.dtype in (jnp.float16, jnp.bfloat16)


def make_master(params: Tree, master_weights: bool) -> Optional[Tree]:
    """fp32 master copy of half params (None leaves where already fp32)."""
    if not master_weights:
        return None
    return jax.tree.map(lambda p: p.astype(jnp.float32), params)


def math_params(params: Tree, master: Optional[Tree]) -> Tree:
    """The tree the optimizer math should read (master if present)."""
    return master if master is not None else params


def emit_params(new_math_params: Tree, params: Tree, master: Optional[Tree]):
    """Return (new_params_in_storage_dtype, new_master)."""
    if master is None:
        return jax.tree.map(lambda n, p: n.astype(p.dtype), new_math_params, params), None
    new_params = jax.tree.map(lambda m, p: m.astype(p.dtype), new_math_params, params)
    return new_params, new_math_params


def predicate_step(grads_finite, step: jnp.ndarray) -> jnp.ndarray:
    """step advances only on finite grads (fused_adam.py:262:
    ``group['step'] += (_dummy_overflow_buf != 1)``)."""
    if grads_finite is None:
        return step + 1
    return step + jnp.asarray(grads_finite).astype(step.dtype)


def select(grads_finite, new: Tree, old: Tree) -> Tree:
    """Predicated commit: keep old values on overflow (noop_flag set)."""
    if grads_finite is None:
        return new
    pred = jnp.asarray(grads_finite)
    return jax.tree.map(lambda n, o: jnp.where(pred, n, o.astype(n.dtype)), new, old)


def f32(tree: Tree) -> Tree:
    return jax.tree.map(lambda x: x.astype(jnp.float32), tree)


class HyperLeaf(dict):
    """An override dict that is a pytree *leaf* (unregistered dict
    subclass), so a tree of them can ride through ``jax.tree.map``
    alongside array trees."""


#: override keys every optimizer understands
_BASE_HYPER_KEYS = frozenset({"lr", "lr_scale", "weight_decay"})


def leaf_hypers(params: Tree, param_group_fn, group_hypers,
                extra_keys=()) -> Optional[Tree]:
    """Per-leaf hyperparameter overrides — the functional form of torch
    ``param_groups`` (reference optimizers iterate
    ``self.param_groups`` with per-group lr/weight_decay,
    fused_adam.py:127+).

    ``param_group_fn(path_str, leaf) -> group_name`` assigns each param
    leaf to a named group at trace time (paths are static);
    ``group_hypers[group_name]`` is a dict of overrides (``lr``
    (absolute — replaces any runtime schedule for that group),
    ``lr_scale`` (multiplies the runtime lr), ``weight_decay``,
    optimizer-specific keys).  Returns a tree of :class:`HyperLeaf`
    matching ``params``, or None when no grouping is configured.
    Raises if a ``group_hypers`` key names a group no param maps to
    (a typo'd group name must not silently disable its overrides), and
    if any override key inside a group is not one the calling optimizer
    reads (``lr``/``lr_scale``/``weight_decay`` plus ``extra_keys``) —
    a typo like ``weight_dacay`` must not be silently ignored.
    When no grouping is configured, returns a tree of empty overrides
    (so optimizers have one code path).
    """
    allowed = _BASE_HYPER_KEYS | set(extra_keys)
    for gname, overrides in (group_hypers or {}).items():
        unknown = set(overrides) - allowed
        if unknown:
            raise ValueError(
                f"group_hypers[{gname!r}] has unknown override keys "
                f"{sorted(unknown)}; this optimizer supports {sorted(allowed)}"
            )
    if param_group_fn is None:
        if group_hypers:
            raise ValueError(
                "group_hypers given without param_group_fn — no param can "
                "map to any group, so the overrides would be silently ignored"
            )
        return jax.tree.map(lambda _: HyperLeaf(), params)
    group_hypers = group_hypers or {}
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    seen = set()
    out = []
    for kp, leaf in flat:
        g = param_group_fn(jax.tree_util.keystr(kp), leaf)
        seen.add(g)
        out.append(HyperLeaf(group_hypers.get(g, {})))
    unused = set(group_hypers) - seen
    if unused:
        raise ValueError(
            f"group_hypers keys {sorted(unused)} match no param group "
            f"(param_group_fn produced {sorted(seen)})"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def leaf_lr(h: dict, lr):
    """Resolve a leaf's lr: absolute ``lr`` override wins, else the
    runtime lr scaled by ``lr_scale``."""
    if "lr" in h:
        return h["lr"]
    return lr * h.get("lr_scale", 1.0)


class OptimizerBase:
    """Common constructor plumbing.  Subclasses define init/update."""

    def __init__(self, lr: float, weight_decay: float = 0.0, master_weights: bool = False):
        self.lr = lr
        self.weight_decay = weight_decay
        self.master_weights = master_weights

    # optax-style aliases so these slot into optax training loops
    def init(self, params):  # pragma: no cover - abstract
        raise NotImplementedError

    def update(self, grads, state, params, **kw):  # pragma: no cover - abstract
        raise NotImplementedError

    def step(self, grads, state, params, **kw):
        """Alias matching the reference's ``optimizer.step()`` naming."""
        return self.update(grads, state, params, **kw)
