"""FusedNovoGrad — NovoGrad with per-tensor second moments.

Reference: ``apex/optimizers/fused_novograd.py`` and
``csrc/multi_tensor_novograd.cu`` (NovoGradFunctor:33-127, host:129-190).

The second moment is one *scalar per tensor*: a blended norm
``gn = sqrt(β2·gn² + (1-β2)·‖g‖²)`` (L2, ``norm_type=2``) or
``gn = β2·gn + (1-β2)·max|g|`` (L-inf, ``norm_type=0``), updated by
``multi_tensor_norm_out_cuda`` before the elementwise functor.  Initial
value: zero (``init_zero=True``) or the first grad's norm so the first
blend is a no-op (default).

Elementwise (fp32), with ``denom = gn/√(1-β2^t) + eps``:
- ``reg_inside_moment=True`` (MOMENT_MODE_0): ``g' = g/denom + wd·p``;
  ``m = β1·m + β3·g'``; ``p -= lr·m̂``.
- default (MOMENT_MODE_1): ``m = β1·m + β3·g``;
  ``p -= lr·(m̂/denom + wd·p)``.

Note ``bias_correction2 = sqrt(1-β2^t)`` here (unlike Adam) —
``multi_tensor_novograd.cu:150-152``.

Runs on the bucketed multi-tensor engine by default (see
:mod:`apex_tpu.optimizers.base`): the per-tensor norms read the grad
bucket through the plan's offset table; ``exp_avg_sq`` stays a tree of
per-leaf scalars in both layouts (it is one float per tensor).
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base, bucketing


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any  # list-like tree of scalar norms, one per leaf
    master: Optional[Any] = None


class FusedNovoGrad(base.OptimizerBase):

    _BUCKET_SLOT = "exp_avg"

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        reg_inside_moment: bool = False,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        master_weights: bool = False,
        use_buckets: bool = True,
    ):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type not in (0, 2):
            raise RuntimeError("FusedNovoGrad only supports l2/inf norm.")
        super().__init__(lr, weight_decay, master_weights,
                         use_buckets=use_buckets)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        # moment_mode mirrors fused_novograd.py:89
        self.moment_mode = 0 if reg_inside_moment else 1
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero

    def init(self, params, bucketed: bool = False) -> NovoGradState:
        # -1 sentinel: "not yet initialized"; replaced by the first
        # grad norm unless init_zero (fused_novograd.py:160-180).
        gn0 = jax.tree.map(
            lambda p: jnp.float32(0.0 if self.init_zero else -1.0), params
        )
        if bucketed:
            (m,), master = self._init_bucket_slots(params, 1)
            return NovoGradState(jnp.int32(0), m, gn0, master)
        return NovoGradState(
            step=jnp.int32(0),
            exp_avg=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            exp_avg_sq=gn0,
            master=base.make_master(params, self.master_weights),
        )

    def _norm(self, g32):
        if self.norm_type == 2:
            return jnp.sqrt(jnp.sum(jnp.square(g32)))
        return jnp.max(jnp.abs(g32))

    def _blend(self, gn, fresh):
        """Norm blend (multi_tensor_novograd.cu:160-164) with the -1
        lazy-init sentinel resolved."""
        gn0 = jnp.where(gn < 0, fresh, gn)
        if self.norm_type == 2:
            return jnp.sqrt(self.beta2 * jnp.square(gn0)
                            + (1.0 - self.beta2) * jnp.square(fresh))
        return self.beta2 * gn0 + (1.0 - self.beta2) * fresh

    def _bias_corrections(self, step):
        t = step.astype(jnp.float32)
        if self.bias_correction:
            return (1.0 - jnp.power(self.beta1, t),
                    jnp.sqrt(1.0 - jnp.power(self.beta2, t)))
        return jnp.float32(1.0), jnp.float32(1.0)

    def _moment_math(self, g, p32, m, denom, lr, bc1):
        """Shared elementwise tail (per-leaf == bucket); ``denom`` is a
        per-element operand (broadcast per-tensor norm)."""
        b1, wd = self.beta1, self.weight_decay
        b3 = (1.0 - b1) if self.grad_averaging else 1.0
        if self.moment_mode == 0:
            gp = g / denom + wd * p32
            m_new = b1 * m + b3 * gp
            p_out = p32 - lr * (m_new / bc1)
        else:
            m_new = b1 * m + b3 * g
            update = (m_new / bc1) / denom + wd * p32
            p_out = p32 - lr * update
        return p_out, m_new

    # ------------------------------------------------------- per-leaf path
    def _leaf_update(self, grads, state: NovoGradState, params,
                     grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr

        step = base.predicate_step(grads_finite, state.step)
        bc1, bc2 = self._bias_corrections(step)
        p_math = base.math_params(params, state.master)

        def one(g, p, m, gn):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            gn_new = self._blend(gn, self._norm(g))
            denom = gn_new / bc2 + self.eps
            p_out, m_new = self._moment_math(g, p32, m, denom, lr, bc1)
            return p_out, m_new, gn_new

        out = jax.tree.map(one, grads, p_math, state.exp_avg, state.exp_avg_sq)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        m_new = jax.tree.unflatten(treedef, [x[1] for x in flat])
        gn_new = jax.tree.unflatten(treedef, [x[2] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        m_new = base.select(grads_finite, m_new, state.exp_avg)
        gn_new = base.select(grads_finite, gn_new, state.exp_avg_sq)

        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, NovoGradState(step, m_new, gn_new, new_master)

    # --------------------------------------------------------- bucket path
    def _bucket_update(self, prep: base.PreparedGrads, state: NovoGradState,
                       params, pred, lr=None):
        lr = self.lr if lr is None else lr
        plan = prep.plan

        step = base.predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)

        m_b, resident = self._slot_buckets(plan, state.exp_avg)
        has_master = state.master is not None
        if has_master:
            p_b, _ = self._slot_buckets(plan, state.master)
        else:
            p_b = bucketing.pack(plan, params)

        # per-tensor fresh norms + blend: one read of the grad bucket
        # through the offset table, exactly the per-leaf reduction order
        fresh = bucketing.per_leaf_reduce(plan, prep.g, self._norm)
        gn_leaves = jax.tree.leaves(state.exp_avg_sq)
        gn_new_leaves = [self._blend(gn, f)
                         for gn, f in zip(gn_leaves, fresh)]
        denoms = [gn / bc2 + self.eps for gn in gn_new_leaves]

        new_p, new_m = [], []
        for bi, b in enumerate(plan.buckets):
            denom = bucketing.seg_broadcast(b, denoms)
            # pad elements would divide by the pad's 0-denominator;
            # keep them finite so a bucket-level isfinite stays usable.
            # Mask by PAD POSITION, not by value: a real leaf can have
            # denom 0 too (eps=0 + zero grads) and must keep the
            # per-leaf path's NaN there — the two paths may not
            # silently disagree.
            if b.pad:
                is_pad = jnp.arange(b.total) >= b.size
                denom = jnp.where(is_pad, jnp.float32(1.0), denom)
            p_out, m_out = self._moment_math(
                prep.g[bi], p_b[bi], m_b[bi], denom, lr, bc1)
            new_p.append(p_out)
            new_m.append(m_out)

        new_p = base.bucket_select(pred, new_p, p_b)
        new_m = base.bucket_select(pred, new_m, m_b)
        if pred is not None:
            w = jnp.asarray(pred)
            gn_new_leaves = [jnp.where(w, n, o)
                             for n, o in zip(gn_new_leaves, gn_leaves)]
        gn_new = jax.tree.unflatten(
            jax.tree.structure(state.exp_avg_sq), gn_new_leaves)

        new_params = bucketing.unpack(plan, new_p)
        new_master = (self._emit_slot(plan, new_p, resident)
                      if has_master else None)
        return new_params, NovoGradState(
            step, self._emit_slot(plan, new_m, resident), gn_new, new_master)
