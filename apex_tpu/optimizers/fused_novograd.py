"""FusedNovoGrad — NovoGrad with per-tensor second moments.

Reference: ``apex/optimizers/fused_novograd.py`` and
``csrc/multi_tensor_novograd.cu`` (NovoGradFunctor:33-127, host:129-190).

The second moment is one *scalar per tensor*: a blended norm
``gn = sqrt(β2·gn² + (1-β2)·‖g‖²)`` (L2, ``norm_type=2``) or
``gn = β2·gn + (1-β2)·max|g|`` (L-inf, ``norm_type=0``), updated by
``multi_tensor_norm_out_cuda`` before the elementwise functor.  Initial
value: zero (``init_zero=True``) or the first grad's norm so the first
blend is a no-op (default).

Elementwise (fp32), with ``denom = gn/√(1-β2^t) + eps``:
- ``reg_inside_moment=True`` (MOMENT_MODE_0): ``g' = g/denom + wd·p``;
  ``m = β1·m + β3·g'``; ``p -= lr·m̂``.
- default (MOMENT_MODE_1): ``m = β1·m + β3·g``;
  ``p -= lr·(m̂/denom + wd·p)``.

Note ``bias_correction2 = sqrt(1-β2^t)`` here (unlike Adam) —
``multi_tensor_novograd.cu:150-152``.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any  # list-like tree of scalar norms, one per leaf
    master: Optional[Any] = None


class FusedNovoGrad(base.OptimizerBase):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        reg_inside_moment: bool = False,
        grad_averaging: bool = True,
        norm_type: int = 2,
        init_zero: bool = False,
        master_weights: bool = False,
    ):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type not in (0, 2):
            raise RuntimeError("FusedNovoGrad only supports l2/inf norm.")
        super().__init__(lr, weight_decay, master_weights)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        # moment_mode mirrors fused_novograd.py:89
        self.moment_mode = 0 if reg_inside_moment else 1
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero

    def init(self, params) -> NovoGradState:
        return NovoGradState(
            step=jnp.int32(0),
            exp_avg=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params),
            # -1 sentinel: "not yet initialized"; replaced by the first
            # grad norm unless init_zero (fused_novograd.py:160-180).
            exp_avg_sq=jax.tree.map(
                lambda p: jnp.float32(0.0 if self.init_zero else -1.0), params
            ),
            master=base.make_master(params, self.master_weights),
        )

    def _norm(self, g32):
        if self.norm_type == 2:
            return jnp.sqrt(jnp.sum(jnp.square(g32)))
        return jnp.max(jnp.abs(g32))

    def update(self, grads, state: NovoGradState, params, grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        b3 = (1.0 - b1) if self.grad_averaging else 1.0

        step = base.predicate_step(grads_finite, state.step)
        t = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - jnp.power(b1, t)
            bc2 = jnp.sqrt(1.0 - jnp.power(b2, t))
        else:
            bc1 = bc2 = jnp.float32(1.0)

        p_math = base.math_params(params, state.master)

        def one(g, p, m, gn):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            fresh = self._norm(g)
            # lazily init norm to the first step's norm (-1 sentinel)
            gn0 = jnp.where(gn < 0, fresh, gn)
            if self.norm_type == 2:
                gn_new = jnp.sqrt(b2 * jnp.square(gn0) + (1.0 - b2) * jnp.square(fresh))
            else:
                gn_new = b2 * gn0 + (1.0 - b2) * fresh
            denom = gn_new / bc2 + eps
            if self.moment_mode == 0:
                gp = g / denom + wd * p32
                m_new = b1 * m + b3 * gp
                p_out = p32 - lr * (m_new / bc1)
            else:
                m_new = b1 * m + b3 * g
                update = (m_new / bc1) / denom + wd * p32
                p_out = p32 - lr * update
            return p_out, m_new, gn_new

        out = jax.tree.map(one, grads, p_math, state.exp_avg, state.exp_avg_sq)
        treedef = jax.tree.structure(grads)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        m_new = jax.tree.unflatten(treedef, [x[1] for x in flat])
        gn_new = jax.tree.unflatten(treedef, [x[2] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        m_new = base.select(grads_finite, m_new, state.exp_avg)
        gn_new = base.select(grads_finite, gn_new, state.exp_avg_sq)

        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, NovoGradState(step, m_new, gn_new, new_master)
