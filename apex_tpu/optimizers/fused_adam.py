"""FusedAdam — Adam/AdamW with exact reference numerics.

Reference: ``apex/optimizers/fused_adam.py:4-276`` (driver) and
``csrc/multi_tensor_adam.cu`` (AdamFunctor :24, AdamCapturableFunctor
:130, AdamCapturableMasterFunctor :243).

Numerics (MATH_T = fp32, per element):

- L2 mode (``adam_w_mode=False``, ADAM_MODE_0): ``g += wd*p`` before the
  moment updates.
- AdamW mode (default, ADAM_MODE_1): ``update = m̂/(sqrt(v̂)+eps) + wd*p``.
- ``m̂ = m/(1-β1^t)``, ``v̂ = v/(1-β2^t)`` when ``bias_correction``.

The capturable behavior is default here: pass ``grads_finite`` (from
:meth:`apex_tpu.amp.DynamicLossScaler.unscale`) and the whole step —
including the step counter — commits only when grads are finite, exactly
like the reference's device-side noop_flag path.

The update runs on the bucketed multi-tensor engine by default
(``use_buckets=True``; see :mod:`apex_tpu.optimizers.base`): one fused
elementwise pass per dtype bucket, bit-exact in fp32 with both the
per-leaf path and ``optax.adamw`` (the second-moment update is
``(1-β2)·(g·g)``, optax's association).  ``init(params, bucketed=True)``
stores m/v (and the fp32 master) as flat bucket buffers that ride the
jit boundary directly — ``donate_argnums`` then donates the bucket
buffers themselves.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base, bucketing


class AdamState(NamedTuple):
    step: jnp.ndarray  # i32 scalar
    exp_avg: Any  # m, fp32 (tree or Buckets)
    exp_avg_sq: Any  # v, fp32 (tree or Buckets)
    master: Optional[Any] = None  # fp32 master params (if enabled)


def adam_core(g, m, v, bc1, bc2, *, beta1, beta2, eps):
    """The param-free half of the Adam expression tree: new moments and
    the core update term ``m̂/(sqrt(v̂)+eps)``.  Module-level so the
    ZeRO-sharded :class:`~apex_tpu.contrib.optimizers.
    DistributedFusedAdam` evaluates the IDENTICAL expressions on its dp
    shards (the bit-exact-parity contract), and factored away from the
    params so the engine's pack-free emit can apply ``wd``/``lr`` per
    original leaf without materializing a param bucket."""
    m_new = beta1 * m + (1.0 - beta1) * g
    # (1-β2)·(g·g): optax's association, pinned for bit-exact parity
    v_new = beta2 * v + (1.0 - beta2) * (g * g)
    core = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    return core, m_new, v_new


def adam_math(g, p32, m, v, wd_i, lr_i, bc1, bc2, *, beta1, beta2, eps,
              adam_w_mode):
    """One Adam step per element (AdamW ADAM_MODE_1 / L2 ADAM_MODE_0) —
    the numerics specification every path (per-leaf, bucket, ZeRO
    shard) shares verbatim, so they cannot drift even by a rounding."""
    if not adam_w_mode:  # ADAM_MODE_0: L2 regularization
        g = g + wd_i * p32
    core, m_new, v_new = adam_core(g, m, v, bc1, bc2,
                                   beta1=beta1, beta2=beta2, eps=eps)
    update = core + wd_i * p32 if adam_w_mode else core
    return p32 - lr_i * update, m_new, v_new


class FusedAdam(base.OptimizerBase):

    _BUCKET_SLOT = "exp_avg"

    #: True restores the pre-fix engine emit (param bucket pack +
    #: unpack) — kept ONLY so ``bench.py`` can time the BENCH_r05
    #: 0.679× path against the pack-free emit in the same run (the
    #: before/after drift evidence); never set in training code.
    _pack_params_emit = False

    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
        use_buckets: bool = True,
    ):
        """``param_group_fn(path, leaf) -> group_name`` +
        ``group_hypers={name: {"lr": ..., "weight_decay": ...}}`` is the
        functional form of the reference's ``param_groups`` (per-group
        hyperparameters, e.g. no weight decay on norms/biases)."""
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(lr, weight_decay, master_weights,
                         use_buckets=use_buckets)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params, bucketed: bool = False) -> AdamState:
        if bucketed:
            (m, v), master = self._init_bucket_slots(params, 2)
            return AdamState(jnp.int32(0), m, v, master)
        zeros = lambda t: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), t)
        return AdamState(
            step=jnp.int32(0),
            exp_avg=zeros(params),
            exp_avg_sq=zeros(params),
            master=base.make_master(params, self.master_weights),
        )

    def _adam_math(self, g, p32, m, v, wd_i, lr_i, bc1, bc2):
        """The one Adam expression tree — shared verbatim by the
        per-leaf and bucket paths (elementwise code is shape-blind), so
        the two cannot drift even by a rounding."""
        return adam_math(g, p32, m, v, wd_i, lr_i, bc1, bc2,
                         beta1=self.beta1, beta2=self.beta2, eps=self.eps,
                         adam_w_mode=self.adam_w_mode)

    # ------------------------------------------------------- per-leaf path
    def _leaf_update(self, grads, state: AdamState, params,
                     grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        wd = self.weight_decay

        step = base.predicate_step(grads_finite, state.step)
        bc1, bc2 = self._bias_corrections(step)
        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers)

        def one(g, p, m, v, h):
            return self._adam_math(
                g.astype(jnp.float32), p.astype(jnp.float32), m, v,
                h.get("weight_decay", wd), base.leaf_lr(h, lr), bc1, bc2)

        treedef = jax.tree.structure(grads)
        # tree.map validates all five trees share grads' structure
        out = jax.tree.map(one, grads, p_math, state.exp_avg, state.exp_avg_sq, hypers)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        m_new = jax.tree.unflatten(treedef, [x[1] for x in flat])
        v_new = jax.tree.unflatten(treedef, [x[2] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        m_new = base.select(grads_finite, m_new, state.exp_avg)
        v_new = base.select(grads_finite, v_new, state.exp_avg_sq)

        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, AdamState(step, m_new, v_new, new_master)

    # --------------------------------------------------------- bucket path
    def _bucket_update_packfree(self, prep: base.PreparedGrads,
                                state: AdamState, params, pred, lr):
        """The BENCH_r05 0.679× fix.  Profiling the resident-bucket
        step against jitted optax ruled OUT the dispute's named
        suspects — no per-leaf norm reconstruction runs in a plain Adam
        step, the noop-flag OR only exists under a finite vote, and the
        tail pad is <0.1% of the bucket — and pinned the gap on the
        param round-trip: ``pack(params)`` concatenates every leaf into
        a bucket XLA materializes, and ``unpack`` writes it all back —
        two whole-model HBM passes per step the optax baseline never
        pays.  With no fp32 master and decoupled decay (AdamW), the
        bucket math only needs the GRADS in bucket form: m/v/core are
        computed per bucket (:func:`adam_core`), then each param leaf
        is emitted directly from its static core slice — slice +
        elementwise fuse, and no param bucket exists in the HLO.
        Bit-exact with the packed path (identical expressions per
        element; only the layout of the param read changed)."""
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = prep.plan
        step = base.predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)
        m_b, resident = self._slot_buckets(plan, state.exp_avg)
        v_b, _ = self._slot_buckets(plan, state.exp_avg_sq)
        hl = self._hyper_leaves(
            base.leaf_hypers(params, self.param_group_fn, self.group_hypers))

        cores, new_m, new_v = [], [], []
        for bi, b in enumerate(plan.buckets):
            core, m_out, v_out = adam_core(
                prep.g[bi], m_b[bi], v_b[bi], bc1, bc2,
                beta1=self.beta1, beta2=self.beta2, eps=self.eps)
            cores.append(core)
            new_m.append(m_out)
            new_v.append(v_out)
        new_m = base.bucket_select(pred, new_m, m_b)
        new_v = base.bucket_select(pred, new_v, v_b)

        leaves = jax.tree.leaves(params)
        new_leaves = [None] * plan.n_leaves
        for bi, b in enumerate(plan.buckets):
            for bl in b.leaves:
                p32 = leaves[bl.leaf_id].astype(jnp.float32)
                u = jax.lax.slice(
                    cores[bi], (bl.offset,), (bl.offset + bl.size,)
                ).reshape(bl.shape)
                h = hl[bl.leaf_id]
                p_new = p32 - base.leaf_lr(h, lr) * (
                    u + h.get("weight_decay", wd) * p32)
                if pred is not None:
                    p_new = jnp.where(jnp.asarray(pred), p_new, p32)
                new_leaves[bl.leaf_id] = p_new.astype(leaves[bl.leaf_id].dtype)
        new_params = jax.tree.unflatten(plan.treedef, new_leaves)
        return new_params, AdamState(
            step,
            self._emit_slot(plan, new_m, resident),
            self._emit_slot(plan, new_v, resident),
            None,
        )

    def _bucket_update(self, prep: base.PreparedGrads, state: AdamState,
                       params, pred, lr=None):
        if (state.master is None and self.adam_w_mode
                and not self._pack_params_emit):
            return self._bucket_update_packfree(prep, state, params, pred, lr)
        lr = self.lr if lr is None else lr
        wd = self.weight_decay
        plan = prep.plan

        step = base.predicate_step(pred, state.step)
        bc1, bc2 = self._bias_corrections(step)

        m_b, resident = self._slot_buckets(plan, state.exp_avg)
        v_b, _ = self._slot_buckets(plan, state.exp_avg_sq)
        has_master = state.master is not None
        if has_master:
            p_b, _ = self._slot_buckets(plan, state.master)
        else:
            p_b = bucketing.pack(plan, params)
        hl = self._hyper_leaves(
            base.leaf_hypers(params, self.param_group_fn, self.group_hypers))
        wd_leaf = [h.get("weight_decay", wd) for h in hl]

        new_p, new_m, new_v = [], [], []
        for bi, b in enumerate(plan.buckets):
            p_out, m_out, v_out = self._adam_math(
                prep.g[bi], p_b[bi], m_b[bi], v_b[bi],
                bucketing.seg_values(b, wd_leaf),
                self._bucket_lr(b, hl, lr), bc1, bc2)
            new_p.append(p_out)
            new_m.append(m_out)
            new_v.append(v_out)

        new_p = base.bucket_select(pred, new_p, p_b)
        new_m = base.bucket_select(pred, new_m, m_b)
        new_v = base.bucket_select(pred, new_v, v_b)

        new_params = bucketing.unpack(plan, new_p)
        new_master = (self._emit_slot(plan, new_p, resident)
                      if has_master else None)
        return new_params, AdamState(
            step,
            self._emit_slot(plan, new_m, resident),
            self._emit_slot(plan, new_v, resident),
            new_master,
        )
