"""FusedAdam — Adam/AdamW with exact reference numerics.

Reference: ``apex/optimizers/fused_adam.py:4-276`` (driver) and
``csrc/multi_tensor_adam.cu`` (AdamFunctor :24, AdamCapturableFunctor
:130, AdamCapturableMasterFunctor :243).

Numerics (MATH_T = fp32, per element):

- L2 mode (``adam_w_mode=False``, ADAM_MODE_0): ``g += wd*p`` before the
  moment updates.
- AdamW mode (default, ADAM_MODE_1): ``update = m̂/(sqrt(v̂)+eps) + wd*p``.
- ``m̂ = m/(1-β1^t)``, ``v̂ = v/(1-β2^t)`` when ``bias_correction``.

The capturable behavior is default here: pass ``grads_finite`` (from
:meth:`apex_tpu.amp.DynamicLossScaler.unscale`) and the whole step —
including the step counter — commits only when grads are finite, exactly
like the reference's device-side noop_flag path.
"""

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import base


class AdamState(NamedTuple):
    step: jnp.ndarray  # i32 scalar
    exp_avg: Any  # m, fp32
    exp_avg_sq: Any  # v, fp32
    master: Optional[Any] = None  # fp32 master params (if enabled)


class FusedAdam(base.OptimizerBase):
    def __init__(
        self,
        lr: float = 1e-3,
        bias_correction: bool = True,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        adam_w_mode: bool = True,
        weight_decay: float = 0.0,
        amsgrad: bool = False,
        master_weights: bool = False,
        param_group_fn=None,
        group_hypers=None,
    ):
        """``param_group_fn(path, leaf) -> group_name`` +
        ``group_hypers={name: {"lr": ..., "weight_decay": ...}}`` is the
        functional form of the reference's ``param_groups`` (per-group
        hyperparameters, e.g. no weight decay on norms/biases)."""
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        super().__init__(lr, weight_decay, master_weights)
        self.bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.param_group_fn = param_group_fn
        self.group_hypers = group_hypers

    def init(self, params) -> AdamState:
        zeros = lambda t: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), t)
        return AdamState(
            step=jnp.int32(0),
            exp_avg=zeros(params),
            exp_avg_sq=zeros(params),
            master=base.make_master(params, self.master_weights),
        )

    def update(self, grads, state: AdamState, params, grads_finite=None, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay

        step = base.predicate_step(grads_finite, state.step)
        t = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - jnp.power(b1, t)
            bc2 = 1.0 - jnp.power(b2, t)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        p_math = base.math_params(params, state.master)
        hypers = base.leaf_hypers(params, self.param_group_fn, self.group_hypers)

        def one(g, p, m, v, h):
            wd_i = h.get("weight_decay", wd)
            lr_i = base.leaf_lr(h, lr)
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode:  # ADAM_MODE_0: L2 regularization
                g = g + wd_i * p32
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * g * g
            denom = jnp.sqrt(v_new / bc2) + eps
            update = (m_new / bc1) / denom
            if self.adam_w_mode:  # ADAM_MODE_1: decoupled weight decay
                update = update + wd_i * p32
            return p32 - lr_i * update, m_new, v_new

        treedef = jax.tree.structure(grads)
        # tree.map validates all five trees share grads' structure
        out = jax.tree.map(one, grads, p_math, state.exp_avg, state.exp_avg_sq, hypers)
        flat = jax.tree.leaves(out, is_leaf=lambda x: isinstance(x, tuple))
        p_new = jax.tree.unflatten(treedef, [x[0] for x in flat])
        m_new = jax.tree.unflatten(treedef, [x[1] for x in flat])
        v_new = jax.tree.unflatten(treedef, [x[2] for x in flat])

        p_new = base.select(grads_finite, p_new, p_math)
        m_new = base.select(grads_finite, m_new, state.exp_avg)
        v_new = base.select(grads_finite, v_new, state.exp_avg_sq)

        new_params, new_master = base.emit_params(p_new, params, state.master)
        return new_params, AdamState(step, m_new, v_new, new_master)
