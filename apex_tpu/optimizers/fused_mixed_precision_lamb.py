"""FusedMixedPrecisionLamb — LAMB with device-resident hyperparams and
fp32 master weights.

Reference: ``apex/optimizers/fused_mixed_precision_lamb.py:8`` — the
fully-capturable LAMB variant (``multi_tensor_lamb_mp.cu``): lr/step live
on device as tensors, model params are half with fp32 masters, and the
step is predicated on the overflow flag.

In apex_tpu every optimizer already has those properties (state is a
device pytree, ``lr`` may be a traced scalar, ``grads_finite`` predicates
the commit), so this is :class:`~apex_tpu.optimizers.FusedLAMB` with
``master_weights=True`` by default.  Kept as its own class for API parity.
"""

from apex_tpu.optimizers.fused_lamb import FusedLAMB


class FusedMixedPrecisionLamb(FusedLAMB):
    def __init__(self, *args, master_weights: bool = True, **kwargs):
        kwargs["master_weights"] = master_weights
        super().__init__(*args, **kwargs)
