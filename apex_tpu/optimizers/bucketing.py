"""Bucket plans: the TPU-native form of ``multi_tensor_apply``.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py`` +
``csrc/multi_tensor_apply.cuh``.  The reference packs ≤110 tensor
pointers and a chunk table into kernel-launch metadata so one CUDA
launch sweeps many tensors.  The XLA analogue is a **bucket plan**:
at optimizer init (or at first trace) the param pytree is flattened in
stable ``tree_flatten`` order into a few dtype-homogeneous 1-D buckets
— per-leaf offset table, tail padded to the dtype's (sublane × 128)
tile (``ops/_pallas_tiling``) — and every optimizer sweep becomes one
fused elementwise pass per bucket instead of one op chain per leaf.
The layout is also the prerequisite for cross-replica sharded weight
updates (PAPERS: arXiv 2004.13336): an equal-size 1-D bucket is what a
``psum_scatter`` shards cleanly.

Two ways to use a plan:

- **transparent** (default inside the fused optimizers): ``update``
  packs grads/params/state into buckets per call and unpacks the
  results — state pytrees keep their per-leaf shape, so sharding specs,
  checkpoints, and oracle tests are unaffected.
- **resident** (``opt.init(params, bucketed=True)``): the optimizer
  state slots are stored as :class:`Buckets` — the flat buffers ride
  the jit boundary directly, so ``donate_argnums`` donates the bucket
  buffers themselves (m/v never leave bucket form between steps).
  Requires an unsharded (single-replica or pure-dp) step: a bucket of
  concatenated *global* leaves does not slice into per-rank buckets of
  the leaf *shards*, so ``make_train_step``-style shard_map states stay
  per-leaf.

The ZeRO optimizers (``contrib.optimizers``) build their plans with two
extra knobs: ``shard_pad`` pads every bucket so it splits evenly into
``dp`` tile-aligned shards (the layout a per-bucket ``psum_scatter``
scatters cleanly), and ``cap_bytes`` (the reference's ``bucket_cap_mb``)
splits an oversized dtype bucket into several collective-sized buckets
at leaf granularity — each bucket then gets its own reduce-scatter /
all-gather, which is what lets XLA's latency-hiding scheduler overlap
one bucket's collective with another's math (and, inside a train step,
with the remaining backward).
"""

import dataclasses
import functools
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.ops._pallas_tiling import LANES, sublane

Tree = Any

__all__ = [
    "BucketLeaf", "BucketSpec", "BucketPlan", "Buckets", "plan_of",
    "plan_of_shapes", "padded_total", "pack", "pack_bucket", "unpack",
    "per_leaf_reduce", "seg_values", "seg_broadcast", "seg_ids",
    "buckets_by_stage",
]


@dataclasses.dataclass(frozen=True)
class BucketLeaf:
    """One leaf's slot inside a bucket."""

    leaf_id: int          # position in tree_flatten order
    shape: Tuple[int, ...]
    offset: int           # element offset into the bucket

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """One dtype-homogeneous bucket: leaves back-to-back, padded tail."""

    dtype: str            # canonical storage dtype name (e.g. "float32")
    leaves: Tuple[BucketLeaf, ...]
    size: int             # payload elements (sum of leaf sizes)
    total: int            # padded length: size rounded up to the tile

    @property
    def pad(self) -> int:
        return self.total - self.size


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The static layout: which leaf lives where.  Hashable (jit-cache
    friendly) and buildable from shapes alone — no arrays are held."""

    treedef: Any
    leaf_dtypes: Tuple[str, ...]          # storage dtype per leaf
    buckets: Tuple[BucketSpec, ...]

    @property
    def n_leaves(self) -> int:
        return len(self.leaf_dtypes)

    def __hash__(self):
        return hash((self.treedef, self.leaf_dtypes, self.buckets))


def _tile(dtype_name: str) -> int:
    """Pad-to size: the dtype's (sublane × 128) VMEM tile in elements."""
    return sublane(jnp.dtype(dtype_name)) * LANES


def padded_total(size: int, dtype_name: str, shard_pad: int = 1) -> int:
    """The bucket length for ``size`` payload elements: rounded up to
    the dtype tile × ``shard_pad``, so every 1/shard_pad shard is itself
    tile-aligned.  The ONE padding formula — the plan builder and the
    ZeRO checkpoint resharder (which re-pads a saved payload for a new
    world size) must agree or a resumed state silently misaligns."""
    unit = _tile(dtype_name) * max(1, int(shard_pad))
    return ((size + unit - 1) // unit) * unit if size else 0


@functools.lru_cache(maxsize=64)
def _plan_from_key(treedef, shapes_dtypes, cap_bytes=None,
                   shard_pad=1) -> BucketPlan:
    by_dtype: dict = {}
    order: List[str] = []  # first-appearance bucket order, deterministic
    for i, (shape, dt) in enumerate(shapes_dtypes):
        if dt not in by_dtype:
            by_dtype[dt] = []
            order.append(dt)
        by_dtype[dt].append((i, shape))
    buckets = []
    for dt in order:
        cap = None
        if cap_bytes is not None:
            # cap in elements of THIS dtype; at least one tile so a cap
            # smaller than the alignment unit still makes progress
            cap = max(int(cap_bytes) // jnp.dtype(dt).itemsize, _tile(dt))
        groups: List[List] = [[]]
        off = 0
        for i, shape in by_dtype[dt]:
            n = int(np.prod(shape)) if shape else 1
            # split at LEAF granularity (the reference splits params into
            # fragments; a leaf spanning buckets would break the static
            # per-leaf offset table every norm/unpack path slices by, so
            # an over-cap leaf gets a bucket of its own instead)
            if cap is not None and off and off + n > cap:
                groups.append([])
                off = 0
            groups[-1].append((i, shape, off))
            off += n
        for group in groups:
            if not group:
                continue
            leaves = tuple(BucketLeaf(leaf_id=i, shape=shape, offset=o)
                           for i, shape, o in group)
            size = sum(bl.size for bl in leaves)
            buckets.append(BucketSpec(
                dtype=dt, leaves=leaves, size=size,
                total=padded_total(size, dt, shard_pad)))
    return BucketPlan(
        treedef=treedef,
        leaf_dtypes=tuple(dt for _, dt in shapes_dtypes),
        buckets=tuple(buckets),
    )


def plan_of(tree: Tree, cap_bytes: Optional[int] = None,
            shard_pad: int = 1) -> BucketPlan:
    """The bucket plan for ``tree``'s (treedef, shapes, dtypes) — cached,
    so repeated traces of the same step reuse one plan object.

    ``cap_bytes`` splits oversized dtype buckets at leaf granularity
    (the reference's ``bucket_cap_mb``); ``shard_pad`` pads each bucket
    to split evenly into that many tile-aligned shards (the ZeRO dp
    shard count)."""
    leaves, treedef = jax.tree.flatten(tree)
    key = tuple((tuple(x.shape), jnp.dtype(x.dtype).name) for x in leaves)
    return _plan_from_key(treedef, key, cap_bytes, shard_pad)


def plan_of_shapes(treedef, shapes_dtypes: Sequence[Tuple[Tuple[int, ...], str]],
                   cap_bytes: Optional[int] = None,
                   shard_pad: int = 1) -> BucketPlan:
    """:func:`plan_of` from ``(shape, dtype_name)`` pairs alone — the
    ZeRO ``init`` path builds the plan for the LOCAL (model-sharded)
    leaf shapes before any local array exists."""
    return _plan_from_key(treedef, tuple(
        (tuple(s), str(d)) for s, d in shapes_dtypes), cap_bytes, shard_pad)


class Buckets:
    """A tree of 1-D bucket buffers + its plan, registered as a pytree
    (children = the buffers, aux = the plan).  ``jax.tree.map`` over a
    ``Buckets`` maps over the buffers, so the amp scaler, ``clip_grad``,
    and the ``multi_tensor_*`` ops all operate on bucket views with no
    special cases."""

    __slots__ = ("plan", "arrays")

    def __init__(self, plan: BucketPlan, arrays: Sequence):
        self.plan = plan
        self.arrays = tuple(arrays)

    def __repr__(self):
        shapes = [getattr(a, "shape", ()) for a in self.arrays]
        return f"Buckets({[b.dtype for b in self.plan.buckets]}, {shapes})"

    def unpack(self, dtype=None) -> Tree:
        """Back to the per-leaf tree (storage dtypes, or ``dtype``)."""
        return unpack(self.plan, self.arrays, dtype=dtype)


jax.tree_util.register_pytree_node(
    Buckets,
    lambda b: (b.arrays, b.plan),
    lambda plan, arrays: Buckets(plan, arrays),
)


def pack_bucket(bucket: BucketSpec, leaves: Sequence, dtype=jnp.float32,
                scale=None) -> jnp.ndarray:
    """ONE bucket's flat concat from the tree_flatten ``leaves``, cast
    to ``dtype``, optional scalar multiply fused in, zero-padded tail —
    the per-bucket unit both :func:`pack` and the ZeRO/quantized sync
    paths read grads through (per-bucket and in the sync dtype, never a
    whole-tree flatten)."""
    parts = [jnp.ravel(leaves[bl.leaf_id]).astype(dtype)
             for bl in bucket.leaves]
    arr = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if scale is not None:
        arr = arr * jnp.asarray(scale, dtype)
    if bucket.pad:
        arr = jnp.pad(arr, (0, bucket.pad))
    return arr


def pack(plan: BucketPlan, tree: Tree, dtype=jnp.float32,
         scale=None) -> List[jnp.ndarray]:
    """Flatten ``tree`` into ``plan``'s buckets, cast to the math dtype,
    with an optional scalar multiply (the loss-scale unscale) fused into
    the same pass.  Padding is zero-filled, so an all-finite vote over a
    packed bucket is exactly the vote over the leaves."""
    leaves = jax.tree.leaves(tree)
    if len(leaves) != plan.n_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves; plan expects {plan.n_leaves}")
    return [pack_bucket(b, leaves, dtype, scale=scale)
            for b in plan.buckets]


def unpack(plan: BucketPlan, arrays: Sequence, dtype=None) -> Tree:
    """Slice the buckets back into the per-leaf tree.  ``dtype=None``
    casts each leaf to its storage dtype from the plan; pass
    ``jnp.float32`` for fp32 state slots."""
    leaves: List[Optional[jnp.ndarray]] = [None] * plan.n_leaves
    for b, arr in zip(plan.buckets, arrays):
        for bl in b.leaves:
            dt = dtype if dtype is not None else plan.leaf_dtypes[bl.leaf_id]
            leaves[bl.leaf_id] = jax.lax.slice(
                arr, (bl.offset,), (bl.offset + bl.size,)
            ).reshape(bl.shape).astype(dt)
    return jax.tree.unflatten(plan.treedef, leaves)


def per_leaf_reduce(plan: BucketPlan, arrays: Sequence,
                    fn: Callable) -> List[jnp.ndarray]:
    """``fn`` over each leaf's flat slice, returned in tree_flatten
    order.  This is how per-tensor reductions (LAMB trust ratios,
    NovoGrad norms, per-leaf l2) read a bucket: static slices, so the
    reduction order per leaf matches the per-leaf code path."""
    out: List[Optional[jnp.ndarray]] = [None] * plan.n_leaves
    for b, arr in zip(plan.buckets, arrays):
        for bl in b.leaves:
            out[bl.leaf_id] = fn(
                jax.lax.slice(arr, (bl.offset,), (bl.offset + bl.size,)))
    return out


def seg_values(bucket: BucketSpec, per_leaf: Sequence[float]):
    """Per-element hyperparameter operand for one bucket: a python
    scalar when every leaf agrees (the common case — no per-element
    memory traffic), else an np.float32 constant vector (pad region 0).
    ``per_leaf`` is indexed by ``leaf_id``."""
    vals = [float(per_leaf[bl.leaf_id]) for bl in bucket.leaves]
    if all(v == vals[0] for v in vals):
        return vals[0]
    parts = [np.full(bl.size, v, np.float32)
             for bl, v in zip(bucket.leaves, vals)]
    if bucket.pad:
        parts.append(np.zeros(bucket.pad, np.float32))
    return jnp.asarray(np.concatenate(parts))


def seg_ids(plan: BucketPlan, bucket: BucketSpec) -> np.ndarray:
    """Static leaf-id per element of one bucket (pad → ``n_leaves``
    sentinel): the segment map a dp-scattered shard's per-leaf
    reductions (``segment_sum``) read, since a 1/dp shard does not
    align to leaf boundaries the way :func:`per_leaf_reduce`'s static
    slices need."""
    parts = [np.full(bl.size, bl.leaf_id, np.int32) for bl in bucket.leaves]
    if bucket.pad:
        parts.append(np.full(bucket.pad, plan.n_leaves, np.int32))
    return np.concatenate(parts) if parts else np.zeros(0, np.int32)


def buckets_by_stage(plan: BucketPlan, leaf_stages: Sequence[int],
                     n_stages: int) -> List[List[int]]:
    """Group bucket indices by gradient-readiness stage for the
    backward-overlapped sync: a bucket can only be packed and wired
    once EVERY leaf in it has a cotangent, so its stage is the max of
    its leaves' (``leaf_stages`` indexed by ``leaf_id``).  Each stage's
    list keeps ascending bucket order — the stable (readiness,
    bucket_index) wire order of ``make_train_step(overlap_grad_sync=
    True)``."""
    out: List[List[int]] = [[] for _ in range(n_stages)]
    for bi, b in enumerate(plan.buckets):
        out[max(leaf_stages[bl.leaf_id] for bl in b.leaves)].append(bi)
    return out


def seg_broadcast(bucket: BucketSpec, per_leaf: Sequence):
    """Broadcast traced per-leaf scalars (indexed by ``leaf_id``) to a
    per-element bucket vector via a static-repeats gather (pad = 0)."""
    vals = [per_leaf[bl.leaf_id] for bl in bucket.leaves]
    sizes = [bl.size for bl in bucket.leaves]
    stacked = jnp.stack([jnp.asarray(v, jnp.float32) for v in vals]
                        + [jnp.float32(0.0)])
    reps = np.asarray(sizes + [bucket.pad])
    return jnp.repeat(stacked, reps, total_repeat_length=bucket.total)
