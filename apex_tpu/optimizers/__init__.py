"""Fused optimizers (reference: ``apex/optimizers``).

Each optimizer is a pure pytree transform with exact reference numerics
(fp32 math regardless of storage dtype), device-side predicated updates
(the capturable/noop_flag design), and optional fp32 master weights.

All five run on the bucketed **multi-tensor engine** by default (the
TPU form of ``multi_tensor_apply``): params flatten into a few
dtype-homogeneous 1-D buckets and each step is one fused elementwise
pass per bucket, with loss-scale unscale, global-norm grad clip, and
the all-finite vote folded into the same pass via ``update_scaled``.
See :mod:`apex_tpu.optimizers.bucketing` and ``docs/optimizers.md``.
"""

from apex_tpu.optimizers.bucketing import BucketPlan, Buckets, plan_of
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam
from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, NovoGradState
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState
from apex_tpu.optimizers.fused_mixed_precision_lamb import FusedMixedPrecisionLamb

__all__ = [
    "FusedAdam",
    "AdamState",
    "FusedLAMB",
    "LambState",
    "FusedSGD",
    "SGDState",
    "FusedNovoGrad",
    "NovoGradState",
    "FusedAdagrad",
    "AdagradState",
    "FusedMixedPrecisionLamb",
    "BucketPlan",
    "Buckets",
    "plan_of",
]
