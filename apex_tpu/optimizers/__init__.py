"""Fused optimizers (reference: ``apex/optimizers``).

Each optimizer is a pure pytree transform with exact reference numerics
(fp32 math regardless of storage dtype), device-side predicated updates
(the capturable/noop_flag design), and optional fp32 master weights.
"""

from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam
from apex_tpu.optimizers.fused_adagrad import AdagradState, FusedAdagrad
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad, NovoGradState
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState
from apex_tpu.optimizers.fused_mixed_precision_lamb import FusedMixedPrecisionLamb

__all__ = [
    "FusedAdam",
    "AdamState",
    "FusedLAMB",
    "LambState",
    "FusedSGD",
    "SGDState",
    "FusedNovoGrad",
    "NovoGradState",
    "FusedAdagrad",
    "AdagradState",
    "FusedMixedPrecisionLamb",
]
