"""Runtime lock-order sanitizer — turn an ABBA deadlock into a named
error instead of a silent hang.

The failure class
-----------------
The host side of a training process is genuinely multi-threaded: the
step watchdog, the preemption signal path, the async checkpointer
worker, the supervisor, and the metrics registry all take locks.  Two
locks acquired in opposite orders on two threads deadlock permanently
— and the presentation is the worst one available: not a stack trace
but a wedged pod, often with the watchdog itself a party to the
deadlock and therefore unable to report it (see APX115 in
``apex_tpu.analysis`` for the static tier; this module is the runtime
tier, for the orders the static lock graph cannot see — locks passed
through callbacks, orders that depend on data).

The contract
------------
- :func:`monitored_lock(name) <monitored_lock>` mints a named lock
  (``kind="lock"`` or ``"rlock"``) that behaves exactly like the
  ``threading`` primitive it wraps — zero bookkeeping, a single bool
  check per acquire — until the sanitizer is switched on.
- :func:`instrument_locks` arms the sanitizer (debug/chaos mode): every
  monitored acquire records, per thread, the set of monitored locks
  already held, and merges the (held → acquiring) edges into one
  global acquisition-order graph with the acquiring stack attached.
  The FIRST acquire that closes a cycle — lock A taken under B when
  some earlier acquire anywhere took B under A — raises
  :class:`LockOrderViolation` naming both locks and carrying BOTH
  stacks (the historical one that established A→B and the live one
  attempting B→A).  It raises on the inconsistent ORDER, before the
  unlucky interleaving: the deadlock is caught every run, not one run
  in a thousand.
- Re-entrant acquires of one RLock add no edge (re-entry is not an
  ordering), and edges are keyed by lock NAME, so two processes'
  reports line up.
- :func:`assert_lock_held(lock) <assert_lock_held>` is the acquittal
  seam the static rules recognize (mirroring ``assert_uniform`` for
  the divergence tier): a function whose contract is "my caller holds
  the lock" calls it, which both CHECKS the contract at runtime (when
  the lock is checkable: monitored, or an unwrapped primitive whose
  ``locked()`` is visible) and acquits APX114/APX116 at that site
  statically.

The sanitizer detects ORDER inversions among monitored locks; it does
not detect hold-and-wait cycles through conditions/queues, and locks
never wrapped in :func:`monitored_lock` are invisible to it.
"""

import logging
import threading
import traceback
from typing import Dict, List, Optional, Tuple

from apex_tpu.utils.logging import get_logger, log_structured

logger = get_logger(__name__)

__all__ = [
    "LockOrderViolation", "LockContractError", "assert_lock_held",
    "instrument_locks", "instrumentation_enabled", "monitored_lock",
    "reset_lock_monitor",
]


class LockOrderViolation(RuntimeError):
    """Two monitored locks were acquired in inconsistent orders.

    ``first``/``second`` name the locks as the LIVE (violating)
    acquire saw them: this thread holds ``first`` and is acquiring
    ``second``, while ``prior_stack`` shows where some thread
    previously acquired ``first`` while holding ``second``.
    ``this_stack`` is the live acquiring stack."""

    def __init__(self, first: str, second: str,
                 this_stack: str, prior_stack: str,
                 this_thread: str, prior_thread: str):
        self.first = first
        self.second = second
        self.this_stack = this_stack
        self.prior_stack = prior_stack
        super().__init__(
            f"lock-order inversion: thread '{this_thread}' is "
            f"acquiring '{second}' while holding '{first}', but "
            f"thread '{prior_thread}' previously acquired '{first}' "
            f"while holding '{second}' — two threads interleaving "
            f"across these orders deadlock permanently, each holding "
            f"the lock the other wants.\n"
            f"--- this acquisition ('{first}' -> '{second}', "
            f"thread '{this_thread}') ---\n{this_stack}"
            f"--- prior acquisition ('{second}' -> '{first}', "
            f"thread '{prior_thread}') ---\n{prior_stack}")


class LockContractError(RuntimeError):
    """:func:`assert_lock_held` found the lock NOT held by the calling
    thread — the caller-holds-the-lock contract the call documents is
    broken."""


# ------------------------------------------------------------- monitor
_monitor_lock = threading.Lock()
_instrumented = False
#: (earlier, later) -> (stack, thread name) of the acquire that first
#: established the order "later taken while earlier held".
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
_tls = threading.local()


def instrument_locks(enable: bool = True) -> bool:
    """Arm (or disarm) the sanitizer process-wide.  Returns the
    previous state, so tests restore it in a finally.  Off (the
    default) costs one bool check per monitored acquire; on, each
    acquire records held-set edges and checks the global order graph
    — debug/chaos-mode overhead, not for the hot path."""
    global _instrumented
    with _monitor_lock:
        prev, _instrumented = _instrumented, bool(enable)
    return prev


def instrumentation_enabled() -> bool:
    return _instrumented


def reset_lock_monitor() -> None:
    """Disarm and clear the recorded order graph (test isolation).
    Per-thread held stacks clear as the holders release."""
    global _instrumented
    with _monitor_lock:
        _instrumented = False
        _edges.clear()


def _held_stack() -> List[str]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _record_acquire(name: str) -> None:
    """Merge this acquire's (held -> name) edges into the global graph
    and raise on the first inversion.  Runs BEFORE the underlying
    acquire: the violation surfaces while this thread still holds only
    its current set, not wedged inside the primitive."""
    held = _held_stack()
    if not held:
        return
    me = threading.current_thread().name
    stack = "".join(traceback.format_stack(limit=16)[:-2])
    with _monitor_lock:
        for h in held:
            if h == name:
                continue  # reentrant RLock re-entry is not an ordering
            prior = _edges.get((name, h))
            if prior is not None:
                prior_stack, prior_thread = prior
                log_structured(
                    logger, logging.ERROR, "lock_order_violation",
                    holding=h, acquiring=name,
                    prior_thread=prior_thread, this_thread=me)
                raise LockOrderViolation(
                    h, name, stack, prior_stack, me, prior_thread)
            _edges.setdefault((h, name), (stack, me))


class _MonitoredLock:
    """A named wrapper over ``threading.Lock``/``RLock`` exposing the
    primitive's interface (``acquire``/``release``/context manager/
    ``locked``) plus owner tracking for :func:`assert_lock_held`.
    Uninstrumented, every method is the primitive's plus one bool
    check."""

    __slots__ = ("name", "kind", "_inner", "_owner", "_count")

    def __init__(self, name: str, kind: str = "lock"):
        if kind not in ("lock", "rlock"):
            raise ValueError(f"kind must be 'lock' or 'rlock', "
                             f"got {kind!r}")
        self.name = name
        self.kind = kind
        self._inner = (threading.RLock() if kind == "rlock"
                       else threading.Lock())
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if _instrumented:
            _record_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
            if _instrumented:
                _held_stack().append(self.name)
        return got

    def release(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._owner = None
        self._inner.release()
        if _instrumented:
            held = _held_stack()
            # remove the LAST occurrence: release order may not mirror
            # acquire order, and an RLock appears once per entry
            for i in range(len(held) - 1, -1, -1):
                if held[i] == self.name:
                    del held[i]
                    break

    def __enter__(self) -> "_MonitoredLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def locked(self) -> bool:
        return self._owner is not None

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self) -> str:
        return (f"<monitored_lock {self.name!r} kind={self.kind} "
                f"owner={self._owner}>")


def monitored_lock(name: str, kind: str = "lock") -> _MonitoredLock:
    """Mint a named lock the sanitizer can see.  ``kind="rlock"`` wraps
    an ``RLock`` (re-entry adds no order edge).  Drop-in for the
    ``threading`` primitive at declaration sites:
    ``self._lock = monitored_lock("goodput.lock")``."""
    return _MonitoredLock(name, kind)


def assert_lock_held(lock) -> None:
    """Runtime check of the caller-holds-the-lock contract, and the
    static acquittal marker for APX114/APX116 (the analyzer treats a
    call in the enclosing function as "the lock discipline for this
    site is enforced HERE, by contract").

    Monitored locks are checked by owner (held by THIS thread);
    plain ``threading.Lock`` objects only expose ``locked()`` (held by
    somebody), which is still enough to catch the bare-call bug;
    ``RLock``-likes with ``_is_owned`` are checked by ownership.
    Raises :class:`LockContractError` on a provable violation."""
    if isinstance(lock, _MonitoredLock):
        if not lock.held_by_current_thread():
            raise LockContractError(
                f"lock '{lock.name}' is not held by the calling "
                f"thread — the caller-holds-the-lock contract this "
                f"assert documents is broken")
        return
    is_owned = getattr(lock, "_is_owned", None)
    if callable(is_owned):
        if not is_owned():
            raise LockContractError(
                "RLock is not owned by the calling thread")
        return
    locked = getattr(lock, "locked", None)
    if callable(locked) and not locked():
        raise LockContractError(
            "lock is not held (not even by another thread) — the "
            "caller-holds-the-lock contract is broken")
