"""Preemption-safe shutdown and resume.

TPU preemption semantics differ from the NCCL-restart world the
reference lived in: Cloud TPU sends SIGTERM with a short grace window
(maintenance events, spot reclamation), after which the VM simply
stops.  Surviving that is three small pieces, composed here:

- :class:`PreemptionHandler` — converts SIGTERM (or an approaching
  wall-clock deadline, or an injected chaos preemption) into a flag the
  training loop polls once per step (a Python bool read — no device
  work).  On the way out the loop calls :meth:`drain` to flush the
  ``AsyncCheckpointer`` queue so every save already accepted is durable
  before the process exits.
- :func:`apex_tpu.io.latest_checkpoint` — restart-side discovery that
  validates checkpoint headers and sizes and *skips torn files*, so a
  kill mid-write (the ``.tmp`` the atomic publish never renamed, or a
  final blob truncated by a dying filesystem) degrades to "resume one
  step earlier", never to a crash or silently corrupt params.
- RNG-tracker snapshot/restore helpers — the Megatron-style named key
  streams (:mod:`apex_tpu.transformer.tensor_parallel.random`) carry a
  per-stream counter; a resume that resets it would replay dropout
  masks.  ``rng_tracker_state_dict`` captures keys+counters into plain
  checkpointable data.
"""

import logging
import signal
import threading
import time
from typing import Optional

from apex_tpu.observability import flightrec as _flightrec
from apex_tpu.observability import metrics as _metrics
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = [
    "PreemptionHandler", "rng_tracker_state_dict",
    "load_rng_tracker_state_dict",
]

_logger = get_logger("apex_tpu.resilience")


class PreemptionHandler:
    """SIGTERM/deadline hook for graceful training-loop shutdown.

    Usage::

        with PreemptionHandler(deadline_sec=None) as pre:
            for step in range(...):
                ...train, save...
                if pre.preempted:
                    pre.drain(ckpt)   # flush queued saves to disk
                    break

    ``signals``: which signals mean "preempted" (default SIGTERM — the
    Cloud TPU maintenance/reclaim notice).  The previous handler is
    chained, not clobbered, and restored on exit.  ``deadline_sec``:
    treat the approach of a wall-clock budget (job schedulers, bench
    watchdogs) as a preemption ``grace_sec`` before it lands.
    """

    def __init__(self, signals=(signal.SIGTERM,),
                 deadline_sec: Optional[float] = None,
                 grace_sec: float = 30.0):
        self._event = threading.Event()
        self._signals = tuple(signals)
        self._prev = {}
        self._installed = False
        self._drain_lock = threading.Lock()
        self._draining = False
        self._drain_done = threading.Event()
        self._deadline = (
            time.monotonic() + float(deadline_sec)
            if deadline_sec is not None else None)
        self._grace = float(grace_sec)
        self.reason: Optional[str] = None

    # ----------------------------------------------------- installation
    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        try:
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        except ValueError:
            # not the main thread (pytest-xdist workers, bg threads):
            # signal delivery is impossible there anyway — deadline and
            # simulate() still work, so degrade instead of failing
            log_structured(_logger, logging.WARNING, "preemption.install_degraded",
                           why="not on main thread; signal hooks skipped")
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ------------------------------------------------------------ state
    def _on_signal(self, signum, frame):
        self._mark(f"signal {signal.Signals(signum).name}")
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def _mark(self, reason: str) -> None:
        if not self._event.is_set():
            self.reason = reason
            log_structured(_logger, logging.WARNING, "preemption.received",
                           reason=reason)
            _metrics.inc("apex_preemptions_total",
                         help="preemption notices received")
            # forensics at the NOTICE (not the exit): the grace window
            # may close before an orderly dump path ever runs (no-op
            # without an installed recorder)
            _flightrec.dump_active("preemption", preempt_reason=reason)
        self._event.set()

    def simulate(self, reason: str = "simulated (chaos)") -> None:
        """Flip the flag as a real signal would (chaos harness hook)."""
        self._mark(reason)

    @property
    def preempted(self) -> bool:
        if self._event.is_set():
            return True
        if self._deadline is not None \
                and time.monotonic() >= self._deadline - self._grace:
            self._mark("deadline approaching")
            return True
        return False

    # ------------------------------------------------------------ drain
    def drain(self, checkpointer) -> None:
        """Flush every queued async save to disk (and surface write
        errors) — the step that turns "a save was accepted" into "the
        bytes are durable" before the grace window closes.

        NOT re-entrant by design, and guarded against it: a second
        SIGTERM landing mid-drain (schedulers often resend), or the
        step watchdog firing from its own thread while the loop is
        already draining, must not re-enter ``wait_until_finished`` —
        worst case two callers race ``close()``-adjacent state.  A
        re-entrant call logs ``preemption.drain_reentered`` and then
        WAITS for the in-flight drain to finish (never flushing twice):
        returning early instead would let the watchdog report
        "drained" and ``os._exit`` while the first flush is still
        writing, losing the final accepted save.  Callers that need a
        bound on that wait wrap drain in their own timeout (the
        watchdog's ``_drain_bounded`` helper thread)."""
        with self._drain_lock:
            if self._draining:
                log_structured(_logger, logging.WARNING,
                               "preemption.drain_reentered",
                               reason=self.reason)
                done = self._drain_done
            else:
                self._draining = True
                done = None
        if done is not None:
            done.wait()  # the in-flight drain's completion IS this one's
            err = getattr(done, "error", None)
            if err is not None:
                # the flush this caller piggybacked on FAILED: returning
                # normally would let a watchdog report "drained" and
                # exit over an unflushed save — surface it here too
                raise RuntimeError(
                    f"in-flight drain failed: {type(err).__name__}: {err}"
                ) from err
            return
        try:
            t0 = time.monotonic()
            checkpointer.wait_until_finished()
            flush_s = time.monotonic() - t0
            log_structured(_logger, logging.WARNING, "preemption.drained",
                           reason=self.reason,
                           flush_seconds=round(flush_s, 3))
            _metrics.inc("apex_preemption_drains_total",
                         help="async-checkpoint queue drains")
            _metrics.observe("apex_preemption_drain_seconds", flush_s,
                             help="drain flush latency")
        except BaseException as e:
            self._drain_done.error = e  # visible to piggybacked waiters
            raise
        finally:
            with self._drain_lock:
                self._draining = False
                self._drain_done.set()
                self._drain_done = threading.Event()  # re-arm


# ----------------------------------------------------- RNG tracker I/O
def rng_tracker_state_dict(tracker=None) -> dict:
    """Snapshot the named RNG streams (base keys + fork counters) into
    plain checkpointable data.  Defaults to the global tracker."""
    import numpy as np

    if tracker is None:
        from apex_tpu.transformer.tensor_parallel.random import (
            get_rng_state_tracker,
        )

        tracker = get_rng_state_tracker()
    return {
        "states": {k: np.asarray(v) for k, v in tracker.get_states().items()},
        "counts": dict(tracker.counts_),
    }


def load_rng_tracker_state_dict(d: dict, tracker=None):
    """Restore a :func:`rng_tracker_state_dict` snapshot so the next
    ``fork`` continues the stream exactly where the save left it."""
    import jax.numpy as jnp

    if tracker is None:
        from apex_tpu.transformer.tensor_parallel.random import (
            get_rng_state_tracker,
        )

        tracker = get_rng_state_tracker()
    tracker.set_states({k: jnp.asarray(v) for k, v in d["states"].items()})
    tracker.counts_ = {k: int(v) for k, v in d["counts"].items()}
    return tracker
