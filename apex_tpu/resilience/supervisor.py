"""Self-healing supervisor: the process that consumes the restart
contract the rest of this package only *documents*.

The resilience runtime established an exit-code table
(:data:`~apex_tpu.resilience.elastic.EXIT_WEDGED`,
:data:`~apex_tpu.resilience.elastic.EXIT_KILLED`), a backoff schedule
(:func:`~apex_tpu.resilience.elastic.restart_backoff`), and a goodput
record (:mod:`apex_tpu.observability.goodput`) — but until now every
chaos test played the supervisor by hand from pytest.  This module is
that supervisor as production code (the torchelastic/TorchTitan agent
pattern, PAPERS.md arxiv 2410.06511): launch the trainer (or the
serving engine) as a child process and run the restart state machine
end to end.

State machine (one ``attempt`` per child launch)::

    SPAWN -> WAIT -> rc == 0 ----------------------------> DONE (exit 0)
              |       rc != 0 and SIGTERM was forwarded --> DONE (exit rc)
              |       rc != 0:
              |         progress advanced?  -> streak = 0
              |         no progress         -> streak += 1
              |         corrupt newest ckpt -> QUARANTINE it
              |         streak >= K         -> BREAKER (exit 76)
              |         restarts exhausted  -> GIVE UP (exit rc)
              +------ BACKOFF (full jitter; wedge repeats lengthen it)
                        -> SPAWN (attempt += 1)

Design points, each load-bearing:

- **Exit-code table.** 0 is done; everything else restarts (75/137 are
  the documented recoverable codes; an unknown nonzero is *also*
  restarted — on real fleets most crashes are environmental — and the
  crash-loop breaker is what bounds the damage when it is not).
- **Progress, not exit codes, feeds the breaker.**  ``progress_fn``
  reads the goodput session files (:func:`apex_tpu.observability
  .goodput.session_progress`) and the newest checkpoint step: a child
  that died *after* banking new steps resets the streak; K consecutive
  failures with NO new progress trip the circuit breaker and the
  supervisor exits :data:`EXIT_CRASH_LOOP` instead of burning the pod.
- **Checkpoint quarantine.**  After every failure the newest restore
  candidate is deep-probed (:func:`apex_tpu.io.probe_checkpoint_dir` —
  index completeness + per-shard validation + blob crc); a corrupt one
  is atomically renamed into ``quarantine/`` with a reason file
  (:func:`apex_tpu.io.quarantine_checkpoint`) so the next restart
  resumes from the previous complete step — one bad save can never
  crash-loop a job to death.
- **Backoff adapts to the goodput record.**  Delays follow
  ``restart_backoff(streak - 1)`` through an injectable ``rng`` so
  tests pin the exact schedule; a wedge (exit 75) recurring at the
  same progress point multiplies the delay by the repeat count and is
  logged as ``supervisor.backoff_lengthened`` — a step that wedges
  every time needs a *longer* cool-down (or the breaker), not a faster
  retry.
- **SIGTERM is forwarded exactly once**, then a bounded grace window,
  then SIGKILL — and the supervisor never restarts a child it was
  asked to stop; it exits with the child's final code so schedulers
  see the truth.
- Every event logs through ``log_structured`` with ``(run_id,
  attempt)``; restarts and backoff land on the metrics registry
  (``apex_supervisor_restarts_total{exit_code}``,
  ``apex_supervisor_backoff_seconds``); the final goodput report
  prints from HERE, so one process owns the whole job's summary.

All effects run through injectable seams (``spawn_fn``, ``sleep_fn``,
``time_fn``, ``rng``, ``progress_fn``, ``probe_fn``), so
``tests/test_supervisor.py`` drives the full state machine with fake
children and a pinned clock — deterministically, on the quick tier.
"""

import logging
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

from apex_tpu.observability import anomaly as _anomaly
from apex_tpu.observability import flightrec as _flightrec
from apex_tpu.observability import metrics as _metrics
from apex_tpu.resilience.elastic import (
    EXIT_KILLED, EXIT_WEDGED, restart_backoff,
)
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = [
    "EXIT_CRASH_LOOP", "SUPERVISOR_FLAGS", "Supervisor",
    "add_supervisor_args", "run_supervised_cli", "strip_supervisor_argv",
]

_logger = get_logger("apex_tpu.resilience")

#: sysexits EX_PROTOCOL repurposed for the restart protocol itself
#: failing: K consecutive relaunches made no step progress, so
#: restarting again would burn the pod, not heal the job.  Distinct
#: from 0 (done), 75 (wedged — restartable), 137 (killed —
#: restartable), and the child's own crash codes, so a fleet scheduler
#: can page a human on exactly this one.
EXIT_CRASH_LOOP = 76

#: supervisor-owned CLI flags (flag -> value-arg count) — what
#: :func:`strip_supervisor_argv` removes so the child never sees (and
#: never recursively re-enters) supervision.
SUPERVISOR_FLAGS = {
    "--supervise": 0,
    "--max-restarts": 1,
    "--crash-loop-threshold": 1,
    "--backoff-base": 1,
    "--backoff-cap": 1,
    "--backoff-seed": 1,
    "--supervise-grace": 1,
    "--fault-script": 1,
}


def strip_supervisor_argv(argv: Sequence[str],
                          flags=None) -> List[str]:
    """Drop the supervisor-owned flags (and their values) from an
    argv, handling both ``--flag value`` and ``--flag=value``
    spellings — the child relaunch command is the operator's own
    command line minus the supervision layer."""
    flags = SUPERVISOR_FLAGS if flags is None else flags
    out: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        name = a.split("=", 1)[0]
        if name in flags:
            i += 1 + (0 if "=" in a else flags[name])
            continue
        out.append(a)
        i += 1
    return out


class Supervisor:
    """Crash-loop-aware restart orchestration for one child command.

    Usage (what ``pretrain_gpt.py --supervise`` and
    ``serve_gpt.py --supervise`` wire)::

        sup = Supervisor(cmd, checkpoint_dir=ck, metrics_dir=md,
                         run_id=args.run_id)
        sys.exit(sup.run())

    Parameters:

    ``cmd``: the child argv (already stripped of supervisor flags).
    ``checkpoint_dir``: enables the post-failure corruption probe +
    quarantine; ``metrics_dir``: enables goodput-based progress reading
    and the final report print.  ``max_restarts`` bounds total
    relaunches; ``crash_loop_threshold`` (K) is the no-progress streak
    that trips the breaker.  ``backoff_base``/``backoff_cap``/``seed``
    parameterize :func:`restart_backoff`; ``rng`` (anything with
    ``uniform``) overrides the seed derivation so tests pin delays.
    ``grace_sec`` bounds the SIGTERM->SIGKILL drain.
    ``min_healthy_runtime_sec``: a child that RAN at least this long
    before failing counts as progress even when no step counter moved —
    the signal a stateless child (the serving engine, which banks no
    checkpoints) still has; without it the breaker would degenerate to
    "K failures ever" and put down a server that served for days
    between transient wedges.
    ``fault_script`` (:class:`~apex_tpu.resilience.chaos
    .SupervisorFaultScript`) arms per-attempt chaos: extra child args
    and/or a pre-spawn corrupt-newest-checkpoint.
    ``install_signals=True`` (the CLI path) forwards a received
    SIGTERM to the child exactly once.
    """

    def __init__(self, cmd: Sequence[str], *, checkpoint_dir=None,
                 metrics_dir=None, run_id: str = "run",
                 max_restarts: int = 16, crash_loop_threshold: int = 3,
                 backoff_base: float = 2.0, backoff_cap: float = 300.0,
                 seed: int = 0, rng=None, grace_sec: float = 30.0,
                 min_healthy_runtime_sec: float = 300.0,
                 fault_script=None, install_signals: bool = False,
                 flight_dir=None,
                 spawn_fn: Optional[Callable] = None,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 time_fn: Callable[[], float] = time.monotonic,
                 progress_fn: Optional[Callable[[], int]] = None,
                 probe_fn: Optional[Callable] = None,
                 anomaly_fn: Optional[Callable[[], int]] = None):
        if crash_loop_threshold < 1:
            raise ValueError(
                f"crash_loop_threshold must be >= 1, got "
                f"{crash_loop_threshold}")
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.cmd = [str(c) for c in cmd]
        self.checkpoint_dir = checkpoint_dir
        self.metrics_dir = metrics_dir
        self.run_id = str(run_id)
        self.max_restarts = int(max_restarts)
        self.crash_loop_threshold = int(crash_loop_threshold)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.seed = int(seed)
        self.rng = rng
        self.grace_sec = float(grace_sec)
        self.min_healthy_runtime_sec = float(min_healthy_runtime_sec)
        self.fault_script = fault_script
        self._spawn = spawn_fn if spawn_fn is not None else self._spawn_child
        self._sleep = sleep_fn
        self._time = time_fn
        self._progress_fn = progress_fn if progress_fn is not None \
            else self._default_progress
        self._probe = probe_fn if probe_fn is not None \
            else self._default_probe
        self._install_signals = bool(install_signals)
        #: where the children's flight-recorder dumps land (the trace
        #: dir when the drivers trace, else <metrics_dir>/flightrec) —
        #: the newest readable dump is attached to every restart and
        #: quarantine record, so each exit-75/137 points at its own
        #: forensics artifact
        self.flight_dir = (str(flight_dir) if flight_dir is not None
                           else _flightrec.default_dir(
                               metrics_dir=metrics_dir))
        self._anomaly_fn = anomaly_fn if anomaly_fn is not None \
            else self._default_anomaly
        self._anomaly_seen = 0
        # ---- run state (introspectable by tests / postmortems)
        self.attempt = 0            # child launches so far
        self.restarts = 0           # relaunches after a failure
        self.quarantined: List[str] = []
        self.flight_dumps: List[Optional[str]] = []
        self.backoffs: List[float] = []
        self._streak = 0            # consecutive no-progress failures
        self._last_progress = 0
        self._wedge_repeats = 0
        self._wedge_progress: Optional[int] = None
        self._child = None
        self._stop_requested = False
        self._term_forwarded = False
        self._kill_deadline: Optional[float] = None

    # -------------------------------------------------------- seams
    @staticmethod
    def _spawn_child(argv):
        # stdout/stderr inherited: the child's loss lines and
        # structured events ARE the job's output; the supervisor only
        # adds its own events around them
        return subprocess.Popen(argv)

    def _default_progress(self) -> int:
        """Best available monotone progress signal: goodput session
        steps (the authoritative record) plus the newest COMPLETE
        checkpoint step (covers runs launched without --metrics-dir).
        Completeness matters: a hard kill can leave an incomplete
        newest ``step_*`` dir that no restore can use — counting it as
        progress would mask exactly the no-progress failure the
        quarantine probe and the breaker exist to catch."""
        from pathlib import Path

        from apex_tpu.io import checkpoint as ckpt

        best = 0
        if self.metrics_dir is not None:
            from apex_tpu.observability.goodput import session_progress

            best = max(best, session_progress(self.metrics_dir))
        d = Path(self.checkpoint_dir) if self.checkpoint_dir is not None \
            else None
        if d is None or not d.is_dir():
            return best
        if any(p.is_dir() for p in d.glob("step_*")):
            try:
                step = ckpt.latest_distributed_step(d)
            except ckpt.AllCheckpointsTornError:
                step = -1  # dirs exist, none complete: nothing banked
            return max(best, step)
        try:
            newest = ckpt.latest_checkpoint(d)
        except FileNotFoundError:  # incl. the all-torn subclass
            newest = None          # no restorable file: nothing banked
        if newest is not None:
            best = max(best, ckpt.checkpoint_step(newest))
        return best

    def _default_probe(self):
        if self.checkpoint_dir is None:
            return None
        from apex_tpu.io.checkpoint import probe_checkpoint_dir

        return probe_checkpoint_dir(self.checkpoint_dir)

    def _default_anomaly(self) -> int:
        """Total alerts the children's anomaly monitors persisted under
        the metrics dir — falling back to the flight/trace dir, where
        the drivers persist when only ``--trace-dir`` is set — recent
        only (a week-old regression record must not keep lengthening
        today's backoff)."""
        d = self.metrics_dir if self.metrics_dir is not None \
            else self.flight_dir
        if d is None:
            return 0
        return _anomaly.recent_alert_count(d, max_age_sec=3600.0)

    def _latest_flight_dump(self) -> Optional[str]:
        if self.flight_dir is None:
            return None
        return _flightrec.latest_dump_path(self.flight_dir)

    # ------------------------------------------------------ signals
    def _on_sigterm(self, signum, frame):  # pragma: no cover - signal path
        self.request_stop()

    def request_stop(self) -> None:
        """Stop the job: forward SIGTERM to the live child EXACTLY
        once, arm the grace-then-SIGKILL deadline, and never spawn
        again.  Idempotent — schedulers resend the reclaim notice."""
        self._stop_requested = True
        child = self._child
        if child is not None and not self._term_forwarded:
            self._term_forwarded = True
            self._kill_deadline = self._time() + self.grace_sec
            log_structured(_logger, logging.WARNING,
                           "supervisor.forwarding_sigterm",
                           run_id=self.run_id, attempt=self.attempt,
                           grace_sec=self.grace_sec)
            try:
                child.terminate()
            except OSError:
                # already-reaped child: wait() below returns immediately
                log_structured(_logger, logging.WARNING,
                               "supervisor.forward_failed",
                               run_id=self.run_id, attempt=self.attempt)

    def _wait(self, child) -> int:
        """Reap the child, honoring the grace-then-SIGKILL drain when a
        stop was requested (the poll loop is what lets a signal landing
        mid-wait arm the deadline and still bound the drain)."""
        killed = False
        while True:
            try:
                rc = child.wait(timeout=0.2)
            except subprocess.TimeoutExpired:
                rc = None  # still running: fall through to the deadline
            if rc is not None:
                rc = int(rc)
                # Popen reports a signal death as -SIGNUM; the process
                # table (and this supervisor's own exit) speaks
                # 128+SIGNUM — returning the raw negative would garble
                # the final exit status (SystemExit(-9) exits 247, not
                # 137) and EXIT_KILLED would never match a REAL SIGKILL
                return 128 - rc if rc < 0 else rc
            if (self._kill_deadline is not None and not killed
                    and self._time() >= self._kill_deadline):
                killed = True
                log_structured(_logger, logging.ERROR,
                               "supervisor.grace_expired_sigkill",
                               run_id=self.run_id, attempt=self.attempt,
                               grace_sec=self.grace_sec)
                try:
                    child.kill()
                except OSError as e:
                    # it died on its own in the window — wait() below
                    # reaps it; still worth a line in the postmortem
                    log_structured(_logger, logging.WARNING,
                                   "supervisor.kill_failed",
                                   run_id=self.run_id,
                                   attempt=self.attempt,
                                   error=f"{type(e).__name__}: {e}")

    # --------------------------------------------------------- faults
    def _apply_fault(self, argv: List[str]) -> List[str]:
        if self.fault_script is None:
            return argv
        fault = self.fault_script.fault_for(self.attempt)
        if fault is None:
            return argv
        if fault.corrupt_newest_checkpoint:
            if self.checkpoint_dir is None:
                raise ValueError(
                    "fault script asks to corrupt the newest checkpoint "
                    "but the supervisor has no checkpoint_dir")
            from apex_tpu.resilience.chaos import corrupt_newest_checkpoint

            corrupt_newest_checkpoint(self.checkpoint_dir)
        return argv + list(fault.extra_args)

    # ------------------------------------------------------ quarantine
    def _probe_and_quarantine(self) -> None:
        """Post-failure: deep-probe the checkpoint the NEXT restore
        would load; quarantine it when corrupt.  Probe errors are
        logged, never fatal — a broken probe must not stop the restart
        machine whose whole job is to keep the run alive."""
        try:
            bad = self._probe()
        except Exception as e:  # noqa: BLE001 — report, keep supervising
            log_structured(_logger, logging.WARNING,
                           "supervisor.probe_failed", run_id=self.run_id,
                           attempt=self.attempt,
                           error=f"{type(e).__name__}: {e}")
            return
        if bad is None:
            return
        from apex_tpu.io.checkpoint import quarantine_checkpoint

        dest = quarantine_checkpoint(self.checkpoint_dir, bad.path,
                                     bad.reason)
        self.quarantined.append(dest)
        _metrics.inc("apex_supervisor_quarantines_total",
                     help="corrupt newest checkpoints quarantined")
        log_structured(_logger, logging.ERROR, "supervisor.quarantined",
                       run_id=self.run_id, attempt=self.attempt,
                       path=bad.path, quarantined_to=dest,
                       reason=bad.reason,
                       flight_dump=self._latest_flight_dump())

    # -------------------------------------------------------- backoff
    def _backoff_delay(self, exit_code: int, progress: int) -> float:
        delay = restart_backoff(max(self._streak - 1, 0),
                                base=self.backoff_base,
                                cap=self.backoff_cap, seed=self.seed,
                                rng=self.rng)
        alerts = self._safe_anomaly()
        if alerts > self._anomaly_seen:
            # the dead child's anomaly monitor recorded NEW regressions
            # (step-time ramp, SLO burn) before it died: the fault was
            # building, not transient — double the cool-down once per
            # batch of fresh alerts (the goodput-adaptive leg of the
            # backoff, same logic as the wedge-repeat lengthening)
            delay *= 2.0
            log_structured(_logger, logging.WARNING,
                           "supervisor.backoff_lengthened",
                           run_id=self.run_id, attempt=self.attempt,
                           reason="anomaly_alerts",
                           new_alerts=alerts - self._anomaly_seen,
                           delay_s=round(delay, 3))
        # track DOWN as well as up: summaries age out of the recent-
        # alert window, and a stale high watermark would silently eat
        # the next batch of fresh alerts (healthy-for-an-hour server,
        # then a real ramp)
        self._anomaly_seen = alerts
        if exit_code == EXIT_WEDGED:
            if self._wedge_progress == progress:
                # the SAME point in the run wedged again: the goodput
                # record says the short cool-down did not help —
                # lengthen it instead of hammering the fault
                self._wedge_repeats += 1
                delay *= (1 + self._wedge_repeats)
                log_structured(_logger, logging.WARNING,
                               "supervisor.backoff_lengthened",
                               run_id=self.run_id, attempt=self.attempt,
                               progress=progress,
                               wedge_repeats=self._wedge_repeats,
                               delay_s=round(delay, 3))
            else:
                self._wedge_progress = progress
                self._wedge_repeats = 0
        return delay

    # ------------------------------------------------------------ run
    def run(self) -> int:
        """Drive the restart state machine to a final exit code (also
        what the process should exit with)."""
        from apex_tpu.observability import set_step_context

        set_step_context(run_id=self.run_id)
        prev_handler = None
        if self._install_signals:
            prev_handler = signal.signal(signal.SIGTERM, self._on_sigterm)
        try:
            return self._run()
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)

    def _run(self) -> int:
        self._last_progress = self._safe_progress()
        # baseline, not zero: anomaly summaries a PREVIOUS run left in
        # the same metrics dir must not double THIS run's first backoff
        self._anomaly_seen = self._safe_anomaly()
        while True:
            if self._stop_requested:
                # SIGTERM landed before this (first or next) spawn —
                # e.g. during the initial progress read: launching a
                # child the scheduler already wants dead would end in
                # an undrained cgroup SIGKILL
                log_structured(_logger, logging.WARNING,
                               "supervisor.stopped_before_spawn",
                               run_id=self.run_id, attempt=self.attempt)
                return self._finish(0, "stopped by SIGTERM before spawn")
            argv = self._apply_fault(list(self.cmd))
            log_structured(_logger, logging.INFO, "supervisor.spawning",
                           run_id=self.run_id, attempt=self.attempt,
                           cmd=" ".join(argv))
            spawned_at = self._time()
            self._child = self._spawn(argv)
            if self._stop_requested and not self._term_forwarded:
                # the signal raced the spawn itself: the handler saw
                # _child=None and could not forward — do it now, so the
                # fresh child still gets the TERM + grace contract
                self.request_stop()
            rc = self._wait(self._child)
            self._child = None
            runtime = self._time() - spawned_at
            log_structured(_logger, logging.INFO, "supervisor.child_exit",
                           run_id=self.run_id, attempt=self.attempt,
                           exit_code=rc, runtime_s=round(runtime, 3))
            if rc == 0:
                return self._finish(0, "clean child exit")
            if self._stop_requested:
                # the child was ASKED to die: its code is the truth,
                # restarting would fight the scheduler
                return self._finish(rc, "stopped by SIGTERM")
            progress = self._safe_progress()
            # a long-healthy runtime IS progress: a stateless child
            # (the serving engine) banks no step counters, and a
            # trainer's sessions may be unreadable — "ran fine for
            # minutes before this fault" must not accumulate toward
            # the breaker across days of otherwise-healthy serving
            if progress > self._last_progress \
                    or runtime >= self.min_healthy_runtime_sec:
                self._streak = 0
            else:
                self._streak += 1
            self._last_progress = progress
            if self._streak >= 1:
                # quarantine probe only on a NO-PROGRESS failure: a
                # corrupt-newest restore crash is one by construction,
                # while probing after every progress-making wedge would
                # re-read multi-GB of healthy shards per restart (the
                # child's own load-time crc re-verifies them anyway)
                self._probe_and_quarantine()
            if self._streak >= self.crash_loop_threshold:
                log_structured(
                    _logger, logging.ERROR,
                    "supervisor.circuit_breaker_tripped",
                    run_id=self.run_id, attempt=self.attempt,
                    exit_code=rc, no_progress_failures=self._streak,
                    threshold=self.crash_loop_threshold,
                    breaker_exit_code=EXIT_CRASH_LOOP)
                return self._finish(
                    EXIT_CRASH_LOOP,
                    f"{self._streak} consecutive no-progress failures")
            if self.restarts >= self.max_restarts:
                log_structured(_logger, logging.ERROR,
                               "supervisor.restarts_exhausted",
                               run_id=self.run_id, attempt=self.attempt,
                               max_restarts=self.max_restarts,
                               exit_code=rc)
                return self._finish(rc, "restart budget exhausted")
            delay = self._backoff_delay(rc, progress)
            self.backoffs.append(delay)
            # the child's own flight recorder dumped on its way out
            # (watchdog wedge, budget abort) or left its periodically
            # republished checkpoint (hard kill): the restart record
            # carries the path, so every exit-75/137 names its own
            # forensics artifact
            flight = self._latest_flight_dump()
            self.flight_dumps.append(flight)
            _metrics.observe("apex_supervisor_backoff_seconds", delay,
                             help="pre-restart backoff delays")
            log_structured(_logger, logging.WARNING,
                           "supervisor.restarting", run_id=self.run_id,
                           attempt=self.attempt, exit_code=rc,
                           delay_s=round(delay, 3), progress=progress,
                           no_progress_failures=self._streak,
                           flight_dump=flight)
            self._sleep(delay)
            if self._stop_requested:
                # SIGTERM landed during the backoff sleep: no child to
                # forward to, nothing new to lose — report the last rc
                # (counted as ZERO relaunches: none happened)
                return self._finish(rc, "stopped by SIGTERM in backoff")
            # counted HERE, after every return that skips the respawn:
            # the metric means relaunches that actually happen, and
            # must agree with self.restarts at every exit
            _metrics.inc("apex_supervisor_restarts_total",
                         help="child relaunches by exit code",
                         exit_code=str(rc))
            self.restarts += 1
            self.attempt += 1

    def _safe_anomaly(self) -> int:
        try:
            return int(self._anomaly_fn())
        except Exception as e:  # noqa: BLE001 — a broken alert probe
            # must degrade to "nothing new", not kill the machine
            log_structured(_logger, logging.WARNING,
                           "supervisor.anomaly_read_failed",
                           run_id=self.run_id, attempt=self.attempt,
                           error=f"{type(e).__name__}: {e}")
            return self._anomaly_seen

    def _safe_progress(self) -> int:
        try:
            return int(self._progress_fn())
        except Exception as e:  # noqa: BLE001 — a broken progress probe
            # must degrade to "no progress seen", not kill the machine
            log_structured(_logger, logging.WARNING,
                           "supervisor.progress_read_failed",
                           run_id=self.run_id, attempt=self.attempt,
                           error=f"{type(e).__name__}: {e}")
            return self._last_progress

    def _finish(self, code: int, why: str) -> int:
        report = None
        if self.metrics_dir is not None:
            try:
                from apex_tpu.observability.goodput import goodput_report

                report = goodput_report(self.metrics_dir)
            except Exception as e:  # noqa: BLE001 — the summary is
                # best-effort; the exit code is the contract
                log_structured(_logger, logging.WARNING,
                               "supervisor.report_failed",
                               run_id=self.run_id,
                               error=f"{type(e).__name__}: {e}")
        log_structured(_logger, logging.INFO, "supervisor.done",
                       run_id=self.run_id, attempt=self.attempt,
                       exit_code=code, why=why, restarts=self.restarts,
                       quarantined=self.quarantined,
                       sessions=(report or {}).get("sessions"))
        if report and report.get("fractions"):
            # ONE process owns the job summary: the per-session lines
            # the children printed cover their own lifetimes; this is
            # the whole job, restarts and backoff included
            print("supervisor goodput: " + " ".join(
                f"{k}={v:.1%}"
                for k, v in sorted(report["fractions"].items())),
                flush=True)
        return int(code)


def run_supervised_cli(args, argv=None, **overrides) -> int:
    """The example drivers' ``--supervise`` entry: rebuild the child
    command from this process's own argv minus the supervisor flags,
    wire the fault script, and run.  ``args`` is the parsed namespace
    (needs ``checkpoint``/``metrics_dir``/``run_id`` plus the
    supervisor flags); ``overrides`` pass straight to
    :class:`Supervisor` (the serving driver has no checkpoint dir)."""
    argv = list(sys.argv if argv is None else argv)
    cmd = [sys.executable, argv[0], *strip_supervisor_argv(argv[1:])]
    fault_script = None
    if getattr(args, "fault_script", None):
        from apex_tpu.resilience.chaos import SupervisorFaultScript

        fault_script = SupervisorFaultScript.from_file(args.fault_script)
    kw = dict(
        checkpoint_dir=getattr(args, "checkpoint", None),
        metrics_dir=getattr(args, "metrics_dir", None),
        flight_dir=_flightrec.default_dir(
            metrics_dir=getattr(args, "metrics_dir", None),
            trace_dir=getattr(args, "trace_dir", None)),
        run_id=getattr(args, "run_id", "run"),
        max_restarts=args.max_restarts,
        crash_loop_threshold=args.crash_loop_threshold,
        backoff_base=args.backoff_base, backoff_cap=args.backoff_cap,
        seed=args.backoff_seed, grace_sec=args.supervise_grace,
        fault_script=fault_script, install_signals=True,
    )
    kw.update(overrides)
    return Supervisor(cmd, **kw).run()


def add_supervisor_args(parser) -> None:
    """The shared ``--supervise`` flag family both example drivers
    expose (one definition so the flags — and therefore
    :data:`SUPERVISOR_FLAGS` — cannot drift per driver)."""
    parser.add_argument(
        "--supervise", action="store_true",
        help="run under the self-healing supervisor: this process "
             "relaunches the SAME command (minus the supervisor flags) "
             "as a child, restarts it with full-jitter backoff on the "
             "documented exit codes (75 wedged, 137 killed, any other "
             "nonzero crash), quarantines a corrupt newest checkpoint "
             "so a bad save costs one save interval instead of a crash "
             "loop, trips a circuit breaker (exit 76) after "
             "--crash-loop-threshold consecutive no-progress failures, "
             "and prints the whole job's goodput report at final exit")
    parser.add_argument("--max-restarts", type=int, default=16,
                        help="total relaunch budget under --supervise")
    parser.add_argument("--crash-loop-threshold", type=int, default=3,
                        help="consecutive no-progress failures that trip "
                             "the circuit breaker (exit 76)")
    parser.add_argument("--backoff-base", type=float, default=2.0,
                        help="restart_backoff base (attempt k waits "
                             "uniform(0, min(cap, base*2^k)) seconds)")
    parser.add_argument("--backoff-cap", type=float, default=300.0)
    parser.add_argument("--backoff-seed", type=int, default=0,
                        help="jitter seed (real pods seed per host so "
                             "restarts don't re-land in lockstep)")
    parser.add_argument("--supervise-grace", type=float, default=30.0,
                        help="SIGTERM-forward grace before SIGKILL")
    parser.add_argument("--fault-script", default=None,
                        help="chaos: JSON mapping attempt index -> "
                             "{args: [...], corrupt_newest_checkpoint: "
                             "bool} (resilience.chaos."
                             "SupervisorFaultScript) — the one-command "
                             "fault gauntlet")
