"""Kernel fallback registry: degrade once instead of crashing the run.

Every Pallas entry point in this repo has an XLA reference
implementation that is the numerics specification (``ops/attention.py``
scan path for ``flash_attention_pallas``, the chunked scan in
``ops/fused_ce.py`` for the CE kernels, the jnp composite in
``normalization/fused_layer_norm.py`` for the LayerNorm kernels).  The
kernels have never been proven on real chips (VERDICT r5), so a Mosaic
lowering surprise must not kill a multi-hour training run that the
reference impl could have carried at reduced throughput.

The registry sits at each call-site seam:

    return get_registry().call("fused_ce", pallas_impl, scan_impl)

- the first failure of a kernel **trips** it: one structured warning
  (``kernel_fallback.tripped``) with the error, then the fallback runs
  — and every later trace of that kernel goes straight to the fallback
  with no further noise ("degrade once").
- the decision composes with the existing explicit ``impl=``-style
  config threading (``fused_ce_impl``, ``flash_attention(impl=...)``):
  when the impl is *chosen* (``auto``/platform default) the registry
  wraps the kernel; when the caller *forced* the kernel impl
  (``impl="pallas"``, ``fused_ce_impl="on"``) it bypasses the registry
  and failures surface loudly — a forced impl silently degrading to the
  reference would make every kernel-vs-oracle test and every
  pallas-vs-scan A/B vacuous (:func:`registry_engaged`).  The chaos
  harness re-engages the registry even for forced impls: CPU tests must
  force ``interpret`` to reach the kernel path at all, and the fallback
  seam is exactly what they exist to prove.  No env vars are consulted
  (the APX101/102 contract).
- the chaos harness injects launch failures through the same seam
  (:func:`apex_tpu.resilience.chaos.check_kernel` runs just before the
  kernel), so the fallback path tested on CPU is byte-for-byte the one
  hardware failures will take.

Scope caveat (documented, deliberate): the registry catches failures
that surface while the kernel's Python/trace-time code runs.  A Mosaic
error deferred to ``jit`` *compile* time surfaces to the caller of the
compiled step; catch it there, feed it to :func:`trip_from_exception`,
and rebuild the step — the new trace consults the registry and lowers
the fallback.  ``examples/gpt/pretrain_gpt.py`` wires this.

Collective-bearing engines NEVER register here.  The multi-tensor
bucket engine routes through ``"multi_tensor_engine"`` only because its
fallback (the per-leaf path) lowers the SAME collective-free program
shape; the ZeRO bucket engine
(:mod:`apex_tpu.contrib.optimizers._zero_engine`) has per-bucket
reduce-scatters and all-gathers INSIDE the optimizer, so a per-process
degrade-once would lower divergent SPMD programs across the pod —
mismatched collective counts deadlock every host device-side with no
error (the same invariant :func:`registry_engaged` enforces by
disengaging under ``jax.process_count() > 1``).  ZeRO therefore runs
its engine directly and fails fast; ``--auto-resume`` is the recovery
path.
"""

import dataclasses
import logging
import threading
from typing import Callable, Dict, List, Optional

from apex_tpu.observability import metrics as _metrics
from apex_tpu.utils.logging import get_logger, log_structured

__all__ = [
    "KERNELS", "KernelFallbackRegistry", "get_registry",
    "registry_engaged", "trip_from_exception",
]

_logger = get_logger("apex_tpu.resilience")

#: The registered Pallas entry points and the markers by which a
#: compile-time error message is attributed to one of them.  Markers are
#: kernel-SPECIFIC tokens (the ``*_pallas`` entry-point/module names and
#: the kernel-body def names) — never the bare op name: XLA runtime
#: errors embed HLO instruction names derived from the traced Python
#: functions, so an OOM or sharding error whose dump mentions
#: ``layer_norm`` must NOT be attributed as a kernel failure (the caller
#: would swallow the real error and burn a recompile per retry).  A
#: marker shared by several kernels' source (``_fwd_kernel`` is a def in
#: BOTH flash_attention_pallas.py and fused_ce_pallas.py) appears under
#: every owner: tripping both costs the innocent one throughput, while
#: tripping the wrong one alone would re-lower the broken kernel and
#: crash the retry.
KERNELS: Dict[str, tuple] = {
    "flash_attention": ("flash_attention_pallas", "flash_fwd_pallas",
                        "flash_bwd_pallas", "_fwd_kernel", "_dq_kernel",
                        "_dkv_kernel"),
    "fused_ce": ("fused_ce_pallas", "fused_ce_fwd_pallas",
                 "fused_ce_bwd_pallas", "_fwd_kernel", "_dx_kernel",
                 "_dembed_kernel"),
    "layer_norm": ("layer_norm_pallas", "_ln_fwd_kernel",
                   "_ln_bwd_kernel"),
    "decode_attention": ("decode_attention_pallas",
                         "paged_decode_attention_pallas",
                         "_decode_attn_kernel"),
    "decode_sampling": ("decode_sampling_pallas", "fused_sample_pallas",
                        "_sample_kernel", "_merge_top_k"),
}


@dataclasses.dataclass
class _Entry:
    tripped: bool = False
    error: Optional[str] = None
    fallback_calls: int = 0
    kernel_calls: int = 0


class KernelFallbackRegistry:
    """Per-process record of which Pallas kernels are trusted."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {k: _Entry() for k in KERNELS}

    def _entry(self, name: str) -> _Entry:
        with self._lock:
            return self._entries.setdefault(name, _Entry())

    # ------------------------------------------------------------- use
    def call(self, name: str, kernel: Callable[[], object],
             fallback: Callable[[], object]):
        """Run ``kernel()`` unless ``name`` is tripped; on failure trip
        it (one structured warning) and run ``fallback()``.

        Both callables are zero-arg closures so the registry never has
        to understand kernel signatures; they must return the same
        pytree structure (each kernel's fallback is its numerics
        specification, so this holds by construction)."""
        from apex_tpu.resilience import chaos

        e = self._entry(name)
        if e.tripped:
            with self._lock:
                e.fallback_calls += 1
            return fallback()
        try:
            chaos.check_kernel(name)
            out = kernel()
        except Exception as err:  # noqa: BLE001 — any kernel-path error
            # (injected launch failure, Mosaic lowering, interpret-mode
            # surprise) degrades to the reference impl; the error is
            # preserved in the warning and in status() for postmortems
            self.trip(name, err)
            with self._lock:
                e.fallback_calls += 1
            try:
                return fallback()
            except Exception:
                # the reference impl rejected the SAME call: the fault
                # is the arguments (e.g. a shape-validation error raised
                # inside the kernel closure), not the kernel — un-trip
                # so later valid calls still reach the kernel, and let
                # the fallback's (clearer) validation error surface
                log_structured(
                    _logger, logging.WARNING, "kernel_fallback.reset",
                    kernel=name,
                    reason="reference impl rejected the same call; "
                           "attributing the failure to the arguments")
                self.reset(name)
                raise
        with self._lock:
            e.kernel_calls += 1
        return out

    # ----------------------------------------------------------- state
    def trip(self, name: str, error) -> None:
        """Mark ``name`` failed; warn exactly once per trip."""
        e = self._entry(name)
        with self._lock:
            if e.tripped:
                return
            e.tripped = True
            e.error = f"{type(error).__name__}: {error}"
        log_structured(
            _logger, logging.WARNING, "kernel_fallback.tripped",
            kernel=name, error=e.error,
            action="using XLA reference impl for every later trace")
        _metrics.inc("apex_kernel_fallback_trips_total",
                     help="Pallas kernels degraded to their XLA reference",
                     kernel=name)

    def tripped(self, name: str) -> bool:
        return self._entry(name).tripped

    def reset(self, name: Optional[str] = None) -> None:
        """Forget trips (all kernels, or one).  Already-compiled jits
        keep whatever impl they traced; only NEW traces re-try the
        kernel."""
        with self._lock:
            names = [name] if name is not None else list(self._entries)
            for n in names:
                self._entries[n] = _Entry()

    def status(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dataclasses.asdict(v)
                    for k, v in self._entries.items()}


_REGISTRY = KernelFallbackRegistry()


def get_registry() -> KernelFallbackRegistry:
    return _REGISTRY


def registry_engaged(forced: bool) -> bool:
    """Should a kernel call site route through the registry?

    ``forced`` means the caller explicitly pinned the kernel impl
    (``flash_attention(impl="pallas")``, ``fused_ce_impl="on"``): that
    is a demand to run THIS impl or fail loudly, so the registry stays
    out of the way — silently degrading a forced kernel to its reference
    would make kernel-vs-oracle tests pass vacuously and pallas-vs-scan
    benchmarks compare the reference against itself.  The chaos harness
    overrides: its CPU tests can only reach the kernel path by forcing
    ``interpret``, and the fallback seam is what they exist to prove.

    Multi-process runs NEVER engage the registry: a per-process degrade
    would lower the fallback's collective program (per-chunk scan psums)
    on the failing host while its peers lower the kernel's — mismatched
    collective counts deadlock every host device-side, with no error.
    Failing fast instead gives the clean job-level crash that
    ``--auto-resume`` restarts from (the same reasoning as the
    fail-fast multiproc rebuild path in examples/gpt/pretrain_gpt.py)."""
    import jax

    from apex_tpu.resilience.chaos import active_monkey
    from apex_tpu.resilience.uniformity import assert_uniform

    if jax.process_count() > 1:
        engaged = False
    else:
        engaged = (not forced) or active_monkey() is not None
    # record-only (no collective): every process must reach the same
    # engagement decision — a per-process degrade lowers mismatched
    # collective programs; check_uniform() surfaces the divergence as
    # a named error before the pod can wedge on it
    assert_uniform(f"kernel_registry.engaged/forced={bool(forced)}",
                   engaged)
    return engaged


def trip_from_exception(exc: BaseException) -> List[str]:
    """Attribute a deferred (jit-compile-time) kernel failure.

    Matches the exception text against each registered kernel's markers
    and trips the ones identified; returns the tripped names (empty when
    the error does not look like a Pallas/Mosaic kernel failure).  The
    caller then rebuilds/re-jits its step: the fresh trace consults the
    registry and lowers the XLA reference impl instead."""
    text = str(exc)
    lower = text.lower()
    # "mosaic" names the TPU kernel compiler and appears only in its
    # own failures; "pallas" is deliberately NOT a generic trigger — it
    # is the API name and shows up in innocent error text (module paths,
    # buffer names of successfully-compiled kernels inside an OOM dump),
    # and tripping every kernel on such an error would swallow the real
    # failure behind len(KERNELS)+1 recompiles (see the KERNELS note)
    generic = "mosaic" in lower
    # A runtime RESOURCE_EXHAUSTED (HBM OOM) names its allocations by op
    # metadata derived from the traced functions — including the
    # ``*_pallas`` entry-point names of kernels that compiled FINE — so
    # the marker match below would misattribute it.  Resource exhaustion
    # is not a lowering failure: unless Mosaic itself is named, nothing
    # trips and the real error surfaces to the caller immediately.
    if not generic and ("resource_exhausted" in lower
                        or "resource exhausted" in lower
                        or "out of memory" in lower):
        return []
    tripped: List[str] = []
    for name, markers in KERNELS.items():
        if any(m in text for m in markers):
            _REGISTRY.trip(name, exc)
            tripped.append(name)
    if not tripped and generic:
        # A Mosaic error we cannot attribute: trip every kernel rather
        # than crash the run on the next identical compile.
        for name in KERNELS:
            _REGISTRY.trip(name, exc)
            tripped.append(name)
    return tripped
