"""Elastic fault-tolerant run control: cross-world resume + watchdog.

The resilience runtime (PR 2) survives preemptions and bad steps but
could only resume at the SAME world size, and a wedged collective hung
forever.  This module closes both gaps — the TorchTitan
production-readiness recipe (PAPERS.md, arxiv 2410.06511) on top of the
cross-replica-sharded state layout of arxiv 2004.13336:

- **Elastic checkpoints** (:func:`save_elastic_checkpoint` /
  :func:`restore_elastic_checkpoint`): one ``step_<N>/`` directory per
  save holding per-dp-rank shard files plus an ``index.json`` that
  records the saved world layout.  On restart the live world size is
  compared against the index; a ZeRO state saved at dp=4 reshards for
  dp=2 (or dp=8) through the ONE pad formula the bucket plan itself
  uses (:func:`apex_tpu.optimizers.bucketing.padded_total`, via
  :meth:`~apex_tpu.contrib.optimizers._zero_engine.ZeroOptimizerBase
  .load_sharded_state_dicts`) — m/v, fp32 masters or uint16
  remainders, and int8/fp8 error-feedback residuals all reshard;
  params, loss-scaler state, StepGuard counts, and the RNG tracker are
  dp-replicated and ride rank 0's shard.  Only the data axis is
  elastic: the model layout (tp/pp) is part of the state's shape and a
  mismatch fails loudly.
- **Step watchdog** (:class:`StepWatchdog`): a heartbeat thread that
  notices a step exceeding its deadline (wedged collective, hung
  Pallas compile, dead tunnel), emits a structured
  ``watchdog.step_wedged`` record, drains the async checkpointer (so
  every ACCEPTED save is durable — the wedged step itself is lost by
  definition), and exits with :data:`EXIT_WEDGED` so a supervisor
  restarts with backoff (:func:`restart_backoff`).
- **Run controller** (:class:`ElasticRunController`): the loop-facing
  composition — restore-or-fresh, per-step heartbeat + chaos delivery
  (per-rank kill plans, wedged steps), bounded-disk saves.

Exit-code contract (what a supervisor keys restart policy on)::

    0            clean finish, or preemption save+drain (resume freely)
    EXIT_WEDGED  (75, EX_TEMPFAIL) watchdog killed a wedged step —
                 restart with backoff; the run resumes elastically
    EXIT_KILLED  (137, 128+SIGKILL) chaos hard-kill stand-in — the
                 supervisor restarts the survivors at the smaller world
    anything else: a real crash; do not blindly restart
"""

import os
import threading
import time
from typing import Any, Dict, Mapping, NamedTuple, Optional

import numpy as np

from apex_tpu.observability import flightrec as _flightrec
from apex_tpu.observability import metrics as _metrics
from apex_tpu.utils.logging import get_logger, log_structured

import logging

__all__ = [
    "EXIT_KILLED", "EXIT_WEDGED", "ElasticRestore", "ElasticRunController",
    "StepWatchdog", "restart_backoff", "restore_elastic_checkpoint",
    "save_elastic_checkpoint",
]

_logger = get_logger("apex_tpu.resilience")

#: sysexits EX_TEMPFAIL: "temporary failure, retry later" — the
#: watchdog's exit code.  Distinct from 0 (clean/preempted) and from
#: Python's generic 1 so a supervisor can apply restart-with-backoff to
#: exactly the wedged-step case.
EXIT_WEDGED = 75

#: 128+SIGKILL — what a hard-killed process reports; the chaos
#: harness's :class:`~apex_tpu.resilience.chaos.ChaosHostKilled` carries
#: it so the simulated death is indistinguishable to a supervisor.
EXIT_KILLED = 137


def restart_backoff(attempt: int, base: float = 2.0, cap: float = 300.0,
                    seed: int = 0, rng=None) -> float:
    """The documented supervisor backoff contract: full-jitter
    exponential — attempt ``k`` sleeps ``uniform(0, min(cap, base·2^k))``
    seconds.  Deterministic per ``(seed, attempt)`` so the chaos matrix
    can assert the schedule; a real supervisor seeds per host (rank) so
    a pod's restarts don't re-land in lockstep.

    ``rng`` (anything with ``uniform(a, b)``) overrides the per-(seed,
    attempt) derivation — the :class:`~apex_tpu.resilience.supervisor
    .Supervisor` tests pin exact jittered delays through it; when
    omitted the historical seeded behavior is unchanged."""
    import random

    if attempt < 0:
        raise ValueError(f"attempt must be >= 0, got {attempt}")
    hi = min(float(cap), float(base) * (2.0 ** int(attempt)))
    if rng is None:
        # int seed (not a tuple): tuple seeding is hash-based + deprecated
        rng = random.Random(int(seed) * 1000003 + int(attempt))
    return rng.uniform(0.0, hi)


# ---------------------------------------------------------- step watchdog
class StepWatchdog:
    """Heartbeat-deadline watchdog for the training loop.

    The loop calls :meth:`beat` once per step (host-side, a couple of
    float stores).  A background thread checks the time since the last
    beat; past ``deadline_sec`` the step is declared WEDGED: one
    structured ``watchdog.step_wedged`` record, a bounded drain of the
    async checkpointer (``drain_timeout_sec`` — the wedged thing may BE
    the filesystem), then ``os._exit(exit_code)`` so the supervisor
    restarts with backoff.  ``os._exit`` (not ``sys.exit``): the main
    thread is blocked inside a C dispatch holding the GIL-adjacent
    runtime; only a hard exit reliably escapes a wedged collective.

    ``first_deadline_sec`` covers the first interval (jit compiles make
    step 0 legitimately slow); defaults to ``deadline_sec``.
    ``on_fire`` replaces the exit for tests: called with the fire-info
    dict instead of terminating.  ``on_wedge`` is a best-effort
    pre-exit hook called with the fire-info dict BEFORE the drain (the
    goodput accountant's ``finalize("wedge")`` rides it, so the wedged
    tail is attributable after the ``os._exit``); its failures are
    swallowed — the watchdog must still exit.  ``preemption`` (a
    :class:`~apex_tpu.resilience.preemption.PreemptionHandler`) routes
    the drain through its re-entrancy guard so a watchdog firing while
    the loop already drains cannot double-enter the flush.
    """

    def __init__(self, deadline_sec: float, checkpointer=None,
                 exit_code: int = EXIT_WEDGED, poll_sec: Optional[float] = None,
                 first_deadline_sec: Optional[float] = None,
                 drain_timeout_sec: float = 60.0, on_fire=None,
                 preemption=None, on_wedge=None):
        if deadline_sec <= 0:
            raise ValueError(f"deadline_sec must be > 0, got {deadline_sec}")
        self.deadline_sec = float(deadline_sec)
        self.first_deadline_sec = float(
            first_deadline_sec if first_deadline_sec is not None
            else deadline_sec)
        self.exit_code = int(exit_code)
        self._checkpointer = checkpointer
        self._preemption = preemption
        self._drain_timeout = float(drain_timeout_sec)
        self._on_fire = on_fire
        self.on_wedge = on_wedge
        self._poll = float(poll_sec) if poll_sec is not None else min(
            1.0, self.deadline_sec / 4.0)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_beat: Optional[float] = None
        self._armed_at: Optional[float] = None
        self._step: Optional[int] = None
        self._interval_deadline = self.deadline_sec
        self.fired = False
        self.fire_info: Optional[dict] = None

    # ------------------------------------------------------- lifecycle
    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._armed_at = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="apex_tpu-step-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, 4 * self._poll))
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------- heartbeat
    def beat(self, step: Optional[int] = None,
             deadline: Optional[float] = None) -> None:
        """Record progress: the loop reached (the top of) ``step``.
        ``deadline`` overrides the allowance for THIS interval only —
        the loop grants the first step its jit-compile grace
        (``watchdog.beat(0, deadline=compile_grace)``) without
        loosening the steady-state deadline."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._interval_deadline = (float(deadline) if deadline is not None
                                       else self.deadline_sec)
            if step is not None:
                self._step = int(step)

    # --------------------------------------------------------- monitor
    def _expired(self, now: float):
        with self._lock:
            last, step = self._last_beat, self._step
            interval = self._interval_deadline
        if last is None:
            # never beaten: the first interval covers startup + compile
            start = self._armed_at if self._armed_at is not None else now
            elapsed, deadline = now - start, self.first_deadline_sec
        else:
            elapsed, deadline = now - last, interval
        return (elapsed, deadline, step) if elapsed >= deadline else None

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            hit = self._expired(time.monotonic())
            if hit is None:
                continue
            elapsed, deadline, step = hit
            self._fire(elapsed, deadline, step)
            return

    def _drain_bounded(self) -> str:
        """Drain the async checkpointer from a helper thread with a
        timeout: the wedge may be the filesystem itself, and a watchdog
        that hangs in its own cleanup protects nothing."""
        if self._checkpointer is None:
            return "no_checkpointer"
        done = threading.Event()
        outcome = {"state": "drain_timeout"}

        def flush():
            try:
                if self._preemption is not None:
                    self._preemption.drain(self._checkpointer)
                else:
                    self._checkpointer.wait_until_finished()
                outcome["state"] = "drained"
            except BaseException as e:  # noqa: BLE001 — report, then exit anyway
                outcome["state"] = f"drain_error:{type(e).__name__}"
            finally:
                done.set()

        threading.Thread(target=flush, daemon=True,
                         name="apex_tpu-watchdog-drain").start()
        done.wait(self._drain_timeout)
        return outcome["state"]

    def _fire(self, elapsed: float, deadline: float,
              step: Optional[int]) -> None:
        info = {"step": step, "elapsed_s": round(elapsed, 3),
                "deadline_s": deadline, "exit_code": self.exit_code}
        log_structured(_logger, logging.ERROR, "watchdog.step_wedged",
                       **info)
        # two SEPARATE best-effort blocks: a metrics registration clash
        # must not also rob the goodput accountant of its wedge stamp
        # (the attribution the report exists to make)
        _metrics.inc("apex_watchdog_wedges_total",
                     help="steps the watchdog declared wedged")
        try:
            if self.on_wedge is not None:
                self.on_wedge(info)
        except Exception as e:  # noqa: BLE001 — the hook is best-effort;
            # the watchdog's one job is to exit, so a broken accountant
            # must never wedge the wedge handler
            log_structured(_logger, logging.WARNING,
                           "watchdog.on_wedge_failed",
                           error=f"{type(e).__name__}: {e}")
        # flight-recorder dump AFTER the on_wedge hook (so the hook's
        # own records — the goodput wedge stamp, the forced anomaly
        # alert — are IN the dump) and BEFORE the drain (the wedged
        # thing may be the filesystem the drain is about to wait on).
        # dump_active is best-effort and a no-op without a recorder.
        info["flight_dump"] = _flightrec.dump_active(
            "wedge", wedged_step=step,
            elapsed_s=info["elapsed_s"], deadline_s=deadline)
        info["drain"] = self._drain_bounded()
        log_structured(_logger, logging.ERROR, "watchdog.exiting",
                       **info)
        self.fired = True
        self.fire_info = info
        if self._on_fire is not None:
            self._on_fire(info)
            return
        os._exit(self.exit_code)


# ------------------------------------------------------ elastic checkpoints
#: index.json metadata kinds — which restore path owns the state
ELASTIC_KIND_ZERO = "zero2"
ELASTIC_KIND_REPLICATED = "replicated"


class ElasticRestore(NamedTuple):
    """What :func:`restore_elastic_checkpoint` hands the loop."""

    step: int                    # steps already taken (resume here)
    params: Any
    opt_state: Any               # resharded for the LIVE world
    scaler: Optional[dict]       # DynamicLossScaler.state_dict payload
    guard: Optional[dict]        # StepGuard.state_dict payload
    rng: Optional[dict]          # rng_tracker_state_dict payload
    saved_world: int             # dp world the checkpoint was written at
    resharded: bool              # saved_world != live world


def _is_zero(optimizer) -> bool:
    return hasattr(optimizer, "sharded_state_dict")


def _step_dir(dir_path, step: int):
    from pathlib import Path

    return Path(dir_path) / f"step_{int(step):08d}"


def save_elastic_checkpoint(dir_path, step: int, *, params, opt_state,
                            optimizer, world_size: int,
                            mesh_axes: Optional[Mapping[str, int]] = None,
                            scaler_state: Optional[dict] = None,
                            guard_state: Optional[dict] = None,
                            rng_state: Optional[dict] = None,
                            checkpointer=None) -> str:
    """Publish the FULL train state as an elastic ``step_<N>/`` dir.

    ZeRO optimizers write one shard file per dp rank
    (:meth:`sharded_state_dict` slices the resident bucket state);
    replicated optimizers write a single world-size-1 shard (their
    state is dp-invariant — elastic by construction).  Rank 0's shard
    additionally carries the dp-replicated pieces: params, the step
    counter, loss-scaler / StepGuard / RNG-tracker state dicts.  The
    ``index.json`` (written FIRST — an interrupted save leaves an
    incomplete dir that ``latest_distributed_step`` skips as torn)
    records the world layout under the ``"elastic"`` key.

    ``scaler_state``/``guard_state``/``rng_state`` are the PLAIN DICTS
    from the owners' ``state_dict()`` methods, not live objects.  With
    a ``checkpointer`` (:class:`apex_tpu.io.AsyncCheckpointer`) shard
    writes are queued after a synchronous host snapshot; otherwise the
    write is synchronous.  Returns the step dir path."""
    from apex_tpu import io
    from apex_tpu.io.checkpoint import _shard_name, _write_index

    zero = _is_zero(optimizer)
    world = int(world_size) if zero else 1
    sd = _step_dir(dir_path, step)
    meta = {"elastic": {
        "kind": ELASTIC_KIND_ZERO if zero else ELASTIC_KIND_REPLICATED,
        "step": int(step),
        "dp_world": world,
        "mesh_axes": {k: int(v) for k, v in (mesh_axes or {}).items()},
    }}

    def rank_tree(r: int) -> Dict[str, Any]:
        if zero:
            tree: Dict[str, Any] = {
                "opt": optimizer.sharded_state_dict(opt_state, r, world)}
        else:
            tree = {"opt": opt_state if r == 0 else None}
        if r == 0:
            tree.update({
                "params": params,
                "step": np.int64(step),
                "scaler": scaler_state,
                "guard": guard_state,
                "rng": rng_state,
            })
        return tree

    if checkpointer is not None:
        # index first (synchronous, tiny) so a crash mid-queue leaves an
        # incomplete dir, then the shard snapshots ride the async queue
        _write_index(sd, world, extra=meta)
        for r in range(world):
            checkpointer.save(sd / _shard_name(r, world), rank_tree(r))
    else:
        for r in range(world):
            io.save_sharded_checkpoint(sd, rank_tree(r), r, world,
                                       index_extra=meta)
    log_structured(_logger, logging.INFO, "elastic.saved", step=int(step),
                   dp_world=world, path=str(sd))
    return str(sd)


def restore_elastic_checkpoint(dir_path, *, optimizer, world_size: int,
                               mesh_axes: Optional[Mapping[str, int]] = None,
                               step: Optional[int] = None
                               ) -> Optional[ElasticRestore]:
    """Resume the full train state from the newest complete elastic
    ``step_<N>/`` dir, RESHARDING for the live ``world_size`` when it
    differs from the saved one.

    Returns ``None`` when no ``step_*`` dirs exist (a legitimate fresh
    start) and propagates :class:`apex_tpu.io.AllCheckpointsTornError`
    when dirs exist but none is complete.  Fails loudly on a model-
    layout change (``mesh_axes`` vs the saved record — only the dp axis
    is elastic), on a replicated/ZeRO kind mismatch, and on the ZeRO
    engine's own state-compat checks (master precision, residual kind,
    incomplete shard sets).  ZeRO resharding routes through
    ``load_sharded_state_dicts`` — the one
    :func:`~apex_tpu.optimizers.bucketing.padded_total` pad formula —
    so a same-world resume is bitwise and a cross-world resume is
    payload-exact with re-derived padding."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import io

    if step is None:
        step = io.latest_distributed_step(dir_path)
        if step < 0:
            return None
    sd = _step_dir(dir_path, step)
    index = io.read_index(sd)
    meta = index.get("elastic")
    if meta is None:
        raise ValueError(
            f"{sd} is a sharded checkpoint but not an ELASTIC one (no "
            "'elastic' index metadata): it cannot be world-size-checked "
            "— load it with io.load_sharded_checkpoint directly")
    zero = _is_zero(optimizer)
    want_kind = ELASTIC_KIND_ZERO if zero else ELASTIC_KIND_REPLICATED
    if meta.get("kind") != want_kind:
        raise ValueError(
            f"elastic checkpoint kind {meta.get('kind')!r} does not match "
            f"this optimizer ({want_kind!r}): a replicated state cannot "
            "restore into a ZeRO optimizer or vice versa — construct the "
            "matching optimizer (the --zero flag must agree between save "
            "and resume)")
    saved_axes = {k: int(v) for k, v in (meta.get("mesh_axes") or {}).items()}
    live_axes = {k: int(v) for k, v in (mesh_axes or {}).items()}
    if saved_axes != live_axes:
        raise ValueError(
            f"elastic resume is data-parallel-only: checkpoint was saved "
            f"with model axes {saved_axes} but the live mesh has "
            f"{live_axes} — tp/pp reshape is a state-layout change this "
            "controller refuses to guess at")
    saved_world = int(meta.get("dp_world", index["world_size"]))
    shards = io.load_sharded_checkpoint(sd)
    r0 = shards[0]
    if zero:
        opt_world = getattr(optimizer, "world_size", None)
        if opt_world is not None and int(opt_world) != int(world_size):
            raise ValueError(
                f"optimizer was init'd for dp={opt_world} but the live "
                f"world is {world_size}: call init(params, world_size="
                f"{world_size}, ...) before restore so the bucket plan "
                "matches the resharded state")
        opt_state = type(optimizer).load_sharded_state_dicts(
            [d["opt"] for d in shards], world_size=int(world_size),
            store_param_remainders=optimizer.store_param_remainders,
            grad_sync_dtype=optimizer.grad_sync_dtype)
    else:
        opt_state = jax.tree.map(jnp.asarray, r0["opt"])
    params = jax.tree.map(jnp.asarray, r0["params"])
    resharded = zero and saved_world != int(world_size)
    log_structured(_logger, logging.INFO, "elastic.restored",
                   step=int(step), saved_world=saved_world,
                   live_world=int(world_size), resharded=resharded,
                   path=str(sd))
    return ElasticRestore(
        step=int(np.asarray(r0["step"])),
        params=params, opt_state=opt_state,
        scaler=r0.get("scaler"), guard=r0.get("guard"), rng=r0.get("rng"),
        saved_world=saved_world, resharded=resharded)


# ---------------------------------------------------------- run controller
class ElasticRunController:
    """Loop-facing composition of elastic checkpoints, the step
    watchdog, and the chaos pod faults.

    Usage (see ``examples/gpt/pretrain_gpt.py`` and
    ``tests/test_elastic.py``)::

        ctl = ElasticRunController(ckdir, optimizer, world_size=dp,
                                   mesh_axes={"tp": tp}, checkpointer=ckpt,
                                   watchdog=StepWatchdog(60, ckpt))
        restored = ctl.restore()          # None on a fresh start
        with ctl:                         # arms the watchdog
            for step in range(start, end):
                ctl.on_step(step)         # heartbeat + chaos delivery
                ...train...
                ctl.save(step + 1, params, state, ...)   # bounded disk

    ``rank`` is this host's index for the per-rank chaos kill plans —
    on a real pod ``jax.process_index()``, in the CPU matrix whatever
    simulated host the test is playing."""

    def __init__(self, checkpoint_dir, optimizer, world_size: int,
                 mesh_axes: Optional[Mapping[str, int]] = None,
                 checkpointer=None, watchdog: Optional[StepWatchdog] = None,
                 keep: int = 3, chaos=None, rank: int = 0):
        self.dir = checkpoint_dir
        self.optimizer = optimizer
        self.world_size = int(world_size)
        self.mesh_axes = dict(mesh_axes or {})
        self.checkpointer = checkpointer
        self.watchdog = watchdog
        self.keep = max(int(keep), 1)
        self.chaos = chaos
        self.rank = int(rank)

    # ------------------------------------------------------- lifecycle
    def __enter__(self):
        if self.watchdog is not None:
            self.watchdog.start()
        return self

    def __exit__(self, *exc):
        if self.watchdog is not None:
            self.watchdog.stop()
        return False

    # ------------------------------------------------------------ loop
    def on_step(self, step: int, deadline: Optional[float] = None) -> None:
        """Top-of-iteration hook: heartbeat the watchdog (``deadline``
        overrides this interval's allowance — the first step's compile
        grace), then deliver any chaos faults planned for ``step`` (a
        wedged dispatch the watchdog should catch; a hard host kill)."""
        if self.watchdog is not None:
            self.watchdog.beat(step, deadline=deadline)
        if self.chaos is not None:
            self.chaos.maybe_wedge_step(step)
            self.chaos.maybe_kill(step, rank=self.rank)

    def restore(self) -> Optional[ElasticRestore]:
        return restore_elastic_checkpoint(
            self.dir, optimizer=self.optimizer, world_size=self.world_size,
            mesh_axes=self.mesh_axes)

    def save(self, step: int, params, opt_state, scaler_state=None,
             guard_state=None, rng_state=None) -> str:
        path = save_elastic_checkpoint(
            self.dir, step, params=params, opt_state=opt_state,
            optimizer=self.optimizer, world_size=self.world_size,
            mesh_axes=self.mesh_axes, scaler_state=scaler_state,
            guard_state=guard_state, rng_state=rng_state,
            checkpointer=self.checkpointer)
        self.prune()
        return path

    def prune(self) -> None:
        """Bounded disk: drop step dirs older than the newest ``keep``
        (min 3 when async — the queue holds ≤2 pending saves, so the 3
        newest can still be in flight; a prune can never race a
        write)."""
        import shutil
        from pathlib import Path

        keep = max(self.keep, 3) if self.checkpointer is not None \
            else self.keep
        old = sorted(Path(self.dir).glob("step_*"))
        for d in old[:-keep]:
            shutil.rmtree(d, ignore_errors=True)
