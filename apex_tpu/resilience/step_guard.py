"""Step guard: device-side bad-step accounting over the amp predicate.

The repo already has the two halves of step skipping (the reference's
``noop_flag`` design): :func:`apex_tpu.amp.scaler.all_finite` produces
the predicate, and every fused optimizer predicates its whole update on
``grads_finite`` (``optimizers/base.predicate_step``/``select``).  What
was missing is the *survivability* layer above them — apex keeps runs
alive not just by skipping one bad step but by noticing when bad steps
stop being transient:

- :class:`GuardState` rides the train step as a tiny pytree: a step
  counter, the CONSECUTIVE bad-step count, and the total skipped.  The
  update is branch-free device arithmetic fused into the compiled step
  — no host sync per step, exactly like the scaler it composes with.
- :meth:`StepGuard.check` is the **host-side** budget check, run at
  whatever cadence the loop already syncs (the loss print, a
  checkpoint boundary): ``consecutive_bad >= max_consecutive_bad``
  raises :class:`BadStepBudgetExceeded` so the loop can flush its
  checkpointer and abort cleanly instead of burning hours skipping
  every step of a diverged run (hysteresis backoff can only save a run
  whose loss surface is still sane).

Wiring: ``make_train_step(..., step_guard=guard)`` threads the state
through the jitted step; see :mod:`apex_tpu.models.gpt`.
"""

from typing import NamedTuple, Optional

import jax.numpy as jnp

from apex_tpu.observability import flightrec as _flightrec
from apex_tpu.observability import metrics as _metrics

__all__ = ["GuardState", "StepGuard", "BadStepBudgetExceeded"]


class GuardState(NamedTuple):
    step: jnp.ndarray             # i32: steps attempted (incl. skipped)
    consecutive_bad: jnp.ndarray  # i32: current bad streak
    total_skipped: jnp.ndarray    # i32: lifetime skipped steps


class BadStepBudgetExceeded(RuntimeError):
    """The consecutive-bad-step budget is exhausted; abort to the last
    checkpoint.  Carries the offending (host-synced) guard state."""

    def __init__(self, msg: str, state: "GuardState"):
        super().__init__(msg)
        self.guard_state = state


class StepGuard:
    """Counts skipped steps device-side; enforces a budget host-side."""

    def __init__(self, max_consecutive_bad: int = 10):
        if max_consecutive_bad < 1:
            raise ValueError("max_consecutive_bad must be >= 1")
        self.max_consecutive_bad = int(max_consecutive_bad)

    # ----------------------------------------------------------- state
    def init(self) -> GuardState:
        return GuardState(
            step=jnp.int32(0),
            consecutive_bad=jnp.int32(0),
            total_skipped=jnp.int32(0),
        )

    def update(self, state: GuardState, all_finite_flag) -> GuardState:
        """Device-side accounting for one step outcome (branch-free)."""
        finite = jnp.asarray(all_finite_flag)
        bad = jnp.where(finite, jnp.int32(0),
                        state.consecutive_bad + jnp.int32(1))
        skipped = state.total_skipped + jnp.where(
            finite, jnp.int32(0), jnp.int32(1))
        return GuardState(
            step=state.step + jnp.int32(1),
            consecutive_bad=bad,
            total_skipped=skipped,
        )

    # ----------------------------------------------------- budget check
    def exhausted(self, state: GuardState) -> jnp.ndarray:
        """Device-side bool: budget blown (no host sync; usable inside
        jit, e.g. to gate a donated-state freeze)."""
        return state.consecutive_bad >= self.max_consecutive_bad

    def check(self, state: GuardState) -> GuardState:
        """HOST-side budget enforcement — call at a cadence that already
        syncs (the loss print).  Raises :class:`BadStepBudgetExceeded`
        when the streak hits the budget; returns the state otherwise."""
        if int(state.consecutive_bad) >= self.max_consecutive_bad:
            _metrics.inc("apex_bad_step_budget_aborts_total",
                         help="runs aborted on the consecutive-bad budget")
            # forensics BEFORE the raise: the abort unwinds to an exit,
            # and the dump is what names the divergence ramp (best-
            # effort no-op without an installed recorder)
            _flightrec.dump_active(
                "step_guard_abort",
                consecutive_bad=int(state.consecutive_bad),
                total_skipped=int(state.total_skipped),
                guard_step=int(state.step))
            raise BadStepBudgetExceeded(
                f"{int(state.consecutive_bad)} consecutive non-finite "
                f"steps (budget {self.max_consecutive_bad}); "
                f"{int(state.total_skipped)} skipped of "
                f"{int(state.step)} total — aborting to the last "
                f"checkpoint", state)
        return state

    # -------------------------------------------------- checkpoint I/O
    def state_dict(self, state: GuardState) -> dict:
        return {
            "step": int(state.step),
            "consecutive_bad": int(state.consecutive_bad),
            "total_skipped": int(state.total_skipped),
        }

    def load_state_dict(self, d: Optional[dict]) -> GuardState:
        if d is None:
            return self.init()
        return GuardState(
            step=jnp.int32(d["step"]),
            consecutive_bad=jnp.int32(d["consecutive_bad"]),
            total_skipped=jnp.int32(d["total_skipped"]),
        )
