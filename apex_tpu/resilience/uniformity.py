"""Runtime uniformity seam — turn cross-process divergence into a
named error instead of a device-side deadlock.

The failure class
-----------------
A multi-process run is one SPMD program launched N times.  Every
decision that shapes the compiled program — does the kernel registry
engage, which bucket plan does ZeRO build, which engine does a
dispatch site pick — must come out IDENTICAL on every process: a
single divergent rank lowers a different collective sequence, and the
pod wedges device-side with no error (each rank blocks in its own
next collective, forever; see
``apex_tpu.analysis.lowered.assert_same_collective_schedule`` for the
single-process lowering-level proof and APX209/210/211 in
``apex_tpu.analysis`` for the static tier).

This module is the RUNTIME tier of that defense: call sites record
the decisions they make, and an explicit check point compares them
across processes and raises :class:`UniformityError` naming the
divergent tag — a loud, attributable host-side crash the supervisor
can restart from, instead of a silent wedge the watchdog has to
shoot.

The contract
------------
- :func:`assert_uniform(tag, value) <assert_uniform>` is
  **record-by-default**: it digests ``value``, stores it under
  ``tag``, and returns.  It performs NO collective — call counts
  themselves diverge in exactly the buggy runs this seam exists to
  catch, and a per-call collective would wedge on the first
  divergence it was meant to report.
- :func:`check_uniform` is the explicit synchronization point: it
  gathers every process's recorded decisions (one bounded allgather)
  and raises on the first tag whose digests differ — including tags
  some processes recorded and others never reached.  Call it at a
  naturally-synchronous cadence: after init, after a plan build,
  every N steps (:class:`UniformityMonitor`).
- ``gather=`` / :func:`install_gather` inject the transport: tests
  (and the chaos harness) pass a fake gather returning divergent
  per-rank views to prove the failure mode single-process; real runs
  default to a ``jax.experimental.multihost_utils`` allgather, which
  degrades to a local no-op when ``process_count() == 1``.

The static analyzer treats a call to :func:`assert_uniform` /
:func:`check_uniform` / :func:`register_uniform` in a function as the
acquittal seam for its divergence rules: the code is saying "this
decision is rank-dependent ON PURPOSE, and here is where it gets
checked".
"""

import hashlib
import json
import logging
import threading
from typing import Callable, Dict, List, Optional

from apex_tpu.utils.logging import get_logger, log_structured

logger = get_logger(__name__)

__all__ = [
    "UniformityError", "UniformityMonitor", "assert_uniform",
    "check_uniform", "install_gather", "recorded_decisions",
    "register_uniform", "reset_uniformity", "uniform_digest",
]


class UniformityError(RuntimeError):
    """A cross-process decision diverged.  ``tag`` names the decision;
    ``views`` is the per-process digest list that disagreed."""

    def __init__(self, tag: str, views: List[Optional[str]]):
        self.tag = tag
        self.views = list(views)
        per_rank = ", ".join(
            f"process {i}: {v if v is not None else '<never recorded>'}"
            for i, v in enumerate(views))
        super().__init__(
            f"cross-process divergence on decision '{tag}': {per_rank} "
            f"— on a real pod this lowers different collective "
            f"schedules and wedges every host device-side; fix the "
            f"decision to be rank-uniform (thread it in as data) or "
            f"broadcast it from process 0 before use")


def uniform_digest(value) -> str:
    """Canonical short digest of a decision value: JSON with sorted
    keys (sets sorted, unknown types via ``repr``), sha256, 16 hex
    chars.  Stable across processes for equal logical values — the
    thing :func:`assert_uniform` records and compares."""
    def _default(obj):
        if isinstance(obj, (set, frozenset)):
            return sorted(obj, key=repr)
        if isinstance(obj, bytes):
            return obj.hex()
        if hasattr(obj, "tolist"):        # numpy scalars/arrays
            return obj.tolist()
        return repr(obj)

    blob = json.dumps(value, sort_keys=True, default=_default)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


_lock = threading.Lock()
_DECISIONS: Dict[str, str] = {}
_PROVIDERS: Dict[str, Callable[[], object]] = {}
_GATHER: Optional[Callable[[Dict[str, str]], List[Dict[str, str]]]] = None


def install_gather(fn) -> Optional[Callable]:
    """Install a transport for :func:`check_uniform`: a callable
    mapping this process's ``{tag: digest}`` payload to the list of
    every process's payload (index = process).  Pass None to restore
    the default (multihost allgather / single-process no-op).
    Returns the previously installed gather — the chaos harness and
    the tests use this seam to inject divergent per-rank views
    without a real multi-process run."""
    global _GATHER
    with _lock:
        prev, _GATHER = _GATHER, fn
    return prev


def reset_uniformity() -> None:
    """Clear recorded decisions, providers, and any installed gather
    (test isolation)."""
    global _GATHER
    with _lock:
        _DECISIONS.clear()
        _PROVIDERS.clear()
        _GATHER = None


def recorded_decisions() -> Dict[str, str]:
    """Snapshot of this process's recorded ``{tag: digest}`` map."""
    with _lock:
        return dict(_DECISIONS)


def assert_uniform(tag: str, value, *, gather=None) -> str:
    """Record a decision that must be identical on every process.

    Digests ``value`` and stores it under ``tag`` (last write wins —
    re-deciding is fine as long as every process re-decides the same
    way).  Performs NO collective: divergent runs diverge in call
    counts too, and a per-call gather would wedge exactly when it
    mattered.  The comparison happens at :func:`check_uniform`.

    ``gather=`` forces an eager check of just this tag through the
    given transport — the test/chaos spelling.  Returns the digest."""
    digest = uniform_digest(value)
    with _lock:
        _DECISIONS[tag] = digest
        g = gather if gather is not None else _GATHER
    if g is not None:
        _compare({tag: digest}, g({tag: digest}))
    return digest


def register_uniform(tag: str, provider: Callable[[], object]) -> None:
    """Register a zero-arg provider evaluated at every
    :func:`check_uniform` — for decisions best re-read at check time
    (registry status, plan fingerprints) rather than recorded once."""
    with _lock:
        _PROVIDERS[tag] = provider


def _default_gather(payload: Dict[str, str]) -> List[Dict[str, str]]:
    import jax

    if jax.process_count() <= 1:
        return [dict(payload)]
    import numpy as np
    from jax.experimental import multihost_utils

    # fixed-width wire format: json blob, length-prefixed, padded —
    # process_allgather needs one static shape on every process.
    cap = 1 << 16
    blob = json.dumps(payload, sort_keys=True).encode()
    if len(blob) > cap - 8:
        raise ValueError(
            f"uniformity payload {len(blob)}B exceeds the {cap}B "
            f"gather frame — too many tags; check more often")
    frame = np.zeros((cap,), np.uint8)
    frame[:8] = np.frombuffer(
        len(blob).to_bytes(8, "little"), np.uint8)
    frame[8:8 + len(blob)] = np.frombuffer(blob, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(frame))
    views = []
    for row in gathered.reshape(-1, cap):
        n = int.from_bytes(bytes(row[:8]), "little")
        views.append(json.loads(bytes(row[8:8 + n]).decode()))
    return views


def _compare(local: Dict[str, str],
             views: List[Dict[str, str]]) -> None:
    tags = sorted({t for v in views for t in v})
    for tag in tags:
        per_rank = [v.get(tag) for v in views]
        if len(set(per_rank)) > 1:
            log_structured(logger, logging.ERROR,
                           "uniformity_divergence", tag=tag,
                           views=per_rank)
            raise UniformityError(tag, per_rank)


def check_uniform(*, gather=None) -> Dict[str, str]:
    """The synchronization point: evaluate registered providers,
    gather every process's recorded decisions (one bounded
    allgather), and raise :class:`UniformityError` on the first tag
    whose digests differ across processes — including tags only SOME
    processes recorded, which is the divergent-call-count shape a
    per-call check could never report.  Single-process (and no
    installed gather): compares a single view, i.e. a no-op.
    Returns this process's ``{tag: digest}`` payload."""
    with _lock:
        providers = dict(_PROVIDERS)
    for tag, provider in providers.items():
        assert_uniform(tag, provider())
    with _lock:
        payload = dict(_DECISIONS)
        g = gather if gather is not None else _GATHER
    views = (g or _default_gather)(payload)
    _compare(payload, views)
    return payload


class UniformityMonitor:
    """Cadenced :func:`check_uniform`: ``on_step(step)`` checks every
    ``every_n_steps``-th step — a naturally-synchronous point, since
    every process runs the same step loop.  The step index itself is
    recorded, so a rank that slipped a step fails the check by
    construction."""

    def __init__(self, every_n_steps: int = 100, *, gather=None):
        if every_n_steps < 1:
            raise ValueError("every_n_steps must be >= 1")
        self.every_n_steps = int(every_n_steps)
        self._gather = gather

    def on_step(self, step: int) -> Optional[Dict[str, str]]:
        if step % self.every_n_steps != 0:
            return None
        assert_uniform("uniformity.monitor_step", int(step))
        return check_uniform(gather=self._gather)
