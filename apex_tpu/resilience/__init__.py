"""apex_tpu.resilience — keep long training runs alive on flaky hardware.

Apex's production value was never just speed: dynamic loss scaling with
hysteresis and found-inf semantics in every multi-tensor op exist so a
run *survives* bad steps.  This package is that pillar rebuilt on TPU
preemption semantics (TorchTitan treats the same concerns as a
first-class pillar of a pre-training stack):

- :mod:`~apex_tpu.resilience.fallback` — kernel fallback registry: a
  Pallas lowering/launch failure degrades once, with a structured
  warning, to the XLA reference impl instead of crashing the run.
- :mod:`~apex_tpu.resilience.step_guard` — device-side bad-step
  accounting over the amp ``all_finite`` predicate, with a host-side
  consecutive-bad-step budget that aborts cleanly to a checkpoint.
- :mod:`~apex_tpu.resilience.preemption` — SIGTERM/deadline hook that
  flushes the async checkpoint queue; pairs with
  :func:`apex_tpu.io.latest_checkpoint` torn-file-safe discovery.
- :mod:`~apex_tpu.resilience.elastic` — cross-world elastic resume
  (a dp=4 checkpoint reshards for dp=2 through the bucket plan's own
  pad formula), a step watchdog that drains and exits on wedged
  collectives, and the run controller composing both.
- :mod:`~apex_tpu.resilience.supervisor` — the self-healing restart
  orchestrator that CONSUMES the exit-code/backoff contract: launches
  the trainer or server as a child, restarts with full-jitter backoff,
  trips a crash-loop circuit breaker after K no-progress failures,
  and quarantines a corrupt newest checkpoint so one bad save never
  crash-loops a job to death (``pretrain_gpt.py --supervise``).
- :mod:`~apex_tpu.resilience.uniformity` — the runtime divergence
  seam: rank-shaping decisions (registry engagement, ZeRO bucket
  plans, serve config) are recorded via ``assert_uniform`` and
  compared across processes at explicit ``check_uniform`` points, so
  one divergent rank raises a named ``UniformityError`` instead of
  wedging the pod device-side (the runtime tier of the APX209–211
  static rules).
- :mod:`~apex_tpu.resilience.chaos` — deterministic fault injection
  (NaN grads, kernel-launch failures, preemptions, wedges, per-rank
  host kills, slow/failing checkpoint I/O, supervisor-level fault
  scripts incl. corrupt-newest-checkpoint) so all of the above is
  testable on the virtual 8-device CPU mesh today.

See ``docs/resilience.md`` for the fault model and usage.
"""

from apex_tpu.resilience.chaos import (
    ChaosHostKilled,
    ChaosIOError,
    ChaosKernelFailure,
    ChaosMonkey,
    ChaosPlan,
    SupervisorFault,
    SupervisorFaultScript,
    active_monkey,
    corrupt_newest_checkpoint,
)
from apex_tpu.resilience.elastic import (
    EXIT_KILLED,
    EXIT_WEDGED,
    ElasticRestore,
    ElasticRunController,
    StepWatchdog,
    restart_backoff,
    restore_elastic_checkpoint,
    save_elastic_checkpoint,
)
from apex_tpu.resilience.fallback import (
    KernelFallbackRegistry,
    get_registry,
    registry_engaged,
    trip_from_exception,
)
from apex_tpu.resilience.preemption import (
    PreemptionHandler,
    load_rng_tracker_state_dict,
    rng_tracker_state_dict,
)
from apex_tpu.resilience.step_guard import (
    BadStepBudgetExceeded,
    GuardState,
    StepGuard,
)
from apex_tpu.resilience.supervisor import (
    EXIT_CRASH_LOOP,
    Supervisor,
    strip_supervisor_argv,
)
from apex_tpu.resilience.uniformity import (
    UniformityError,
    UniformityMonitor,
    assert_uniform,
    check_uniform,
    install_gather,
    register_uniform,
    uniform_digest,
)

__all__ = [
    "BadStepBudgetExceeded",
    "ChaosHostKilled",
    "ChaosIOError",
    "ChaosKernelFailure",
    "ChaosMonkey",
    "ChaosPlan",
    "EXIT_CRASH_LOOP",
    "EXIT_KILLED",
    "EXIT_WEDGED",
    "ElasticRestore",
    "ElasticRunController",
    "GuardState",
    "KernelFallbackRegistry",
    "PreemptionHandler",
    "StepGuard",
    "StepWatchdog",
    "Supervisor",
    "SupervisorFault",
    "SupervisorFaultScript",
    "UniformityError",
    "UniformityMonitor",
    "active_monkey",
    "assert_uniform",
    "check_uniform",
    "corrupt_newest_checkpoint",
    "get_registry",
    "install_gather",
    "load_rng_tracker_state_dict",
    "register_uniform",
    "registry_engaged",
    "restart_backoff",
    "restore_elastic_checkpoint",
    "rng_tracker_state_dict",
    "save_elastic_checkpoint",
    "strip_supervisor_argv",
    "trip_from_exception",
    "uniform_digest",
]
