"""Deterministic fault injection for the resilience runtime.

The faults this harness injects are the ones the project has actually
suffered (VERDICT r5): NaN gradients mid-run, Pallas kernels dying at
launch on hardware they were never proven on, preemptions that kill a
run between checkpoint flushes, and tunnel wedges that hang a section
forever.  Each is injected *deterministically* (a static plan, no RNG,
no clocks) so the virtual 8-device mesh tests can assert exact recovery
behavior — skip THIS step, fall back on THAT kernel, resume at exactly
step k — today on CPU and unchanged on real TPU later.

Injection points (each sits at the seam where the real fault would
surface, so the production code path under test is the real one):

- **NaN grads** — :meth:`ChaosMonkey.grad_fault` returns a ``1.0``/NaN
  f32 scalar from a *static* step set; the train step multiplies it
  into the loss before ``grad``, so the NaN propagates into every
  gradient device-side (no per-step host sync, no retrace — the step
  set is baked into the compiled program as a constant).
- **kernel-launch failure** — the kernel fallback registry calls
  :func:`check_kernel` immediately before invoking a Pallas entry
  point; an armed plan raises :class:`ChaosKernelFailure` there, which
  is indistinguishable (to the registry) from a Mosaic lowering error.
- **preemption** — :meth:`ChaosMonkey.maybe_preempt` flips a
  :class:`~apex_tpu.resilience.preemption.PreemptionHandler` exactly as
  a real SIGTERM would.
- **wedged/slow sections** — :meth:`ChaosMonkey.maybe_wedge` sleeps at
  a named site, exercising watchdog/timeout paths (bench.py's `_try`,
  the subprocess section runner).

Activate with ``with monkey.active(): ...`` — module-global so the
registry and guards deep inside jitted-step construction see it without
threading a handle through every layer (the plan itself is static data,
so nothing traced ever reads mutable chaos state except the kernel
check, which runs at trace/launch time by design).
"""

import contextlib
import dataclasses
import threading
import time
from typing import Dict, FrozenSet, Iterable, Mapping, Optional

import logging

from apex_tpu.utils.logging import get_logger, log_structured

__all__ = [
    "ChaosKernelFailure", "ChaosPlan", "ChaosMonkey", "active_monkey",
    "check_kernel",
]

_logger = get_logger("apex_tpu.resilience")


class ChaosKernelFailure(RuntimeError):
    """Injected stand-in for a Mosaic lowering / kernel-launch error."""


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """Static description of the faults to inject.

    ``nan_grad_steps``: step indices whose gradients are poisoned.
    ``kernel_failures``: kernel name -> how many calls fail (a large
    count means "every call until the registry trips").
    ``preempt_at_step``: loop step at which a simulated SIGTERM lands.
    ``wedge_seconds``: site name -> seconds to sleep when reached.
    """

    nan_grad_steps: FrozenSet[int] = frozenset()
    kernel_failures: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    preempt_at_step: Optional[int] = None
    wedge_seconds: Mapping[str, float] = dataclasses.field(
        default_factory=dict)

    @staticmethod
    def make(nan_grad_steps: Iterable[int] = (),
             kernel_failures: Optional[Mapping[str, int]] = None,
             preempt_at_step: Optional[int] = None,
             wedge_seconds: Optional[Mapping[str, float]] = None
             ) -> "ChaosPlan":
        return ChaosPlan(
            nan_grad_steps=frozenset(int(s) for s in nan_grad_steps),
            kernel_failures=dict(kernel_failures or {}),
            preempt_at_step=preempt_at_step,
            wedge_seconds=dict(wedge_seconds or {}),
        )


class ChaosMonkey:
    """One armed fault plan plus the mutable counters it burns down."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._kernel_budget: Dict[str, int] = dict(plan.kernel_failures)
        self.injected: Dict[str, int] = {}  # fault kind -> times fired

    # ------------------------------------------------------- NaN grads
    def grad_fault(self, step):
        """f32 scalar: NaN on planned steps, 1.0 otherwise.

        ``step`` may be a traced i32 (e.g. a guard-state step counter):
        the planned set lowers to a constant array, the comparison to a
        handful of device ops — nothing here syncs with the host."""
        import jax.numpy as jnp

        if not self.plan.nan_grad_steps:
            return jnp.float32(1.0)
        steps = jnp.asarray(sorted(self.plan.nan_grad_steps), jnp.int32)
        hit = jnp.any(steps == jnp.asarray(step, jnp.int32))
        return jnp.where(hit, jnp.float32(jnp.nan), jnp.float32(1.0))

    # ------------------------------------------------ kernel failures
    def fail_kernel(self, name: str) -> None:
        """Raise the injected launch failure if ``name`` is armed."""
        with self._lock:
            left = self._kernel_budget.get(name, 0)
            if left <= 0:
                return
            self._kernel_budget[name] = left - 1
            self.injected[f"kernel:{name}"] = \
                self.injected.get(f"kernel:{name}", 0) + 1
        log_structured(_logger, logging.INFO, "chaos.kernel_failure",
                       kernel=name, remaining=left - 1)
        raise ChaosKernelFailure(
            f"injected launch failure for kernel {name!r}")

    # ----------------------------------------------------- preemption
    def maybe_preempt(self, step: int, handler) -> bool:
        """Deliver the planned preemption to ``handler`` at ``step``."""
        if self.plan.preempt_at_step is None \
                or int(step) != int(self.plan.preempt_at_step):
            return False
        with self._lock:
            self.injected["preemption"] = \
                self.injected.get("preemption", 0) + 1
        log_structured(_logger, logging.INFO, "chaos.preemption", step=int(step))
        handler.simulate()
        return True

    # -------------------------------------------------------- wedges
    def maybe_wedge(self, site: str) -> float:
        """Sleep the planned seconds at ``site`` (0.0 when unarmed)."""
        secs = float(self.plan.wedge_seconds.get(site, 0.0))
        if secs > 0.0:
            with self._lock:
                self.injected[f"wedge:{site}"] = \
                    self.injected.get(f"wedge:{site}", 0) + 1
            log_structured(_logger, logging.INFO, "chaos.wedge",
                           site=site, seconds=secs)
            time.sleep(secs)
        return secs

    # ---------------------------------------------------- activation
    @contextlib.contextmanager
    def active(self):
        """Install this monkey as the process-wide active one."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev


_ACTIVE: Optional[ChaosMonkey] = None


def active_monkey() -> Optional[ChaosMonkey]:
    return _ACTIVE


def check_kernel(name: str) -> None:
    """Fallback-registry hook: raise the injected failure when armed."""
    m = _ACTIVE
    if m is not None:
        m.fail_kernel(name)
