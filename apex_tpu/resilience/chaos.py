"""Deterministic fault injection for the resilience runtime.

The faults this harness injects are the ones the project has actually
suffered (VERDICT r5): NaN gradients mid-run, Pallas kernels dying at
launch on hardware they were never proven on, preemptions that kill a
run between checkpoint flushes, and tunnel wedges that hang a section
forever.  Each is injected *deterministically* (a static plan, no RNG,
no clocks) so the virtual 8-device mesh tests can assert exact recovery
behavior — skip THIS step, fall back on THAT kernel, resume at exactly
step k — today on CPU and unchanged on real TPU later.

Injection points (each sits at the seam where the real fault would
surface, so the production code path under test is the real one):

- **NaN grads** — :meth:`ChaosMonkey.grad_fault` returns a ``1.0``/NaN
  f32 scalar from a *static* step set; the train step multiplies it
  into the loss before ``grad``, so the NaN propagates into every
  gradient device-side (no per-step host sync, no retrace — the step
  set is baked into the compiled program as a constant).
- **kernel-launch failure** — the kernel fallback registry calls
  :func:`check_kernel` immediately before invoking a Pallas entry
  point; an armed plan raises :class:`ChaosKernelFailure` there, which
  is indistinguishable (to the registry) from a Mosaic lowering error.
- **preemption** — :meth:`ChaosMonkey.maybe_preempt` flips a
  :class:`~apex_tpu.resilience.preemption.PreemptionHandler` exactly as
  a real SIGTERM would.
- **wedged/slow sections** — :meth:`ChaosMonkey.maybe_wedge` sleeps at
  a named site, exercising watchdog/timeout paths (bench.py's `_try`,
  the subprocess section runner).

Activate with ``with monkey.active(): ...`` — module-global so the
registry and guards deep inside jitted-step construction see it without
threading a handle through every layer (the plan itself is static data,
so nothing traced ever reads mutable chaos state except the kernel
check, which runs at trace/launch time by design).
"""

import contextlib
import dataclasses
import threading
import time
from typing import Dict, FrozenSet, Iterable, Mapping, Optional

import logging

from apex_tpu.utils.logging import get_logger, log_structured

__all__ = [
    "ChaosHostKilled", "ChaosIOError", "ChaosKernelFailure", "ChaosPlan",
    "ChaosMonkey", "ChaosReplicaKilled", "SupervisorFault",
    "SupervisorFaultScript", "active_monkey", "check_io", "check_kernel",
    "corrupt_newest_checkpoint",
]

_logger = get_logger("apex_tpu.resilience")


class ChaosKernelFailure(RuntimeError):
    """Injected stand-in for a Mosaic lowering / kernel-launch error."""


class ChaosHostKilled(SystemExit):
    """Injected stand-in for one host of N dying hard (spot reclaim
    past the grace window, kernel panic): no save, no drain, no exit
    handler — the pod-scale fault the elastic controller must resume
    from at a SMALLER world.  A ``SystemExit`` subclass so an unwitting
    ``except Exception`` recovery path cannot swallow the death; the
    carried code is :data:`~apex_tpu.resilience.elastic.EXIT_KILLED`."""

    def __init__(self, rank: int, step: int, code: int):
        super().__init__(code)
        self.rank = int(rank)
        self.step = int(step)

    def __str__(self):
        return (f"injected hard kill of host rank {self.rank} at step "
                f"{self.step} (exit {self.code})")


class ChaosReplicaKilled(SystemExit):
    """Injected stand-in for one serving replica of N dying hard
    (SIGKILL, OOM, host loss): no drain, no manifest, no exit handler —
    the fleet fault the frontend's request journal exists to replay
    from.  A ``SystemExit`` subclass for the same reason as
    :class:`ChaosHostKilled`; the carried code is
    :data:`~apex_tpu.resilience.elastic.EXIT_KILLED` (137)."""

    def __init__(self, replica_id: str, step: int, code: int):
        super().__init__(code)
        self.replica_id = str(replica_id)
        self.step = int(step)

    def __str__(self):
        return (f"injected hard kill of serving replica "
                f"{self.replica_id!r} at replica step {self.step} "
                f"(exit {self.code})")


class ChaosIOError(OSError):
    """Injected transient filesystem error on a checkpoint I/O site —
    an ``OSError`` subclass so it takes exactly the retry-with-backoff
    path real NFS/GCS hiccups take (``io.checkpoint._with_io_retries``)."""


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """Static description of the faults to inject.

    ``nan_grad_steps``: step indices whose gradients are poisoned.
    ``kernel_failures``: kernel name -> how many calls fail (a large
    count means "every call until the registry trips").
    ``preempt_at_step``: loop step at which a simulated SIGTERM lands.
    ``wedge_seconds``: site name -> seconds to sleep when reached.

    Pod-scale faults (all deterministic, all CPU-testable):

    ``kill_at``: host rank -> loop step at which that host dies HARD
    (:meth:`ChaosMonkey.maybe_kill` raises :class:`ChaosHostKilled` —
    no save, no drain; the elastic-resume scenario "preempt one host
    of N").  Per-rank, so a matrix test can kill host 2 of 4 and
    resume the survivors at world 3.
    ``wedge_step_at``: loop step whose dispatch wedges for
    ``wedge_step_seconds`` (a hung whole-step: dead tunnel, compile
    hang) — the step-watchdog fault.
    ``wedge_collective_rank``/``wedge_collective_at_step``: ONE mesh
    rank sleeps ``wedge_collective_seconds`` INSIDE the compiled step,
    immediately before the gradient sync — its peers block device-side
    in the collective waiting for it, which is exactly how a real
    wedged all-reduce presents (see ``models/gpt.py`` ``chaos=``).
    ``io_failures``: I/O site name (``"ckpt.write"``/``"ckpt.read"``)
    -> how many operations raise :class:`ChaosIOError` before the
    "filesystem" recovers; ``io_delay_seconds``: site -> seconds each
    operation stalls first (slow disk).  Both ride
    :func:`check_io` inside ``io.checkpoint``'s retry loop.

    Serving-fleet faults (``inference.fleet`` — per-replica, keyed on
    the replica's OWN step count so a 2-replica plan kills exactly one
    mid-stream):

    ``kill_replica_at``: replica id -> replica step at which that
    replica dies HARD (:meth:`ChaosMonkey.maybe_kill_replica` raises
    :class:`ChaosReplicaKilled` — no drain, no manifest; the frontend
    must replay from its own journal, exit-137 shape).
    ``wedge_replica_at``: replica id -> replica step at which that
    replica's decode step wedges (:meth:`ChaosMonkey
    .maybe_wedge_replica` returns True once) — the exit-75 shape: the
    watchdog path emits the ``serve.step_wedged`` manifest and the
    frontend replays THAT.
    """

    nan_grad_steps: FrozenSet[int] = frozenset()
    kernel_failures: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    preempt_at_step: Optional[int] = None
    wedge_seconds: Mapping[str, float] = dataclasses.field(
        default_factory=dict)
    kill_at: Mapping[int, int] = dataclasses.field(default_factory=dict)
    wedge_step_at: Optional[int] = None
    wedge_step_seconds: float = 0.0
    wedge_collective_rank: Optional[int] = None
    wedge_collective_at_step: Optional[int] = None
    wedge_collective_seconds: float = 0.0
    io_failures: Mapping[str, int] = dataclasses.field(default_factory=dict)
    io_delay_seconds: Mapping[str, float] = dataclasses.field(
        default_factory=dict)
    kill_replica_at: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    wedge_replica_at: Mapping[str, int] = dataclasses.field(
        default_factory=dict)

    @staticmethod
    def make(nan_grad_steps: Iterable[int] = (),
             kernel_failures: Optional[Mapping[str, int]] = None,
             preempt_at_step: Optional[int] = None,
             wedge_seconds: Optional[Mapping[str, float]] = None,
             kill_at: Optional[Mapping[int, int]] = None,
             wedge_step_at: Optional[int] = None,
             wedge_step_seconds: float = 0.0,
             wedge_collective_rank: Optional[int] = None,
             wedge_collective_at_step: Optional[int] = None,
             wedge_collective_seconds: float = 0.0,
             io_failures: Optional[Mapping[str, int]] = None,
             io_delay_seconds: Optional[Mapping[str, float]] = None,
             kill_replica_at: Optional[Mapping[str, int]] = None,
             wedge_replica_at: Optional[Mapping[str, int]] = None
             ) -> "ChaosPlan":
        return ChaosPlan(
            nan_grad_steps=frozenset(int(s) for s in nan_grad_steps),
            kernel_failures=dict(kernel_failures or {}),
            preempt_at_step=preempt_at_step,
            wedge_seconds=dict(wedge_seconds or {}),
            kill_at={int(r): int(s) for r, s in (kill_at or {}).items()},
            wedge_step_at=wedge_step_at,
            wedge_step_seconds=float(wedge_step_seconds),
            wedge_collective_rank=wedge_collective_rank,
            wedge_collective_at_step=wedge_collective_at_step,
            wedge_collective_seconds=float(wedge_collective_seconds),
            io_failures=dict(io_failures or {}),
            io_delay_seconds=dict(io_delay_seconds or {}),
            kill_replica_at={str(r): int(s)
                             for r, s in (kill_replica_at or {}).items()},
            wedge_replica_at={str(r): int(s)
                              for r, s in (wedge_replica_at or {}).items()},
        )


class ChaosMonkey:
    """One armed fault plan plus the mutable counters it burns down."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._kernel_budget: Dict[str, int] = dict(plan.kernel_failures)
        self._io_budget: Dict[str, int] = dict(plan.io_failures)
        self.injected: Dict[str, int] = {}  # fault kind -> times fired

    def _count(self, kind: str) -> None:
        with self._lock:
            self.injected[kind] = self.injected.get(kind, 0) + 1

    # ------------------------------------------------------- NaN grads
    def grad_fault(self, step):
        """f32 scalar: NaN on planned steps, 1.0 otherwise.

        ``step`` may be a traced i32 (e.g. a guard-state step counter):
        the planned set lowers to a constant array, the comparison to a
        handful of device ops — nothing here syncs with the host."""
        import jax.numpy as jnp

        if not self.plan.nan_grad_steps:
            return jnp.float32(1.0)
        steps = jnp.asarray(sorted(self.plan.nan_grad_steps), jnp.int32)
        hit = jnp.any(steps == jnp.asarray(step, jnp.int32))
        return jnp.where(hit, jnp.float32(jnp.nan), jnp.float32(1.0))

    # ------------------------------------------------ kernel failures
    def fail_kernel(self, name: str) -> None:
        """Raise the injected launch failure if ``name`` is armed."""
        with self._lock:
            left = self._kernel_budget.get(name, 0)
            if left <= 0:
                return
            self._kernel_budget[name] = left - 1
            self.injected[f"kernel:{name}"] = \
                self.injected.get(f"kernel:{name}", 0) + 1
        log_structured(_logger, logging.INFO, "chaos.kernel_failure",
                       kernel=name, remaining=left - 1)
        raise ChaosKernelFailure(
            f"injected launch failure for kernel {name!r}")

    # ----------------------------------------------------- preemption
    def maybe_preempt(self, step: int, handler) -> bool:
        """Deliver the planned preemption to ``handler`` at ``step``."""
        if self.plan.preempt_at_step is None \
                or int(step) != int(self.plan.preempt_at_step):
            return False
        with self._lock:
            self.injected["preemption"] = \
                self.injected.get("preemption", 0) + 1
        log_structured(_logger, logging.INFO, "chaos.preemption", step=int(step))
        handler.simulate()
        return True

    # -------------------------------------------------------- wedges
    def maybe_wedge(self, site: str) -> float:
        """Sleep the planned seconds at ``site`` (0.0 when unarmed)."""
        secs = float(self.plan.wedge_seconds.get(site, 0.0))
        if secs > 0.0:
            with self._lock:
                self.injected[f"wedge:{site}"] = \
                    self.injected.get(f"wedge:{site}", 0) + 1
            log_structured(_logger, logging.INFO, "chaos.wedge",
                           site=site, seconds=secs)
            time.sleep(secs)
        return secs

    # ------------------------------------------------ pod-scale faults
    def maybe_kill(self, step, rank: int = 0) -> None:
        """Deliver the planned HARD death of host ``rank`` at ``step``:
        raises :class:`ChaosHostKilled` (a ``SystemExit``) with the
        elastic runtime's documented kill exit code — no save, no
        drain, mirroring a spot VM vanishing past its grace window.
        The elastic matrix tests catch it to play the supervisor; the
        example lets it exit the process."""
        planned = self.plan.kill_at.get(int(rank))
        if planned is None or int(step) != int(planned):
            return
        self._count(f"kill:{int(rank)}")
        from apex_tpu.resilience.elastic import EXIT_KILLED

        log_structured(_logger, logging.WARNING, "chaos.host_killed",
                       rank=int(rank), step=int(step))
        raise ChaosHostKilled(int(rank), int(step), EXIT_KILLED)

    def maybe_wedge_step(self, step) -> float:
        """Host-side whole-step wedge: sleep the planned seconds before
        dispatching ``step`` (a dead tunnel / hung compile presents as
        the dispatch never returning).  Returns the seconds slept —
        the step watchdog should fire mid-sleep."""
        if self.plan.wedge_step_at is None \
                or int(step) != int(self.plan.wedge_step_at):
            return 0.0
        secs = float(self.plan.wedge_step_seconds)
        if secs > 0.0:
            self._count("wedge_step")
            log_structured(_logger, logging.INFO, "chaos.wedge_step",
                           step=int(step), seconds=secs)
            time.sleep(secs)
        return secs

    # ---------------------------------------------- serving-fleet faults
    def maybe_kill_replica(self, replica_id: str, step: int) -> None:
        """Deliver the planned HARD death of serving replica
        ``replica_id`` at ITS step ``step``: raises
        :class:`ChaosReplicaKilled` (a ``SystemExit``, exit 137) — no
        drain, no wedge manifest, so the only replay source is the
        frontend's own request journal."""
        planned = self.plan.kill_replica_at.get(str(replica_id))
        if planned is None or int(step) != int(planned):
            return
        self._count(f"kill_replica:{replica_id}")
        from apex_tpu.resilience.elastic import EXIT_KILLED

        log_structured(_logger, logging.WARNING, "chaos.replica_killed",
                       replica=str(replica_id), step=int(step))
        raise ChaosReplicaKilled(str(replica_id), int(step), EXIT_KILLED)

    def maybe_wedge_replica(self, replica_id: str, step: int) -> bool:
        """True exactly once, at the planned (replica, step): the
        replica's decode dispatch has wedged (dead tunnel shape) — the
        caller runs the watchdog path (``serve.step_wedged`` manifest,
        exit 75) instead of sleeping a real watchdog out."""
        planned = self.plan.wedge_replica_at.get(str(replica_id))
        if planned is None or int(step) != int(planned):
            return False
        self._count(f"wedge_replica:{replica_id}")
        log_structured(_logger, logging.WARNING, "chaos.replica_wedged",
                       replica=str(replica_id), step=int(step))
        return True

    def collective_wedge_callback(self, step, rank) -> None:
        """In-step host callback (see ``models/gpt.py``): sleep on
        exactly the planned (rank, step) so that rank arrives LATE at
        the next collective while its peers block device-side waiting —
        the truthful shape of a wedged all-reduce.  ``step``/``rank``
        arrive as 0-d arrays from ``jax.experimental.io_callback``."""
        if int(step) != int(self.plan.wedge_collective_at_step) \
                or int(rank) != int(self.plan.wedge_collective_rank):
            return
        secs = float(self.plan.wedge_collective_seconds)
        self._count("wedge_collective")
        log_structured(_logger, logging.INFO, "chaos.wedge_collective",
                       step=int(step), rank=int(rank), seconds=secs)
        time.sleep(secs)

    @property
    def wedges_collective(self) -> bool:
        return (self.plan.wedge_collective_at_step is not None
                and self.plan.wedge_collective_rank is not None
                and self.plan.wedge_collective_seconds > 0.0)

    # ------------------------------------------------------ I/O faults
    def io_fault(self, site: str) -> None:
        """Checkpoint-I/O seam: stall the planned delay, then raise
        :class:`ChaosIOError` while the site's failure budget lasts —
        each retry of ``io.checkpoint._with_io_retries`` burns one
        budget unit, so a budget smaller than the retry cap means "the
        filesystem recovers mid-retry" and larger means "stays down"."""
        delay = float(self.plan.io_delay_seconds.get(site, 0.0))
        if delay > 0.0:
            self._count(f"io_delay:{site}")
            time.sleep(delay)
        with self._lock:
            left = self._io_budget.get(site, 0)
            if left <= 0:
                return
            self._io_budget[site] = left - 1
        self._count(f"io_fail:{site}")
        log_structured(_logger, logging.INFO, "chaos.io_failure",
                       site=site, remaining=left - 1)
        raise ChaosIOError(f"injected transient I/O failure at {site!r} "
                           f"({left - 1} more planned)")

    # ---------------------------------------------------- activation
    @contextlib.contextmanager
    def active(self):
        """Install this monkey as the process-wide active one."""
        global _ACTIVE
        prev = _ACTIVE
        _ACTIVE = self
        try:
            yield self
        finally:
            _ACTIVE = prev


# ------------------------------------------------- supervisor-level faults
def corrupt_newest_checkpoint(dir_path, flip_bytes: int = 64) -> str:
    """Deterministic stand-in for silent storage corruption: XOR the
    LAST ``flip_bytes`` of the newest restore candidate (a complete
    ``step_*`` dir's rank-0 shard, or the newest single-file
    checkpoint) with 0xFF — **size-preserving**, so the index
    completeness check and the torn-size validation both still pass and
    only the blob-crc corruption probe (``io.probe_checkpoint``) or the
    load-time crc verify can see it.  The tail of the file is blob
    bytes by the format's layout (header first), so the flip never
    fabricates a different-but-parseable header.  Returns the corrupted
    file's path; raises ``FileNotFoundError`` when the dir holds no
    complete checkpoint to corrupt."""
    import os
    from pathlib import Path

    from apex_tpu.io.checkpoint import (
        _shard_name, checkpoint_step, latest_distributed_step, read_index,
    )

    d = Path(dir_path)
    target = None
    if any(d.glob("step_*/index.json")):
        step = latest_distributed_step(d)
        if step >= 0:
            sd = d / f"step_{step:08d}"
            world = int(read_index(sd)["world_size"])
            target = sd / _shard_name(0, world)
    else:
        cands = sorted(
            (p for p in d.iterdir()
             if p.is_file() and p.suffix in (".ckpt", ".apex")),
            key=checkpoint_step, reverse=True) if d.is_dir() else []
        target = cands[0] if cands else None
    if target is None or not target.exists():
        raise FileNotFoundError(
            f"no complete checkpoint under {dir_path} to corrupt")
    size = target.stat().st_size
    n = min(int(flip_bytes), size)
    # r+b (no truncate, no append): the size must not change — that is
    # the whole point of this fault class
    with open(target, "r+b") as f:
        f.seek(size - n)
        tail = f.read(n)
        f.seek(size - n)
        f.write(bytes(b ^ 0xFF for b in tail))
        f.flush()
        os.fsync(f.fileno())
    log_structured(_logger, logging.WARNING, "chaos.checkpoint_corrupted",
                   path=str(target), flipped_bytes=n)
    return str(target)


@dataclasses.dataclass(frozen=True)
class SupervisorFault:
    """One restart attempt's planned fault, applied by the
    :class:`~apex_tpu.resilience.supervisor.Supervisor` around a spawn:
    ``extra_args`` append to the child argv (arming the child-side
    chaos flags — kill at step N, wedge a step — for THIS attempt
    only, so the fault does not recur on every relaunch), and
    ``corrupt_newest_checkpoint`` flips bytes in the newest restore
    candidate before the child launches."""

    extra_args: tuple = ()
    corrupt_newest_checkpoint: bool = False


class SupervisorFaultScript:
    """attempt index -> :class:`SupervisorFault`: the deterministic
    script that turns the whole fault gauntlet (kill, wedge storm,
    corrupt checkpoint, recover) into ONE supervised invocation.

    JSON shape (``from_file`` / ``pretrain_gpt.py --fault-script``)::

        {"0": {"args": ["--chaos-kill-at-step", "3"]},
         "1": {"args": ["--watchdog-secs", "3",
                         "--chaos-wedge-step", "4",
                         "--chaos-wedge-secs", "300"]},
         "2": {"corrupt_newest_checkpoint": true}}

    Unlisted attempts run clean."""

    def __init__(self, faults: Mapping[int, SupervisorFault]):
        self.faults = {int(k): v for k, v in dict(faults).items()}

    @classmethod
    def from_dict(cls, spec: Mapping) -> "SupervisorFaultScript":
        faults = {}
        for k, v in dict(spec).items():
            unknown = set(v) - {"args", "corrupt_newest_checkpoint"}
            if unknown:
                raise ValueError(
                    f"fault script attempt {k!r}: unknown key(s) "
                    f"{sorted(unknown)} (valid: args, "
                    "corrupt_newest_checkpoint)")
            faults[int(k)] = SupervisorFault(
                extra_args=tuple(str(a) for a in v.get("args", ())),
                corrupt_newest_checkpoint=bool(
                    v.get("corrupt_newest_checkpoint", False)))
        return cls(faults)

    @classmethod
    def from_file(cls, path) -> "SupervisorFaultScript":
        import json

        with open(path) as f:
            return cls.from_dict(json.load(f))

    def fault_for(self, attempt: int) -> Optional[SupervisorFault]:
        return self.faults.get(int(attempt))


_ACTIVE: Optional[ChaosMonkey] = None


def active_monkey() -> Optional[ChaosMonkey]:
    return _ACTIVE


def check_kernel(name: str) -> None:
    """Fallback-registry hook: raise the injected failure when armed."""
    m = _ACTIVE
    if m is not None:
        m.fail_kernel(name)


def check_io(site: str) -> None:
    """Checkpoint-I/O hook (``io.checkpoint`` calls this inside its
    retry loop): stall/raise the injected fault when armed."""
    m = _ACTIVE
    if m is not None:
        m.io_fault(site)
