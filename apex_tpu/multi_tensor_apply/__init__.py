"""API-parity shim for ``apex.multi_tensor_apply``.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``.  The
chunked dispatch machinery is unnecessary under XLA; ``multi_tensor_applier``
here simply calls the op with the tensor lists.  Kept so reference users
find the familiar entry point.
"""

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    tree_not_finite,
)


class MultiTensorApply:
    """Callable matching ``multi_tensor_applier(op, noop_flag, lists, *args)``.

    ``noop_flag`` is ignored on input (XLA is functional); the op's returned
    ``found_inf`` plays its role.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args):
        return op(*tensor_lists, *args)


multi_tensor_applier = MultiTensorApply(2048 * 32)

__all__ = [
    "MultiTensorApply",
    "multi_tensor_applier",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "tree_not_finite",
]
