"""API-parity shim for ``apex.multi_tensor_apply``.

Reference: ``apex/multi_tensor_apply/multi_tensor_apply.py:3-30``.  The
chunked dispatch machinery is unnecessary under XLA; ``multi_tensor_applier``
here calls the op with the tensor lists.  Kept so reference users find
the familiar entry point.

The real multi-tensor engine — the TPU analogue of the reference's
chunked kernels — is the bucket plan in
:mod:`apex_tpu.optimizers.bucketing`: the ops below all accept a
:class:`~apex_tpu.optimizers.bucketing.Buckets` wherever a pytree is
accepted, so one flat dtype bucket plays the role of the reference's
≤110-pointer chunk table.
"""

import jax.numpy as jnp

from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    tree_not_finite,
)


class MultiTensorApply:
    """Callable matching ``multi_tensor_applier(op, noop_flag, lists, *args)``.

    Reference semantics (``multi_tensor_apply.cuh``): ``noop_flag`` is a
    device int buffer — the kernels early-exit when it is already set
    (``if (*noop_gmem) return;``) and WRITE 1 into it when they see a
    non-finite value, so the flag accumulates across chained op calls.

    The functional form here: ``__call__`` returns ``(out, noop_flag_out)``
    where ``noop_flag_out`` is an int32 0/1 scalar that ORs the incoming
    flag with the op's own found-inf vote — the accumulate-across-calls
    behavior, as a value instead of a mutated buffer.  Pass
    ``noop_flag=None`` (or 0) on the first call and thread the returned
    flag into the next; predicate the final commit on it with
    :func:`apex_tpu.ops.multi_tensor.tree_where` (the XLA form of the
    kernels' early-exit).  Ops that do not produce a found-inf vote
    (``multi_tensor_l2norm``) pass the incoming flag through unchanged.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args):
        out = op(*tensor_lists, *args)
        flag = jnp.int32(0) if noop_flag is None else (
            jnp.asarray(noop_flag).astype(jnp.int32))
        # (result, found_inf) ops: fold the vote into the flag.  The
        # vote is a 0-d bool — a tuple whose second element is anything
        # else (l2norm's per-tensor norm list) is not a vote.
        if (isinstance(out, tuple) and len(out) == 2
                and getattr(out[1], "dtype", None) == jnp.bool_
                and getattr(out[1], "ndim", None) == 0):
            result, found = out
            flag = flag | found.astype(jnp.int32)
            return result, flag
        return out, flag


multi_tensor_applier = MultiTensorApply(2048 * 32)

__all__ = [
    "MultiTensorApply",
    "multi_tensor_applier",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "tree_not_finite",
]
