"""Pallas/Mosaic TPU tiling-contract rules.

Mosaic tiles the last two dims of every VMEM block as (sublane, lane)
= (8·32/bitwidth, 128): fp32 packs (8, 128), bf16/fp16 (16, 128),
int8/fp8 (32, 128).  A block shape that violates this lowers fine in
interpret mode and on CPU tests, then fails Mosaic layout on the chip
— with chip time scarce, that error class must die in CI.  (See
``/opt/skills/guides`` TPU material and ``ops/fused_ce_pallas.py``'s
``_sublane``.)
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from apex_tpu.analysis.core import Finding, ModuleContext, Rule, last_name

_LANES = 128
_MIN_SUBLANE = 8           # fp32's tile — every dtype's is a multiple
_BF16_MARKERS = ("bfloat16", "bf16")
_BLOCK_HELPER_MARKERS = ("block", "ceil", "tile")


def _literal_shape(call: ast.Call) -> Optional[List[object]]:
    """The BlockSpec block-shape argument as a list (ints where
    literal, None where dynamic), or None when absent/not a tuple."""
    arg = None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            arg = kw.value
    if arg is None and call.args:
        arg = call.args[0]
    if not isinstance(arg, (ast.Tuple, ast.List)):
        return None
    out: List[object] = []
    for el in arg.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, int):
            out.append(el.value)
        else:
            out.append(None)
    return out


class BlockShapeTilingViolation(Rule):
    """APX301: literal ``pl.BlockSpec`` block shape off the TPU tile
    grid."""

    rule_id = "APX301"
    severity = "error"
    fix_hint = ("make the lane (last) dim 128-aligned (or exactly 1 for "
                "a padded scalar column) and the sublane dim a multiple "
                "of the dtype tile: 8 fp32 / 16 bf16 / 32 int8-fp8")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and last_name(node.func) == "BlockSpec"):
                continue
            shape = _literal_shape(node)
            if shape is None or len(shape) < 2:
                continue
            lane, sublane = shape[-1], shape[-2]
            if isinstance(lane, int) and lane != 1 and lane % _LANES != 0:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec lane dim {lane} is neither 1 nor a "
                    f"multiple of {_LANES}: Mosaic lays VMEM out in "
                    f"(sublane, {_LANES}) tiles, so this block cannot "
                    f"be tiled and fails only on real hardware")
            if isinstance(sublane, int) and sublane != 1 \
                    and sublane % _MIN_SUBLANE != 0:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec sublane dim {sublane} is not a multiple "
                    f"of {_MIN_SUBLANE} (fp32's tile; bf16 needs 16, "
                    f"int8/fp8 32): Mosaic rejects the layout on-chip")


class HardCodedSublaneAlignment(Rule):
    """APX302: fp32-only sublane constant in a dtype-generic block
    computation (the ``_ceil_block(..., align=8)``-on-bf16 class).

    The 8 is correct for fp32 and silently wrong for bf16 (needs 16)
    and int8/fp8 (need 32).  Flagged only when the module also handles
    bf16, i.e. when the hard-coded constant provably coexists with a
    dtype it is wrong for; derive the alignment from the dtype instead
    (``sublane(x.dtype)`` from ops/_pallas_tiling.py).
    """

    rule_id = "APX302"
    severity = "error"
    fix_hint = ("derive the sublane alignment from the block's dtype "
                "({4: 8, 2: 16, 1: 32}[dtype.itemsize], cf. "
                "ops/_pallas_tiling.sublane) instead of hard-coding fp32's 8")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.mentions(*_BF16_MARKERS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (last_name(node.func) or "").lower()
            if not any(m in fname for m in _BLOCK_HELPER_MARKERS):
                continue
            hits = [kw.value for kw in node.keywords
                    if kw.arg == "align"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value == 8]
            # the positional spelling of the same constant: the
            # alignment rides after (n, target) in every block helper
            hits += [a for a in node.args[2:]
                     if isinstance(a, ast.Constant) and a.value == 8]
            for _ in hits:
                yield self.finding(
                    ctx, node,
                    f"`{last_name(node.func)}(..., align=8)` in a "
                    f"module that handles bf16: 8 is the fp32 "
                    f"sublane tile — bf16 blocks need 16 and "
                    f"int8/fp8 need 32, so this block passes "
                    f"interpret-mode tests and fails Mosaic layout "
                    f"on the chip")
