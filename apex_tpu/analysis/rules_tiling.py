"""Pallas/Mosaic TPU tiling-contract rules.

Mosaic tiles the last two dims of every VMEM block as (sublane, lane)
= (8·32/bitwidth, 128): fp32 packs (8, 128), bf16/fp16 (16, 128),
int8/fp8 (32, 128).  A block shape that violates this lowers fine in
interpret mode and on CPU tests, then fails Mosaic layout on the chip
— with chip time scarce, that error class must die in CI.  (See
``/opt/skills/guides`` TPU material and ``ops/fused_ce_pallas.py``'s
``_sublane``.)
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import Finding, ModuleContext, Rule, last_name

_LANES = 128
_MIN_SUBLANE = 8           # fp32's tile — every dtype's is a multiple
_BF16_MARKERS = ("bfloat16", "bf16")
_BLOCK_HELPER_MARKERS = ("block", "ceil", "tile")


def _arity(fn) -> Optional[int]:
    """Positional arity of a lambda or function def (both expose the
    same ``.args``), or None for dynamic spellings (*args / **kw /
    keyword-only) this rule cannot judge."""
    a = fn.args
    if a.vararg or a.kwarg or a.kwonlyargs:
        return None
    return len(getattr(a, "posonlyargs", ())) + len(a.args)


class BlockSpecIndexMapArity(Rule):
    """APX105: ``pl.BlockSpec`` index_map arity != the ``grid`` rank of
    the ``pallas_call`` that consumes it.

    The grid has one index per dimension and Pallas calls the index_map
    with exactly that many program ids; an arity mismatch is a
    ``TypeError`` at trace time — but only on the code path that
    actually traces the kernel, which for TPU-gated kernels is the
    chip, not the CPU test suite.  Worse, a *smaller* refactor hazard:
    the grid grows a dimension (e.g. a new batch axis) and every
    lambda that wasn't updated fails one by one on scarce chip time.
    The rule resolves BlockSpecs and grids through simple local
    aliases (``spec = pl.BlockSpec(...)``, ``grid = (a, b)``), the
    idiom the repo's own kernels use.
    """

    rule_id = "APX105"
    severity = "error"
    fix_hint = ("give every index_map exactly one parameter per grid "
                "dimension (grid rank N ⇒ ``lambda i0, ..., iN-1``), "
                "including dimensions the block ignores")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for scope in self._scopes(ctx.tree):
            aliases = self._local_aliases(scope)
            for node in self._walk_scope(scope):
                if not (isinstance(node, ast.Call)
                        and last_name(node.func) == "pallas_call"):
                    continue
                rank = self._grid_rank(node, aliases)
                if rank is None:
                    continue
                for spec in self._blockspecs(node, aliases):
                    arity = self._index_map_arity(spec, scope, aliases)
                    if arity is not None and arity != rank:
                        yield self.finding(
                            ctx, spec,
                            f"BlockSpec index_map takes {arity} "
                            f"argument(s) but the pallas_call grid has "
                            f"rank {rank}: Pallas passes one program "
                            f"id per grid dimension, so this traces "
                            f"only to a TypeError — typically on the "
                            f"chip, after the CPU suite passed")

    @staticmethod
    def _scopes(tree):
        """Each function body is one alias scope; the module is too."""
        yield tree
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _walk_scope(scope):
        """Walk one scope WITHOUT descending into nested function
        bodies (each pallas_call is judged exactly once, in its
        innermost scope, against that scope's aliases).  Nested def
        nodes themselves are yielded so name-valued index_maps
        resolve."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    @classmethod
    def _local_aliases(cls, scope):
        """name -> value node for simple single-target assignments in
        this scope — lexically LAST wins (a linter approximation; the
        baseline absorbs deliberate shadowing).  ``_walk_scope`` visits
        siblings in reverse, so order by source position explicitly
        rather than by visit order."""
        assigns = [
            node for node in cls._walk_scope(scope)
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name))
        ]
        out = {}
        for node in sorted(assigns,
                           key=lambda n: (n.lineno, n.col_offset)):
            out[node.targets[0].id] = node.value
        return out

    @staticmethod
    def _grid_rank(call: ast.Call, aliases) -> Optional[int]:
        grid = None
        for kw in call.keywords:
            if kw.arg == "grid":
                grid = kw.value
        if isinstance(grid, ast.Name):
            grid = aliases.get(grid.id)
        if isinstance(grid, (ast.Tuple, ast.List)):
            return len(grid.elts)
        if isinstance(grid, ast.Constant) and isinstance(grid.value, int):
            return 1
        return None  # absent or dynamic: nothing to judge

    @staticmethod
    def _blockspecs(call: ast.Call, aliases):
        """Every BlockSpec call reachable from in_specs/out_specs —
        direct, or through one local-name hop."""
        def resolve(node):
            if isinstance(node, ast.Name):
                node = aliases.get(node.id)
            if (isinstance(node, ast.Call)
                    and last_name(node.func) == "BlockSpec"):
                yield node
            elif isinstance(node, (ast.Tuple, ast.List)):
                for el in node.elts:
                    yield from resolve(el)

        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                yield from resolve(kw.value)

    @classmethod
    def _index_map_arity(cls, spec: ast.Call, scope, aliases
                         ) -> Optional[int]:
        im = None
        for kw in spec.keywords:
            if kw.arg == "index_map":
                im = kw.value
        if im is None and len(spec.args) >= 2:
            im = spec.args[1]
        if im is None:
            return None  # default index_map: always rank-correct
        if isinstance(im, ast.Name):
            aliased = aliases.get(im.id)
            if isinstance(aliased, ast.Lambda):
                im = aliased
            else:
                for node in cls._walk_scope(scope):
                    if (isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and node.name == im.id):
                        return _arity(node)
        if isinstance(im, ast.Lambda):
            return _arity(im)
        return None  # partials / attribute refs: out of static reach


def _literal_shape(call: ast.Call) -> Optional[List[object]]:
    """The BlockSpec block-shape argument as a list (ints where
    literal, None where dynamic), or None when absent/not a tuple."""
    arg = None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            arg = kw.value
    if arg is None and call.args:
        arg = call.args[0]
    if not isinstance(arg, (ast.Tuple, ast.List)):
        return None
    out: List[object] = []
    for el in arg.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, int):
            out.append(el.value)
        else:
            out.append(None)
    return out


class BlockShapeTilingViolation(Rule):
    """APX301: literal ``pl.BlockSpec`` block shape off the TPU tile
    grid."""

    rule_id = "APX301"
    severity = "error"
    fix_hint = ("make the lane (last) dim 128-aligned (or exactly 1 for "
                "a padded scalar column) and the sublane dim a multiple "
                "of the dtype tile: 8 fp32 / 16 bf16 / 32 int8-fp8")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and last_name(node.func) == "BlockSpec"):
                continue
            shape = _literal_shape(node)
            if shape is None or len(shape) < 2:
                continue
            lane, sublane = shape[-1], shape[-2]
            if isinstance(lane, int) and lane != 1 and lane % _LANES != 0:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec lane dim {lane} is neither 1 nor a "
                    f"multiple of {_LANES}: Mosaic lays VMEM out in "
                    f"(sublane, {_LANES}) tiles, so this block cannot "
                    f"be tiled and fails only on real hardware")
            if isinstance(sublane, int) and sublane != 1 \
                    and sublane % _MIN_SUBLANE != 0:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec sublane dim {sublane} is not a multiple "
                    f"of {_MIN_SUBLANE} (fp32's tile; bf16 needs 16, "
                    f"int8/fp8 32): Mosaic rejects the layout on-chip")


class VmemFootprintOverBudget(Rule):
    """APX304: the provable VMEM footprint of one ``pallas_call`` —
    Σ block-shape bytes across its BlockSpecs plus its scratch shapes,
    plus the score-sized f32 temporaries its kernel body provably keeps
    live — exceeds the budget.

    VMEM is ~16 MiB/core and Mosaic reports an overrun only when the
    kernel actually compiles for the chip; interpret-mode CPU tests
    allocate host RAM and sail through.  The estimate is a LOWER bound:
    dims resolve through local int assignments (``bn = 256``) via the
    dataflow lattice, dynamic dims price at 0, BlockSpec elements price
    at 4 bytes (dtype is the array's, invisible here) and scratch at
    its declared dtype — and Mosaic double-buffers grid-revisited
    blocks, so the true requirement is larger still.  When the kernel
    function resolves statically (a direct name or a
    ``functools.partial(fn, ...)`` first argument), each
    last-dim-contracting ``dot_general`` in its body — the flash
    ``s = q·kᵀ`` / ``dp = do·vᵀ`` score pattern — prices two
    (sublane × sublane) f32 temporaries (the dot result and the
    elementwise tile derived from it), sized from the two largest
    distinct literal BlockSpec sublane dims: at large blocks these
    temporaries, not the declared buffers, dominate the backward
    kernels' footprint.  A warning, not an error: the budget is
    configurable (``VmemFootprintOverBudget(budget_bytes=...)``, CLI
    ``--vmem-budget-mib``) for targets with different VMEM.
    """

    rule_id = "APX304"
    severity = "warning"
    fix_hint = ("shrink the block shapes (the grid revisits tiles; "
                "smaller blocks trade VMEM for grid steps) or move "
                "rarely-touched scratch to pltpu.ANY/HBM; budgets "
                "other than 16 MiB: --vmem-budget-mib")

    DEFAULT_BUDGET = 16 * 2 ** 20

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET):
        self.budget_bytes = int(budget_bytes)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        arity = BlockSpecIndexMapArity  # shares the scope/alias helpers
        for scope in arity._scopes(ctx.tree):
            aliases = arity._local_aliases(scope)
            for node in arity._walk_scope(scope):
                if not (isinstance(node, ast.Call)
                        and last_name(node.func) == "pallas_call"):
                    continue
                total, priced, skipped = self._footprint(ctx, node, aliases)
                temp_bytes, temps = self._score_temp_bytes(ctx, node, aliases)
                if priced and total + temp_bytes > self.budget_bytes:
                    about = "" if not skipped else \
                        f" (+{skipped} buffer(s) with dynamic dims, " \
                        f"unpriced — the true footprint is larger)"
                    scored = "" if not temps else \
                        f" plus {temps} score-sized f32 kernel " \
                        f"temporaries"
                    yield self.finding(
                        ctx, node,
                        f"pallas_call VMEM footprint ≥ "
                        f"{(total + temp_bytes) / 2**20:.1f} MiB across "
                        f"{priced} "
                        f"block/scratch buffer(s){scored}{about}, over the "
                        f"{self.budget_bytes / 2**20:.0f} MiB budget: "
                        f"Mosaic rejects the allocation only when the "
                        f"kernel first compiles on the chip")

    def _footprint(self, ctx: ModuleContext, call: ast.Call, aliases):
        """(bytes, priced_buffer_count, skipped_buffer_count): literal
        contributions only — a lower bound."""
        total = 0
        priced = skipped = 0
        for spec in BlockSpecIndexMapArity._blockspecs(call, aliases):
            dims = dataflow.literal_dims(_shape_node(spec), aliases)
            if dims is None:
                skipped += 1
                continue
            total += _prod(dims) * 4
            priced += 1
        scratch = dataflow.scratch_entries(call)
        env = dataflow.dtype_env(
            ctx, ctx.enclosing_function(call)) if scratch else {}
        for _entry, shape, dtype_node in scratch:
            dims = dataflow.literal_dims(shape, aliases)
            if dims is None:
                skipped += 1
                continue
            size = dataflow.itemsize(
                dataflow.dtype_literal(dtype_node, env))
            total += _prod(dims) * (size or 4)
            priced += 1
        return total, priced, skipped

    def _score_temp_bytes(self, ctx: ModuleContext, call: ast.Call,
                          aliases):
        """(bytes, temp_count) for the score-sized f32 temporaries the
        kernel body provably keeps live: 2 per last-dim-contracting
        ``dot_general`` (the dot result + the elementwise tile derived
        from it — the flash ``s``/``p`` and ``dp``/``ds`` pairs), each
        sized as the product of the two largest distinct literal
        BlockSpec sublane dims (the (bq, bk) score tile).  (0, 0) when
        the kernel function or the sublane dims are out of static
        reach — a lower bound, like the rest of the rule."""
        fn_def = self._kernel_fn(ctx, call, aliases)
        if fn_def is None:
            return 0, 0
        dots = self._score_dots(fn_def)
        if not dots:
            return 0, 0
        sublanes = set()
        for spec in BlockSpecIndexMapArity._blockspecs(call, aliases):
            dims = dataflow.literal_dims(_shape_node(spec), aliases)
            if dims and len(dims) >= 2:
                sublanes.add(dims[-2])
        sublanes.discard(0)
        if not sublanes:
            return 0, 0
        top = sorted(sublanes, reverse=True)
        elems = top[0] * (top[1] if len(top) > 1 else top[0])
        temps = 2 * dots
        return temps * elems * 4, temps

    @staticmethod
    def _kernel_fn(ctx: ModuleContext, call: ast.Call, aliases
                   ) -> Optional[ast.FunctionDef]:
        """The kernel FunctionDef the pallas_call invokes — its first
        positional argument, resolved through a local alias and/or one
        ``functools.partial(fn, ...)`` wrapper (the repo idiom for
        binding scale/blocks).  None for dynamic spellings."""
        fn = call.args[0] if call.args else None
        if isinstance(fn, ast.Name):
            fn = aliases.get(fn.id, fn)
        if (isinstance(fn, ast.Call) and last_name(fn.func) == "partial"
                and fn.args):
            fn = fn.args[0]
        if not isinstance(fn, ast.Name):
            return None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and node.name == fn.id:
                return node
        return None

    @staticmethod
    def _score_dots(fn_def: ast.FunctionDef) -> int:
        """``dot_general`` calls in the kernel body (nested ``pl.when``
        bodies included) whose dimension_numbers literally contract dim
        1 of BOTH 2-D operands — ``(((1,), (1,)), ...)``, the
        row-block × col-blockᵀ score pattern.  The pv/dv/dq-style dots
        (``(1,)×(0,)`` / ``(0,)×(0,)``) produce block-shaped results
        already priced via specs/scratch and are not counted."""

        def _is_dim1(node) -> bool:
            return (isinstance(node, (ast.Tuple, ast.List))
                    and len(node.elts) == 1
                    and isinstance(node.elts[0], ast.Constant)
                    and node.elts[0].value == 1)

        n = 0
        for node in ast.walk(fn_def):
            if not (isinstance(node, ast.Call)
                    and last_name(node.func) == "dot_general"
                    and len(node.args) >= 3):
                continue
            dims = node.args[2]
            if not (isinstance(dims, (ast.Tuple, ast.List)) and dims.elts):
                continue
            contract = dims.elts[0]
            if (isinstance(contract, (ast.Tuple, ast.List))
                    and len(contract.elts) == 2
                    and _is_dim1(contract.elts[0])
                    and _is_dim1(contract.elts[1])):
                n += 1
        return n


def _prod(dims: List[int]) -> int:
    out = 1
    for d in dims:
        out *= d
    return out


def _shape_node(spec: ast.Call) -> Optional[ast.AST]:
    for kw in spec.keywords:
        if kw.arg == "block_shape":
            return kw.value
    return spec.args[0] if spec.args else None


class HardCodedSublaneAlignment(Rule):
    """APX302: fp32-only sublane constant in a dtype-generic block
    computation (the ``_ceil_block(..., align=8)``-on-bf16 class).

    The 8 is correct for fp32 and silently wrong for bf16 (needs 16)
    and int8/fp8 (need 32).  Flagged only when the module also handles
    bf16, i.e. when the hard-coded constant provably coexists with a
    dtype it is wrong for; derive the alignment from the dtype instead
    (``sublane(x.dtype)`` from ops/_pallas_tiling.py).
    """

    rule_id = "APX302"
    severity = "error"
    fix_hint = ("derive the sublane alignment from the block's dtype "
                "({4: 8, 2: 16, 1: 32}[dtype.itemsize], cf. "
                "ops/_pallas_tiling.sublane) instead of hard-coding fp32's 8")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.mentions(*_BF16_MARKERS):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (last_name(node.func) or "").lower()
            if not any(m in fname for m in _BLOCK_HELPER_MARKERS):
                continue
            hits = [kw.value for kw in node.keywords
                    if kw.arg == "align"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value == 8]
            # the positional spelling of the same constant: the
            # alignment rides after (n, target) in every block helper
            hits += [a for a in node.args[2:]
                     if isinstance(a, ast.Constant) and a.value == 8]
            for _ in hits:
                yield self.finding(
                    ctx, node,
                    f"`{last_name(node.func)}(..., align=8)` in a "
                    f"module that handles bf16: 8 is the fp32 "
                    f"sublane tile — bf16 blocks need 16 and "
                    f"int8/fp8 need 32, so this block passes "
                    f"interpret-mode tests and fails Mosaic layout "
                    f"on the chip")
