"""Checkpoint-I/O hygiene rules (the torn-write class).

A checkpoint file published with a plain ``open(path, "wb")`` is torn
the moment a preemption, OOM-kill, or dying filesystem interrupts the
writer: a truncated file sits under the FINAL name, and the resume side
can only detect it after the fact (``io.validate_checkpoint``) — or
worse, load garbage.  The tree has exactly one sanctioned publish
primitive, :func:`apex_tpu.io.native.atomic_output` (write to
``<path>.tmp``, fsync, rename, dir-fsync), and every checkpoint write
must route through it or a wrapper of it.

- APX104: a write-mode binary ``open()`` whose path (or enclosing
  function) is checkpoint-shaped, outside the atomic helper and not
  staged through a ``.tmp`` name.  Only statically certain cases are
  flagged: a literal mode string, a builtin-``open`` call (attribute
  spellings like ``gzip.open`` are other formats' business).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from apex_tpu.analysis.core import Finding, ModuleContext, Rule

__all__ = ["NonAtomicCheckpointWrite"]

#: Path-or-function spellings that mark a write as checkpoint-bound.
_CKPT_MARKERS = ("ckpt", "checkpoint", "shard_", ".apex")

#: Functions allowed to open checkpoint bytes directly: the designated
#: atomic helper itself (io/native.py) and explicit wrappers named for
#: the contract.
_ATOMIC_FN_PREFIXES = ("atomic_output", "_atomic")


def _write_binary_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string if this ``open`` call writes binary
    (``wb``/``ab``/``xb``/``w+b``...); None otherwise/unknown."""
    mode_node: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if not (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return None
    mode = mode_node.value
    if "b" in mode and any(c in mode for c in "wax"):
        return mode
    return None


class NonAtomicCheckpointWrite(Rule):
    """APX104: direct binary write to a checkpoint path — bypasses the
    atomic write/rename helper, so an interrupted writer publishes a
    torn file under the final name."""

    rule_id = "APX104"
    severity = "error"
    fix_hint = ("publish through apex_tpu.io.native.atomic_output (tmp "
                "+ fsync + rename + dir-fsync) or a wrapper of it "
                "(io.save_checkpoint); a direct open(path, 'wb') leaves "
                "a truncated file under the final name when the writer "
                "dies mid-save — the torn-write class "
                "io.validate_checkpoint exists to detect after the fact")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            if not (isinstance(call.func, ast.Name)
                    and call.func.id == "open"):
                continue
            mode = _write_binary_mode(call)
            if mode is None or not call.args:
                continue
            path_src = (ast.get_source_segment(ctx.source, call.args[0])
                        or "").lower()
            qual = ctx.enclosing_qualname(call).lower()
            fn_name = qual.rsplit(".", 1)[-1]
            checkpointish = (
                any(m in path_src for m in _CKPT_MARKERS)
                or any(m in fn_name for m in _CKPT_MARKERS))
            if not checkpointish:
                continue
            if any(fn_name.startswith(p) for p in _ATOMIC_FN_PREFIXES):
                continue  # the designated helper / an explicit wrapper
            if ".tmp" in path_src:
                continue  # staged write: the rename-publish idiom
            yield self.finding(
                ctx, call,
                f"checkpoint path opened for direct binary write "
                f"(mode {mode!r}): a writer killed mid-save leaves a "
                f"TORN file under the final name — publish via "
                f"io.native.atomic_output instead")
