"""Host-sync hygiene: blocking device reads inside step loops.

The repo's hot loops are asynchronous by construction — the host
dispatches step N+1 while the device runs step N — and ONE blocking
spelling silently serializes them: converting a device array to a host
value inside the loop (``float(loss)``, ``loss.item()``,
``np.asarray(tokens)``, ``f"loss={loss}"``).  On CPU tests this is
invisible; on TPU it drains the dispatch queue every iteration — the
exact per-step host sync ``apex_tpu.observability.stepstats`` exists
to remove (its :class:`~apex_tpu.observability.stepstats.AsyncFetcher`
is the allowed spelling: hand the array over, harvest the copy N steps
later).

- **APX108**: inside a ``for``/``while`` loop that dispatches a
  compiled step, a value *proven* to be a device array is converted to
  a host value.
- **APX112**: a wall-clock delta (``time.time()``/``perf_counter()``/
  ``monotonic()``) taken around a step dispatch with NO blocking seam
  in between — async dispatch returns as soon as the work is queued,
  so the delta measures enqueue time, not step time (the classic
  10000x-too-fast "benchmark").  The acquitting seams: a
  ``block_until_ready``/``device_get`` call, any host materialization
  (``float()``/``.item()``/``np.asarray``), or an async-fetch drain
  (``.flush()``/``.wait_until_finished()``) between the dispatch and
  the second timestamp.

What "proven" means (the only-statically-certain contract every rule
family here follows):

- a *step binding* is a name assigned from ``jax.jit(...)``, from a
  ``make_*step``/``make_prefill`` builder call (the repo's step-builder
  naming), or from a local zero-arg builder function whose return is
  one of those calls (the ``step = build_step()`` rebuild idiom);
- a *step-calling function* is a local def whose return statement
  calls a step binding (the ``run_step`` retry-wrapper idiom) — its
  call results are device arrays too;
- *device names* are the assignment targets (incl. tuple unpacking) of
  calls to either, resolved through the lexical scope chain;
- a *step loop* is a ``for``/``while`` whose body calls a step binding
  or step-calling function;
- flagged sinks inside a step loop: ``float(x)``/``int(x)``,
  ``x.item()``, ``np.asarray(x)``/``np.array(x)`` (numpy only —
  ``jnp.asarray`` stays on device), and f-string formatting of ``x``,
  where ``x`` is a device name (or an attribute off one, e.g.
  ``scaler_state.loss_scale``).

Values threaded through containers, attributes (``self._decode``), or
multi-value builder returns are trusted, same as the donation rule.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, last_name,
)

__all__ = ["BlockingHostSyncInStepLoop", "UnseamedDispatchTiming"]

#: builder callees whose result is a compiled step function
_STEP_BUILDER = re.compile(r"^make_\w*step$|^make_prefill$")

#: numpy spellings whose call materializes on host
_NP_SINKS = {"asarray", "array"}


def _is_step_builder_call(call: ast.Call) -> bool:
    name = last_name(call.func)
    return name == "jit" or (name is not None
                             and _STEP_BUILDER.match(name) is not None)


def _target_name_positions(stmt: ast.Assign) -> List[str]:
    """Plain names an assignment binds (single name or a flat tuple of
    names); anything fancier returns [] (trusted)."""
    if len(stmt.targets) != 1:
        return []
    t = stmt.targets[0]
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out = []
        for e in t.elts:
            if isinstance(e, ast.Name):
                out.append(e.id)
        return out
    return []


def _rebound_names(stmt: ast.AST) -> List[str]:
    """EVERY plain name a statement rebinds (assignment/loop/with-as
    targets, destructuring included) — used to invalidate clock stamps
    on reuse: after ``t0 = offsets[0]``, a ``time.time() - t0`` is data
    math, not a timing, and must not be flagged."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in stmt.items
                   if i.optional_vars is not None]
    out = []
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Name):
                out.append(node.id)
    return out


class _StepDispatchFacts:
    """The shared step-binding facts both host-sync rules prove their
    findings on: which names hold compiled steps, which local defs
    dispatch them, and which names hold their (device-array) results —
    see the module docstring for the "proven" contract."""

    # ------------------------------------------------------------ facts
    def _scope_of(self, ctx: ModuleContext, node: ast.AST) -> ast.AST:
        return ctx.enclosing_function(node) or ctx.tree

    def _collect(self, ctx: ModuleContext
                 ) -> Tuple[Dict[int, Set[str]], Set[str]]:
        """Per-scope step bindings and the step-calling function names
        (two-pass fixpoint: builders can chain one level deep per
        pass)."""
        step_bindings: Dict[int, Set[str]] = {}
        builder_fns: Set[str] = set()    # defs returning a step build
        step_callers: Set[str] = set()   # defs returning a step CALL

        def record(node: ast.AST, name: str) -> None:
            step_bindings.setdefault(id(self._scope_of(ctx, node)),
                                     set()).add(name)

        for _ in range(3):  # bounded fixpoint: jit -> builder -> caller
            changed = False
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    callee = last_name(node.value.func)
                    is_step = _is_step_builder_call(node.value) \
                        or callee in builder_fns
                    if not is_step:
                        continue
                    for name in _target_name_positions(node):
                        scope = id(self._scope_of(ctx, node))
                        if name not in step_bindings.get(scope, set()):
                            record(node, name)
                            changed = True
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for ret in ast.walk(node):
                        if not (isinstance(ret, ast.Return)
                                and isinstance(ret.value, ast.Call)):
                            continue
                        callee = last_name(ret.value.func)
                        if _is_step_builder_call(ret.value) \
                                or callee in builder_fns:
                            if node.name not in builder_fns:
                                builder_fns.add(node.name)
                                changed = True
                        elif callee is not None and self._is_step_name(
                                ctx, ret.value.func, ret, step_bindings):
                            if node.name not in step_callers:
                                step_callers.add(node.name)
                                changed = True
            if not changed:
                break
        return step_bindings, builder_fns | step_callers


    def _is_step_name(self, ctx: ModuleContext, func: ast.AST,
                      site: ast.AST,
                      step_bindings: Dict[int, Set[str]]) -> bool:
        """Does ``func`` (at ``site``) resolve to a step binding through
        the lexical scope chain?"""
        if not isinstance(func, ast.Name):
            return False
        scope: Optional[ast.AST] = ctx.enclosing_function(site)
        while True:
            node = scope if scope is not None else ctx.tree
            if func.id in step_bindings.get(id(node), set()):
                return True
            if scope is None:
                return False
            scope = ctx.enclosing_function(scope)

    def _device_names(self, ctx: ModuleContext,
                      step_bindings: Dict[int, Set[str]],
                      step_fns: Set[str]) -> Dict[int, Set[str]]:
        """Per-scope names bound from a step (or step-calling fn) call —
        the proven device arrays."""
        out: Dict[int, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            callee = node.value.func
            is_step_call = self._is_step_name(ctx, callee, node,
                                              step_bindings) \
                or (isinstance(callee, ast.Name) and callee.id in step_fns)
            if not is_step_call:
                continue
            scope = id(self._scope_of(ctx, node))
            out.setdefault(scope, set()).update(
                _target_name_positions(node))
        return out

    def _dispatches_step(self, ctx: ModuleContext, node: ast.AST,
                         step_bindings: Dict[int, Set[str]],
                         step_fns: Set[str]) -> bool:
        """Does any call under ``node`` dispatch a proven step?"""
        return any(
            isinstance(n, ast.Call) and (
                self._is_step_name(ctx, n.func, n, step_bindings)
                or (isinstance(n.func, ast.Name) and n.func.id in step_fns))
            for n in ast.walk(node))

    def _numpy_call(self, ctx: ModuleContext, call: ast.Call) -> bool:
        name = last_name(call.func)
        if name not in _NP_SINKS:
            return False
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name):
            head = call.func.value.id
            mod = ctx.import_aliases.get(head, head)
            return mod == "numpy" or head == "np"
        if isinstance(call.func, ast.Name):
            tgt = ctx.from_imports.get(call.func.id)
            return tgt is not None and tgt[0] == "numpy"
        return False


class BlockingHostSyncInStepLoop(_StepDispatchFacts, Rule):
    """APX108: device array forced to host inside a step loop."""

    rule_id = "APX108"
    severity = "error"
    fix_hint = ("move the conversion after the loop, or route it through "
                "the async telemetry seam "
                "(apex_tpu.observability.stepstats.AsyncFetcher: put() the "
                "device array in the loop, harvest ready() copies without "
                "blocking) — every in-loop float()/.item()/np.asarray/"
                "f-string of a device array drains the dispatch queue and "
                "serializes host and device once per step")

    # ------------------------------------------------------------- sinks
    def _base_device_name(self, ctx: ModuleContext, expr: ast.AST,
                          device: Dict[int, Set[str]]) -> Optional[str]:
        """``expr``'s base Name if it is a proven device value
        (``loss``, ``scaler_state.loss_scale``, ``stats[0]``)."""
        node = expr
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        scope: Optional[ast.AST] = ctx.enclosing_function(expr)
        while True:
            s = scope if scope is not None else ctx.tree
            if node.id in device.get(id(s), set()):
                return node.id
            if scope is None:
                return None
            scope = ctx.enclosing_function(scope)

    def _call_sink(self, ctx: ModuleContext, node: ast.Call,
                   device: Dict[int, Set[str]]
                   ) -> Optional[Tuple[str, str]]:
        fname = last_name(node.func)
        if fname in ("float", "int") and isinstance(node.func, ast.Name) \
                and len(node.args) == 1:
            dn = self._base_device_name(ctx, node.args[0], device)
            if dn is not None:
                return dn, f"{fname}()"
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            dn = self._base_device_name(ctx, node.func.value, device)
            if dn is not None:
                return dn, ".item()"
        elif self._numpy_call(ctx, node) and node.args:
            dn = self._base_device_name(ctx, node.args[0], device)
            if dn is not None:
                return dn, "np.asarray()"
        return None

    def _sinks_in(self, ctx: ModuleContext, loop: ast.AST,
                  device: Dict[int, Set[str]]
                  ) -> Iterator[Tuple[ast.AST, str, str]]:
        # pass 1: conversion calls (float/int/.item/np.asarray)
        call_sinks: List[Tuple[ast.Call, str, str]] = []
        for node in ast.walk(loop):
            if isinstance(node, ast.Call):
                hit = self._call_sink(ctx, node, device)
                if hit is not None:
                    call_sinks.append((node, *hit))
        flagged = {id(n) for n, _, _ in call_sinks}
        yield from ((n, dn, how) for n, dn, how in call_sinks)
        # pass 2: f-string interpolation (formats = host-materializes);
        # skip ones whose conversion call was already reported
        for node in ast.walk(loop):
            if not isinstance(node, ast.FormattedValue):
                continue
            if any(id(sub) in flagged for sub in ast.walk(node.value)):
                continue
            dn = None
            for sub in ast.walk(node.value):
                if isinstance(sub, (ast.Name, ast.Attribute,
                                    ast.Subscript)):
                    dn = self._base_device_name(ctx, sub, device)
                    if dn is not None:
                        break
            if dn is not None:
                yield node, dn, "f-string formatting"

    # ------------------------------------------------------------- check
    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.mentions("jit", "make_"):
            return
        step_bindings, step_fns = self._collect(ctx)
        if not step_bindings and not step_fns:
            return
        device = self._device_names(ctx, step_bindings, step_fns)
        if not device:
            return
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            # a STEP loop: its body dispatches a compiled step
            if not self._dispatches_step(ctx, loop, step_bindings,
                                         step_fns):
                continue
            seen: Set[int] = set()
            for node, dn, how in self._sinks_in(ctx, loop, device):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                yield self.finding(
                    ctx, node,
                    f"{how} of device array `{dn}` inside this step "
                    f"loop (line {loop.lineno}) blocks the host on the "
                    f"device every iteration — the loop dispatches a "
                    f"compiled step, so this is a per-step sync barrier")


#: wall-clock callables whose deltas APX112 audits
_CLOCKS = {"time", "perf_counter", "monotonic"}

#: attribute/name calls that force the queued work to finish — any one
#: of these between a dispatch and the second timestamp makes the
#: delta truthful (generous on purpose: a seam only ACQUITS)
_SEAM_ATTRS = {"block_until_ready", "device_get", "item", "flush",
               "wait_until_finished"}


class UnseamedDispatchTiming(_StepDispatchFacts, Rule):
    """APX112: a wall-clock delta spanning a step dispatch with no
    blocking seam — async dispatch makes the timing a lie.

    Statement-list dataflow, only-statically-certain: within one
    straight-line statement sequence, ``t0 = time.time()`` (or
    ``perf_counter``/``monotonic``, module or from-imported) followed
    by a statement that dispatches a proven step binding, followed by
    ``<clock>() - t0`` (or ``t1 = <clock>(); ... t1 - t0``) with no
    acquitting seam between the dispatch and the second timestamp.
    Timestamps bound in nested blocks, unproven callees, and deltas
    over names from other scopes are all trusted."""

    rule_id = "APX112"
    severity = "error"
    fix_hint = ("call jax.block_until_ready(...) on the step's outputs "
                "(or materialize one of them: float()/np.asarray, or "
                "drain the async fetcher) before taking the second "
                "timestamp — jit dispatch is asynchronous, so a bare "
                "wall-clock delta around it measures how fast the work "
                "was ENQUEUED, not how fast it ran")

    # ------------------------------------------------------------ clocks
    def _clock_call(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """``time.time()`` / ``tm.perf_counter()`` /
        ``from time import monotonic; monotonic()`` spellings."""
        if not (isinstance(node, ast.Call)
                and last_name(node.func) in _CLOCKS
                and not node.args and not node.keywords):
            return False
        if isinstance(node.func, ast.Attribute):
            if not isinstance(node.func.value, ast.Name):
                return False
            head = node.func.value.id
            return ctx.import_aliases.get(head, head) == "time"
        tgt = ctx.from_imports.get(node.func.id)
        return tgt is not None and tgt[0] == "time"

    def _seam_fns(self, ctx: ModuleContext) -> Set[str]:
        """Module/function-local defs whose body contains a seam call
        (the ``def block(tree): ... jax.block_until_ready(tree)``
        wrapper idiom) — calling one IS the seam."""
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(self._seam_call(ctx, n)
                            for n in ast.walk(node)
                            if isinstance(n, ast.Call)):
                out.add(node.name)
        return out

    def _seam_call(self, ctx: ModuleContext, n: ast.Call,
                   seam_fns: Set[str] = frozenset()) -> bool:
        name = last_name(n.func)
        if name in _SEAM_ATTRS or name in seam_fns:
            return True
        if name in ("float", "int") and isinstance(n.func, ast.Name) \
                and len(n.args) == 1:
            return True
        return self._numpy_call(ctx, n)

    def _is_seam(self, ctx: ModuleContext, stmt: ast.AST,
                 seam_fns: Set[str]) -> bool:
        return any(self._seam_call(ctx, n, seam_fns)
                   for n in ast.walk(stmt) if isinstance(n, ast.Call))

    # ------------------------------------------------------------- check
    def _statement_lists(self, tree: ast.AST) -> Iterator[List[ast.stmt]]:
        for node in ast.walk(tree):
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(node, field, None)
                if isinstance(stmts, list) and stmts \
                        and isinstance(stmts[0], ast.stmt):
                    yield stmts

    def _deltas_in(self, ctx: ModuleContext, stmt: ast.AST,
                   stamps: Dict[str, int], idx: int
                   ) -> Iterator[Tuple[ast.AST, str, int, int]]:
        """``(node, t0_name, t0_idx, t1_idx)`` for each audited
        subtraction under ``stmt`` (at list position ``idx``)."""
        for n in ast.walk(stmt):
            if not (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)):
                continue
            if not (isinstance(n.right, ast.Name)
                    and n.right.id in stamps):
                continue
            j = stamps[n.right.id]
            if self._clock_call(ctx, n.left):
                yield n, n.right.id, j, idx
            elif isinstance(n.left, ast.Name) and n.left.id in stamps \
                    and stamps[n.left.id] > j:
                yield n, n.right.id, j, stamps[n.left.id]

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.mentions("jit", "make_"):
            return
        step_bindings, step_fns = self._collect(ctx)
        if not step_bindings and not step_fns:
            return
        seam_fns = self._seam_fns(ctx)
        for stmts in self._statement_lists(ctx.tree):
            stamps: Dict[str, int] = {}     # name -> taken-at index
            dispatch_at: List[int] = []
            seam_at: List[int] = []
            for idx, stmt in enumerate(stmts):
                for node, t0, j, k in self._deltas_in(ctx, stmt, stamps,
                                                      idx):
                    # EVERY in-window dispatch needs a seam after it —
                    # a seam between a warmup dispatch and the timed
                    # loop must not acquit the loop's own dispatches
                    uncovered = [
                        d for d in dispatch_at
                        if j < d <= k and not any(d <= s <= k
                                                  for s in seam_at)]
                    if uncovered:
                        yield self.finding(
                            ctx, node,
                            f"wall-clock delta against `{t0}` (line "
                            f"{stmts[j].lineno}) spans the step "
                            f"dispatch on line "
                            f"{stmts[uncovered[-1]].lineno} with no "
                            f"block_until_ready/host-read seam in "
                            f"between — async dispatch means this "
                            f"times the enqueue, not the step")
                # facts AFTER deltas: a stmt's own dispatch/seam/stamp
                # affects later statements only (same-statement order
                # is uncertain, so same-statement hazards are trusted)
                if self._is_seam(ctx, stmt, seam_fns):
                    seam_at.append(idx)
                if self._dispatches_step(ctx, stmt, step_bindings,
                                         step_fns):
                    dispatch_at.append(idx)
                if isinstance(stmt, ast.Assign) \
                        and self._clock_call(ctx, stmt.value):
                    for name in _target_name_positions(stmt):
                        stamps[name] = idx
                else:
                    # a rebind to anything else INVALIDATES the stamp
                    # — a later delta against the reused name is not a
                    # dispatch timing and must not turn the gate red
                    for name in _rebound_names(stmt):
                        stamps.pop(name, None)
