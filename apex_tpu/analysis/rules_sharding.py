"""Sharding-annotation consistency rules — the GSPMD half of the
collective-axis family.

The collective rules (APX201-205) police ``lax.psum``-style EXPLICIT
collectives against what ``shard_map`` binds.  This module polices the
ANNOTATIONS the GSPMD-native path (``gpt.make_train_step(spmd="auto")``,
``jit`` + ``NamedSharding``) is built from — where the failure modes are
nastier, because nothing has to fail at all:

- A ``with_sharding_constraint`` whose ``NamedSharding`` was built on a
  DIFFERENT mesh object than the jit's ``in_shardings`` compiles and
  runs with zero exceptions — XLA logs an "involuntary full
  rematerialization" to stderr and silently re-lays the tensor out
  (reproduced on jax 0.4.37; pinned live in
  tests/test_lowered_invariants.py::TestShardingRuleProof).  The stale
  prod-mesh annotation in a CI-mesh step is exactly one refactor away.
- A typo'd axis inside a ``NamedSharding`` against its OWN mesh raises
  — but at annotation-construction time, which for TPU-gated step
  builders (mesh built from ``jax.devices()`` on the chip) is on the
  chip, after CPU CI passed: the APX203 deferral story.
- A donated jit argument whose in/out shardings provably differ keeps
  compiling: XLA drops the donation with a ``UserWarning`` and the step
  silently re-inflates by the donated bytes.

Three rules, same quiet-on-unknown contract as the rest of the
dataflow tier: only literal ``P(...)`` specs (one last-wins alias hop,
:func:`dataflow.resolve_spec`) and statically-resolvable meshes
(:func:`dataflow.mesh_axes_of`) are judged; everything else is the
threading pattern the rules exist to push code toward.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Tuple

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import Finding, ModuleContext, Rule, last_name
from apex_tpu.analysis.dataflow import _kwarg
from apex_tpu.analysis.rules_donation import _literal_argnums

__all__ = [
    "ShardingSpecAxisUnbound", "ShardingSpecRankMismatch",
    "DonatedShardingMismatch",
]

#: call sites whose second argument (or ``shardings=``) annotates the
#: first: the reaching-mesh check applies inside traced code
_CONSTRAINT_FNS = {"with_sharding_constraint"}


def _named_sharding_calls(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and last_name(node.func) == "NamedSharding":
            yield node


def _ns_parts(call: ast.Call):
    """(mesh_expr, spec_expr) of one NamedSharding call."""
    mesh = call.args[0] if call.args else _kwarg(call, "mesh")
    spec = call.args[1] if len(call.args) > 1 else _kwarg(call, "spec")
    return mesh, spec


def _constraint_calls(ctx: ModuleContext):
    """(call, value_expr, sharding_expr) per with_sharding_constraint."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) \
                and last_name(node.func) in _CONSTRAINT_FNS:
            value = node.args[0] if node.args else _kwarg(node, "x")
            shardings = node.args[1] if len(node.args) > 1 \
                else _kwarg(node, "shardings")
            yield node, value, shardings


def _spec_of_annotation(node: Optional[ast.AST],
                        aliases) -> Tuple[Optional[ast.Call],
                                          Optional[FrozenSet[str]]]:
    """``(P_call, own_mesh_axes)`` of one annotation expression: a bare
    ``P(...)`` (or alias), or a ``NamedSharding(mesh, P(...))`` whose
    own mesh's axes are returned when statically resolvable."""
    if isinstance(node, ast.Call) and last_name(node.func) == "NamedSharding":
        mesh, spec = _ns_parts(node)
        return dataflow.resolve_spec(spec, aliases), \
            dataflow.mesh_axes_of(mesh, aliases)
    return dataflow.resolve_spec(node, aliases), None


def _reaching_mesh(ctx: ModuleContext,
                   call: ast.Call) -> Optional[FrozenSet[str]]:
    """The mesh-axis set provably reaching an annotation site through
    the enclosing jit's ``in_shardings``/``out_shardings`` — None
    unless EVERY reaching scope carries resolved mesh information (one
    unannotated or unresolvable path silences the check; shard_map
    paths have their own axis semantics and silence it too)."""
    scopes = dataflow.scopes_at(ctx, call)
    if not scopes:
        return None
    axes: set = set()
    for s in scopes:
        if s.mesh_axes is None or s.mesh_unknown or s.shard_map \
                or s.unknown:
            return None
        axes |= s.mesh_axes
    return frozenset(axes)


class ShardingSpecAxisUnbound(Rule):
    """APX206: a ``PartitionSpec`` names an axis no reaching mesh binds.

    Two precision tiers, one finding per hazard:

    - Self-inconsistent: the axis is not on the ``NamedSharding``'s OWN
      (statically resolved) mesh — raises, but only when the annotation
      is constructed, which for TPU-gated builders is on the chip.
    - Silently replicating: the annotation is self-consistent, but the
      mesh reaching the ``with_sharding_constraint`` through the
      enclosing jit's ``in_shardings`` binds none of its axes — a stale
      mesh object from another config.  jit compiles and runs WITHOUT
      ERROR; XLA rematerializes/replicates and the "sharded" program
      quietly stops being sharded (reproduced on jax 0.4.37).
    """

    rule_id = "APX206"
    severity = "error"
    fix_hint = ("build the annotation from the SAME mesh the step's "
                "in_shardings use (thread the mesh/sharding in as an "
                "argument), or add the axis to that mesh — an axis no "
                "reaching mesh binds either dies at first trace on the "
                "chip or silently replicates")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        aliases = dataflow.value_aliases(ctx)
        flagged: set = set()
        for call in _named_sharding_calls(ctx):
            mesh, spec_expr = _ns_parts(call)
            axes = dataflow.mesh_axes_of(mesh, aliases)
            spec = dataflow.resolve_spec(spec_expr, aliases)
            if axes is None or spec is None:
                continue
            for node, name in dataflow.spec_axis_literals(spec):
                if name not in axes:
                    flagged.add(id(node))
                    known = ", ".join(sorted(axes)) or "(none)"
                    yield self.finding(
                        ctx, node,
                        f"PartitionSpec names axis {name!r} but its own "
                        f"mesh binds only {{{known}}}: NamedSharding "
                        f"construction raises — at annotation-build "
                        f"time, which for a TPU-gated step builder is "
                        f"on the chip, after CPU CI passed")
        for call, _value, annot in _constraint_calls(ctx):
            spec, own_axes = _spec_of_annotation(annot, aliases)
            if spec is None:
                continue
            reaching = _reaching_mesh(ctx, call)
            if reaching is None:
                continue
            for node, name in dataflow.spec_axis_literals(spec):
                if id(node) in flagged:
                    continue  # the self-inconsistency finding above
                if own_axes is not None and name not in own_axes:
                    continue  # ditto (NamedSharding loop owns it)
                if name not in reaching:
                    known = ", ".join(sorted(reaching)) or "(none)"
                    yield self.finding(
                        ctx, node,
                        f"with_sharding_constraint names axis {name!r} "
                        f"but the mesh reaching this jit (its "
                        f"in_shardings/out_shardings) binds only "
                        f"{{{known}}}: the annotation is from ANOTHER "
                        f"mesh — jit compiles without error and XLA "
                        f"silently rematerializes/replicates, so the "
                        f"'sharded' tensor quietly is not")


class ShardingSpecRankMismatch(Rule):
    """APX207: a spec with provably more entries than the annotated
    array has dimensions.

    ``with_sharding_constraint(jnp.zeros((8, 128)), P("dp", None,
    "tp"))`` is a trace-time error — deferred, as ever, to whenever
    that code path first traces, which for TPU-gated branches is the
    chip.  Ranks resolve through the same one-hop value-alias lattice
    as block shapes (``dataflow.creation_rank``): only arrays created
    by a local ``zeros/ones/empty/full/normal/...`` with a literal (or
    locally-aliased) shape are judged.
    """

    rule_id = "APX207"
    severity = "error"
    fix_hint = ("drop the extra spec entries (a PartitionSpec may name "
                "at most one entry per array dimension; shorter specs "
                "are legal and leave trailing dims replicated)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        aliases = dataflow.value_aliases(ctx)
        sites = list(_constraint_calls(ctx))
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and last_name(node.func) == "device_put":
                value = node.args[0] if node.args else _kwarg(node, "x")
                annot = node.args[1] if len(node.args) > 1 \
                    else _kwarg(node, "device")
                sites.append((node, value, annot))
        for call, value, annot in sites:
            spec, _own = _spec_of_annotation(annot, aliases)
            if spec is None:
                continue
            rank = dataflow.creation_rank(value, aliases)
            if rank is None:
                continue
            n = dataflow.spec_rank(spec)
            if n > rank:
                yield self.finding(
                    ctx, spec,
                    f"PartitionSpec constrains {n} dimensions but the "
                    f"annotated array is rank {rank}: a spec longer "
                    f"than the array's rank fails at trace time — on "
                    f"the chip, for TPU-gated paths (the spec probably "
                    f"belongs to a different tensor after a refactor)")


def _normalized_spec(entry: Optional[ast.AST],
                     aliases) -> Optional[Tuple]:
    """A comparable identity for one sharding annotation: the tuple of
    its P entries (None / axis name / tuple of axis names) with
    trailing Nones stripped, or None when anything is unresolvable."""
    spec, _own = _spec_of_annotation(entry, aliases)
    if spec is None:
        return None
    out: List = []
    for arg in spec.args:
        if isinstance(arg, ast.Constant) and arg.value is None:
            out.append(None)
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append(arg.value)
        elif isinstance(arg, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in arg.elts):
            out.append(tuple(e.value for e in arg.elts))
        else:
            return None
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


class DonatedShardingMismatch(Rule):
    """APX208: a donated jit argument whose in-sharding provably
    differs from EVERY out-sharding.

    Donation lets XLA alias the input buffer to an output of matching
    layout; when the annotated shardings can never match, XLA DROPS the
    donation with nothing but a ``UserWarning`` ("Some donated buffers
    were not usable") and the step's peak memory silently re-inflates
    by the donated bytes — the failure mode
    ``analysis.lowered.assert_donation_covers`` catches at compile
    time, moved to the source.  Only fully-literal spec tuples are
    compared (both sides); any unresolvable entry silences the call.
    """

    rule_id = "APX208"
    severity = "warning"
    fix_hint = ("give the donated argument an out_sharding it can "
                "alias (same PartitionSpec on the matching output), or "
                "drop it from donate_argnums — a donation XLA cannot "
                "use buys nothing and hides the real peak memory")

    @staticmethod
    def _is_jit_call(call: ast.Call) -> bool:
        """``jax.jit(...)`` directly, or the decorator spelling
        ``functools.partial(jax.jit, donate_argnums=..., ...)`` — the
        kwargs live on the partial call either way."""
        if last_name(call.func) == "jit":
            return True
        return (last_name(call.func) == "partial" and call.args
                and last_name(call.args[0]) == "jit")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        aliases = dataflow.value_aliases(ctx)
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call) or not self._is_jit_call(call):
                continue
            donate = _kwarg(call, "donate_argnums")
            ins = _kwarg(call, "in_shardings")
            outs = _kwarg(call, "out_shardings")
            if donate is None or ins is None or outs is None:
                continue
            nums = _literal_argnums(donate)
            if not nums:
                continue
            in_entries = list(ins.elts) \
                if isinstance(ins, (ast.Tuple, ast.List)) else [ins]
            out_entries = list(outs.elts) \
                if isinstance(outs, (ast.Tuple, ast.List)) else [outs]
            out_specs = [_normalized_spec(e, aliases) for e in out_entries]
            if any(s is None for s in out_specs):
                continue  # an unknowable output may alias anything
            for pos in sorted(nums):
                if pos >= len(in_entries):
                    continue
                ispec = _normalized_spec(in_entries[pos], aliases)
                if ispec is None:
                    continue
                if ispec not in out_specs:
                    yield self.finding(
                        ctx, in_entries[pos],
                        f"argument {pos} is donated but its in_sharding "
                        f"P{ispec!r} matches none of the out_shardings "
                        f"{out_specs!r}: XLA drops the donation with "
                        f"only a UserWarning, and the step's peak "
                        f"memory silently re-inflates by the donated "
                        f"buffer")
