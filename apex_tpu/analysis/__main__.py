"""CLI: ``python -m apex_tpu.analysis [paths] [options]``.

Exit codes: 0 clean (modulo baseline), 1 findings, 2 usage/baseline
error.  With no paths, scans the repo's default surface (``apex_tpu``,
``bench.py``, ``examples`` — whichever exist under the current
directory) against ``analysis_baseline.json`` when present.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from apex_tpu.analysis import (
    BaselineError, analyze_paths, apply_baseline, default_rules,
    discover_axis_registry, load_baseline, sarif, write_baseline,
)

DEFAULT_PATHS = ("apex_tpu", "bench.py", "examples")
DEFAULT_BASELINE = "analysis_baseline.json"


def _find_default_baseline(paths):
    """The committed baseline lives at the repo root; the CLI may be
    invoked from anywhere (pre-commit hooks, CI jobs with their own
    CWD).  Search the CWD, then each scanned root and its parents, so
    absolute-path invocations still pick the suppressions up instead of
    silently reporting baselined findings as live."""
    candidates = [os.getcwd()]
    for p in paths:
        d = os.path.abspath(p) if os.path.isdir(p) \
            else os.path.dirname(os.path.abspath(p))
        while True:
            candidates.append(d)
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    for c in candidates:
        f = os.path.join(c, DEFAULT_BASELINE)
        if os.path.isfile(f):
            return f
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description=__doc__.split("\n\n")[0])
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to scan (default: "
                         f"{' '.join(DEFAULT_PATHS)} where present)")
    ap.add_argument("--baseline", default=None,
                    help=f"suppression file (default: the first "
                         f"{DEFAULT_BASELINE} found in the CWD or above "
                         f"any scanned path)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: report everything")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline file for the CURRENT "
                         "findings: kept entries verbatim, stale "
                         "entries dropped, new findings added with a "
                         "justification of 'TODO' that the loader "
                         "REJECTS — the refresh is mechanical, the "
                         "review is not skippable")
    ap.add_argument("--check-baseline", action="store_true",
                    help="additionally FAIL (exit 1) when any baseline "
                         "entry no longer suppresses a finding — stale "
                         "suppressions rot silently otherwise (an entry "
                         "whose code was fixed keeps matching the next "
                         "unrelated finding that drifts into its "
                         "substring)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallelize the per-file parse/index pass "
                         "across N worker processes (module linking and "
                         "rule checks stay single-pass; results are "
                         "identical to --jobs 1)")
    ap.add_argument("--only-rules", default=None, metavar="IDS",
                    help="comma-separated rule ids to run (e.g. "
                         "APX209,APX210) — everything else is skipped; "
                         "unknown ids are a usage error, not a silent "
                         "no-op scan")
    ap.add_argument("--skip-rules", default=None, metavar="IDS",
                    help="comma-separated rule ids to skip; combines "
                         "with --only-rules (skip wins)")
    ap.add_argument("--timing", action="store_true",
                    help="print per-rule wall time (plus the shared "
                         "<load>/<link> phases) to stderr, slowest "
                         "first, then a per-family rollup (trace/io "
                         "APX1xx, concurrency APX114-116, distributed "
                         "APX2xx, kernel APX3xx, numerics APX4xx)")
    ap.add_argument("--timing-json", default=None, metavar="FILE",
                    help="also write the raw timings dict (rule id -> "
                         "seconds, plus <load>/<link>) as JSON to FILE "
                         "— the CI artifact next to the SARIF "
                         "(implies --timing collection)")
    ap.add_argument("--axes", default=None,
                    help="comma-separated collective-axis registry "
                         "override (default: *_AXIS constants parsed "
                         "from any scanned parallel_state.py)")
    ap.add_argument("--vmem-budget-mib", type=float, default=None,
                    help="APX304 per-pallas_call VMEM budget in MiB "
                         "(default 16)")
    args = ap.parse_args(argv)
    if args.update_baseline and args.no_baseline:
        # --no-baseline loads nothing, so the rewrite would drop every
        # reviewed justification and emit TODOs for the whole tree
        ap.error("--update-baseline with --no-baseline would discard "
                 "every existing justification; drop one of the flags")

    paths = args.paths or [p for p in DEFAULT_PATHS if os.path.exists(p)]
    if not paths:
        ap.error("no paths given and none of the defaults exist here")
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        ap.error(f"no such path: {missing}")

    registry = (set(a for a in args.axes.split(",") if a)
                if args.axes is not None else discover_axis_registry(paths))
    rules = default_rules(
        vmem_budget_bytes=None if args.vmem_budget_mib is None
        else int(args.vmem_budget_mib * 2 ** 20))
    known = {r.rule_id for r in rules}

    def _rule_ids(flag, value):
        ids = [x.strip() for x in value.split(",") if x.strip()]
        unknown = sorted(set(ids) - known)
        if unknown:
            ap.error(f"{flag}: unknown rule id(s) {unknown} — "
                     f"available: {', '.join(sorted(known))}")
        return set(ids)

    if args.only_rules is not None:
        rules = tuple(r for r in rules
                      if r.rule_id in _rule_ids("--only-rules",
                                                args.only_rules))
    if args.skip_rules is not None:
        rules = tuple(r for r in rules
                      if r.rule_id not in _rule_ids("--skip-rules",
                                                    args.skip_rules))
    if not rules:
        ap.error("--only-rules/--skip-rules left nothing to run")
    timings = {} if (args.timing or args.timing_json) else None
    findings = analyze_paths(paths, rules, registry, jobs=args.jobs,
                             timings=timings)
    if args.timing and timings is not None:
        for name, secs in sorted(timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"timing: {name:10s} {secs:8.3f}s", file=sys.stderr)
        families = {"APX1": "trace/io", "APX2": "distributed",
                    "APX3": "kernel", "APX4": "numerics"}
        concurrency = {"APX114", "APX115", "APX116"}
        rollup: dict = {}
        for name, secs in timings.items():
            if name in concurrency:
                fam = "concurrency"
            else:
                fam = families.get(name[:4],
                                   "shared" if name.startswith("<")
                                   else "other")
            rollup[fam] = rollup.get(fam, 0.0) + secs
        for fam, secs in sorted(rollup.items(), key=lambda kv: -kv[1]):
            print(f"timing: family {fam:12s} {secs:8.3f}s",
                  file=sys.stderr)
    if args.timing_json and timings is not None:
        with open(args.timing_json, "w") as fh:
            json.dump(dict(sorted(timings.items())), fh, indent=2)
            fh.write("\n")

    entries = []
    baseline_path = args.baseline or _find_default_baseline(paths)
    if not args.no_baseline:
        bootstrapping = (args.update_baseline and baseline_path
                         and not os.path.isfile(baseline_path))
        if baseline_path and not bootstrapping:
            try:
                entries = load_baseline(
                    baseline_path, allow_todo=args.update_baseline)
            except BaselineError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
    kept, suppressed, stale = apply_baseline(findings, entries)

    if args.update_baseline:
        target = baseline_path or DEFAULT_BASELINE
        n_kept, n_dropped, n_added = write_baseline(
            target, findings, entries)
        print(f"{target}: kept {n_kept} entr(ies), dropped {n_dropped} "
              f"stale, added {n_added} with justification \"TODO\""
              + (" — fill every TODO in before the next run will load "
                 "this file" if n_added else ""),
              file=sys.stderr)
        return 0

    if args.format == "sarif":
        print(json.dumps(sarif.render(kept, suppressed, rules), indent=2))
        if kept:
            # the red-CI-log summary: the SARIF document is for the
            # editor/code-scanning upload, not for the human reading
            # the failed job — name the damage on stderr too
            by_rule: dict = {}
            for f in kept:
                by_rule.setdefault(f.rule, 0)
                by_rule[f.rule] += 1
            rules_s = ", ".join(f"{r} x{n}" if n > 1 else r
                                for r, n in sorted(by_rule.items()))
            print(f"{len(kept)} finding(s) [{rules_s}], "
                  f"{len(suppressed)} baselined, {len(stale)} stale "
                  f"baseline entr(ies) — full detail in the SARIF "
                  f"document above", file=sys.stderr)
    elif args.format == "json":
        print(json.dumps({
            "findings": [f.to_json() for f in kept],
            "suppressed": [f.to_json() for f in suppressed],
            "stale_baseline_entries": [
                {"rule": e.rule, "path": e.path, "symbol": e.symbol}
                for e in stale],
            "axes": sorted(registry),
        }, indent=2))
    else:
        for f in kept:
            print(f.render())
        for e in stale:
            print(f"note: stale baseline entry ({e.rule} {e.path} "
                  f"{e.symbol}) suppresses nothing — remove it",
                  file=sys.stderr)
        print(f"{len(kept)} finding(s), {len(suppressed)} baselined, "
              f"{len(stale)} stale baseline entr(ies)", file=sys.stderr)
    if args.check_baseline and stale:
        for e in stale:
            print(f"error: stale baseline entry ({e.rule} {e.path} "
                  f"{e.symbol}) suppresses nothing — the code it "
                  f"covered was fixed; remove the entry "
                  f"(--check-baseline)", file=sys.stderr)
        return 1
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
