"""Lowered-artifact invariant checkers — the analyzer's SECOND tier.

The AST tier (the rest of this package) proves properties of source
code; this module proves properties of what the compiler was actually
ASKED to do, by pattern-matching ``jax.jit(...).lower(...)`` artifacts.
The two tiers are complementary: no AST rule can see that a bucketed
optimizer's grad sync lowered to one reduce-scatter per bucket, and no
HLO grep survives a refactor that renames the function it was pinned
to — these checkers live in tests, next to the step builders they pin.

**This module imports jax** and is deliberately NOT imported by
``apex_tpu.analysis.__init__`` or the CLI: the no-jax contract of the
AST tier (runs in broken containers, over trees that do not import)
stays intact.  Import it explicitly — ``from apex_tpu.analysis import
lowered`` — from test code.

Checkers accept a ``jax.stages.Lowered``, anything with ``as_text()``,
or a plain StableHLO/MHLO text dump.  They assert on the LOWERING, not
the compiled module, wherever possible: the CPU backend's compile
rewrites TPU-irrelevant details (e.g. upcasting bf16 collectives), so
the lowering is what faithfully records the program's intent.  The one
exception is :func:`assert_donation_covers` with ``compiled=True``,
which reads the compiled module's ``input_output_alias`` header — the
aliasing table only materializes at compile time.

Born from PR 4's inline string-grep asserts in
``tests/test_distributed_optimizers.py`` (per-bucket reduce-scatters,
no whole-tree concat, donation aliasing), refactored here so
``tests/test_lowered_invariants.py`` can pin the same invariants on
the real GPT train steps.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import jax

__all__ = [
    "hlo_text", "count_collectives", "operand_dtypes",
    "assert_collective_dtype", "assert_no_host_transfer",
    "assert_no_whole_tree_concat", "assert_donation_covers",
    "donated_buffer_count", "host_transfer_sites",
]

#: collective ops that carry a reduction REGION in StableHLO — their
#: type signature follows the closing ``})`` of the region, so the
#: dtype regex must skip it (re.S); region-less ops type right after
#: their attribute dict on the same line.
_REGION_OPS = {"reduce_scatter", "all_reduce", "reduce"}


def hlo_text(artifact) -> str:
    """The StableHLO/MHLO text of a lowering artifact: a str passes
    through, anything with ``as_text()`` (``Lowered``, ``Compiled``)
    is rendered."""
    if isinstance(artifact, str):
        return artifact
    if hasattr(artifact, "as_text"):
        return artifact.as_text()
    raise TypeError(
        f"expected StableHLO text or an object with as_text() "
        f"(jax.stages.Lowered / Compiled), got {type(artifact).__name__}")


def _op_occurrences(txt: str, kind: str) -> List[str]:
    # MLIR prints ops in generic quoted form ("stablehlo.reduce_scatter")
    # inside shard_map bodies and pretty unquoted form (stablehlo.
    # concatenate) elsewhere — match the dotted name either way
    return re.findall(
        r'(?:stablehlo|mhlo)\.' + re.escape(kind) + r'\b', txt)


def count_collectives(artifact, kind: str, *,
                      minimum: Optional[int] = None,
                      maximum: Optional[int] = None) -> int:
    """Occurrences of one collective (``reduce_scatter``,
    ``all_gather``, ``all_reduce``, ``all_to_all``,
    ``collective_permute``, ...) in the lowering.  With ``minimum``/
    ``maximum`` given, asserts the count is inside the bounds — the
    per-bucket contract reads ``count_collectives(txt,
    "reduce_scatter", minimum=n_buckets, maximum=n_buckets)``."""
    txt = hlo_text(artifact)
    n = len(_op_occurrences(txt, kind))
    if minimum is not None:
        assert n >= minimum, (
            f"expected >= {minimum} {kind} collective(s) in the "
            f"lowering, found {n} — the per-bucket plan did not lower "
            f"to per-bucket collectives")
    if maximum is not None:
        assert n <= maximum, (
            f"expected <= {maximum} {kind} collective(s) in the "
            f"lowering, found {n} — something introduced extra "
            f"collectives (a whole-tree sync path?)")
    return n


def operand_dtypes(artifact, kind: str) -> List[str]:
    """Element dtype of each ``kind`` collective's first operand, in
    program order (``["bf16", "f32"]`` for a two-dtype bucket plan).
    Ops with reduction regions type after the region's ``})``;
    region-less ops type directly."""
    txt = hlo_text(artifact)
    if kind in _REGION_OPS:
        pat = (r'"?(?:stablehlo|mhlo)\.' + re.escape(kind)
               + r'\b.*?\}\)\s*:\s*\(tensor<[0-9x]*x?(\w+)>')
        return re.findall(pat, txt, re.S)
    # the literal "( " before tensor<> is load-bearing: it anchors the
    # match to the op's TYPE SIGNATURE, skipping `dense<...> :
    # tensor<NxMxi64>` replica_groups attributes inside the attr dict
    pat = (r'"?(?:stablehlo|mhlo)\.' + re.escape(kind)
           + r'\b.*?:\s*\(tensor<[0-9x]*x?(\w+)>')
    return re.findall(pat, txt)


def assert_collective_dtype(artifact, kind: str, dtype: str,
                            mode: str = "any") -> None:
    """Assert the wire dtype of ``kind`` collectives: ``mode="any"`` —
    at least one runs in ``dtype`` (the bf16 bucket syncs in bf16);
    ``mode="all"`` — every one does (grad_sync_dtype=fp32 forces the
    whole plan up); ``mode="none"`` — none does."""
    dts = operand_dtypes(artifact, kind)
    if mode == "any":
        assert dtype in dts, (
            f"no {kind} with {dtype} operands in the lowering "
            f"(found {dts or 'none'}) — the {dtype} bucket is not "
            f"syncing on its own wire type")
    elif mode == "all":
        assert dts and all(d == dtype for d in dts), (
            f"expected every {kind} in {dtype}, found {dts or 'none'}")
    elif mode == "none":
        assert dtype not in dts, (
            f"found a {kind} with {dtype} operands ({dts}) — "
            f"expected none")
    else:
        raise ValueError(f"mode must be any/all/none, got {mode!r}")


def assert_no_whole_tree_concat(artifact, total_elements: int,
                                dtype: str = "f32") -> None:
    """No concatenate producing the FULL flat tree (``total_elements``
    x ``dtype``) anywhere in the lowering — the signature of the
    pre-bucket ``_flatten`` stub (one whole-model HBM round trip per
    step) that the bucket plan exists to avoid."""
    txt = hlo_text(artifact)
    m = re.search(
        r'"?(?:stablehlo|mhlo)\.concatenate"?.*->\s*tensor<'
        + str(int(total_elements)) + r'x' + re.escape(dtype) + r'>', txt)
    assert m is None, (
        f"the lowering concatenates the whole tree to one "
        f"tensor<{total_elements}x{dtype}> — a full-model flatten is "
        f"back in the step (the pre-bucket _flatten shape)")


#: StableHLO ops that move data across the device/host boundary
_HOST_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv")

#: custom_call targets that round-trip through the host: Python
#: callbacks (io_callback / pure_callback / debug.print lower to
#: these) and explicit host-memory placement
_HOST_CALL_MARKERS = ("callback", "host")


def host_transfer_sites(artifact) -> List[str]:
    """Every host-transfer site in the lowering, as matched snippets:
    infeed/outfeed/send/recv ops plus ``custom_call`` targets naming a
    Python callback or host placement.  Empty list = the program runs
    entirely on device."""
    txt = hlo_text(artifact)
    sites = []
    for op in _HOST_TRANSFER_OPS:
        sites.extend(_op_occurrences(txt, op))
    # custom_call targets appear as `@target(` in pretty form and as
    # call_target_name = "target" in generic form
    targets = re.findall(
        r'custom_call\s*@([\w.\-]+)\(', txt)
    targets += re.findall(r'call_target_name\s*=\s*"([^"]+)"', txt)
    for t in targets:
        low = t.lower()
        if any(m in low for m in _HOST_CALL_MARKERS):
            sites.append(f"custom_call @{t}")
    return sites


def assert_no_host_transfer(artifact) -> None:
    """The lowering must contain ZERO host transfers — no infeed/
    outfeed/send/recv, no Python-callback or host-placement custom
    calls.  The decode-step contract (ROADMAP: "decode step pinned to
    zero host transfers"): one stray ``debug.print``, ``io_callback``,
    or host-pinned buffer inserts a device->host sync into a loop that
    runs tens of times per generated token."""
    sites = host_transfer_sites(artifact)
    assert not sites, (
        f"the lowering contains {len(sites)} host-transfer site(s): "
        f"{sites[:5]} — a compiled hot-loop step must run entirely on "
        f"device (drop the callback/debug print, or move the host work "
        f"between steps)")


def donated_buffer_count(artifact) -> int:
    """Buffers the LOWERING declares donatable: ``jax.buffer_donor``
    (shard_map inputs) plus ``tf.aliasing_output`` (plain-jit donated
    args pre-aliased to outputs)."""
    txt = hlo_text(artifact)
    return txt.count("jax.buffer_donor") + txt.count("tf.aliasing_output")


def _expected_leaves(donated_trees: Sequence, extra: int) -> int:
    return extra + sum(
        len(jax.tree_util.tree_leaves(t)) for t in donated_trees)


def assert_donation_covers(lowered, *donated_trees, extra: int = 0,
                           compiled: bool = True) -> None:
    """Every leaf of ``donated_trees`` (plus ``extra`` buffers) must be
    donated through the step: the lowering declares at least that many
    donatable buffers, and — with ``compiled=True`` — the compiled
    module's ``input_output_alias`` table actually aliases them to
    outputs.  Donation that LOWERS but does not ALIAS is the silent
    failure mode (XLA drops donations it cannot use, keeping the ~3x
    param-bytes peak the donation was written to avoid), so prefer the
    compiled check whenever the test budget allows; ``compiled=False``
    skips the XLA compile and pins only the declaration."""
    n = _expected_leaves(donated_trees, extra)
    assert n > 0, "no donated leaves to check — pass the donated trees"
    declared = donated_buffer_count(lowered)
    assert declared >= n, (
        f"{declared} buffer(s) declared donatable in the lowering but "
        f"the donated trees hold {n} leaves — donate_argnums is not "
        f"covering the state (dropped arg? tuple index drift?)")
    if not compiled:
        return
    hdr = lowered.compile().as_text().splitlines()[0]
    assert "input_output_alias=" in hdr, (
        f"compiled module has no input_output_alias table at all — "
        f"every donation was dropped: {hdr}")
    aliased = hdr.count("may-alias") + hdr.count("must-alias")
    assert aliased >= n, (
        f"only {aliased} aliased buffer(s) in input_output_alias for "
        f"{n} donated leaves — XLA dropped donations (dtype/layout "
        f"mismatch between the donated input and every output?)")
