"""Lowered-artifact invariant checkers — the analyzer's SECOND tier.

The AST tier (the rest of this package) proves properties of source
code; this module proves properties of what the compiler was actually
ASKED to do, by pattern-matching ``jax.jit(...).lower(...)`` artifacts.
The two tiers are complementary: no AST rule can see that a bucketed
optimizer's grad sync lowered to one reduce-scatter per bucket, and no
HLO grep survives a refactor that renames the function it was pinned
to — these checkers live in tests, next to the step builders they pin.

**This module imports jax** and is deliberately NOT imported by
``apex_tpu.analysis.__init__`` or the CLI: the no-jax contract of the
AST tier (runs in broken containers, over trees that do not import)
stays intact.  Import it explicitly — ``from apex_tpu.analysis import
lowered`` — from test code.

Checkers accept a ``jax.stages.Lowered``, anything with ``as_text()``,
or a plain StableHLO/MHLO text dump.  They assert on the LOWERING, not
the compiled module, wherever possible: the CPU backend's compile
rewrites TPU-irrelevant details (e.g. upcasting bf16 collectives), so
the lowering is what faithfully records the program's intent.  The one
exception is :func:`assert_donation_covers` with ``compiled=True``,
which reads the compiled module's ``input_output_alias`` header — the
aliasing table only materializes at compile time.

Born from PR 4's inline string-grep asserts in
``tests/test_distributed_optimizers.py`` (per-bucket reduce-scatters,
no whole-tree concat, donation aliasing), refactored here so
``tests/test_lowered_invariants.py`` can pin the same invariants on
the real GPT train steps.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import jax

__all__ = [
    "hlo_text", "count_collectives", "operand_dtypes",
    "collective_sites", "collective_schedule", "mesh_axis_groups",
    "assert_collective_axes", "assert_collective_dtype",
    "assert_no_host_transfer", "assert_no_recompile",
    "assert_no_whole_tree_concat", "assert_same_collective_schedule",
    "assert_interleaved", "interleave_gaps",
    "assert_donation_covers", "donated_buffer_count",
    "host_transfer_sites",
    "arg_shardings", "sharding_of", "assert_sharding",
    "spmd_collective_sites", "assert_spmd_collectives",
]

#: collective ops that carry a reduction REGION in StableHLO — their
#: type signature follows the closing ``})`` of the region, so the
#: dtype regex must skip it (re.S); region-less ops type right after
#: their attribute dict on the same line.
_REGION_OPS = {"reduce_scatter", "all_reduce", "reduce"}


def hlo_text(artifact) -> str:
    """The StableHLO/MHLO text of a lowering artifact: a str passes
    through, anything with ``as_text()`` (``Lowered``, ``Compiled``)
    is rendered."""
    if isinstance(artifact, str):
        return artifact
    if hasattr(artifact, "as_text"):
        return artifact.as_text()
    raise TypeError(
        f"expected StableHLO text or an object with as_text() "
        f"(jax.stages.Lowered / Compiled), got {type(artifact).__name__}")


def _op_occurrences(txt: str, kind: str) -> List[str]:
    # MLIR prints ops in generic quoted form ("stablehlo.reduce_scatter")
    # inside shard_map bodies and pretty unquoted form (stablehlo.
    # concatenate) elsewhere — match the dotted name either way
    return re.findall(
        r'(?:stablehlo|mhlo)\.' + re.escape(kind) + r'\b', txt)


#: how far past an op's name to look for its attribute dict — the
#: replica_groups attr precedes the (possibly multi-line) reduction
#: region, so a bounded window is enough and never bleeds into the
#: NEXT collective's attrs (ops are > 40 chars of SSA plumbing apart).
_ATTR_WINDOW = 4000


def _parse_replica_groups(window: str) -> Optional[List[List[int]]]:
    m = re.search(r'replica_groups\s*=\s*dense<([^>]*)>', window)
    if m is None:
        return None
    body = m.group(1).strip()
    try:
        if body.startswith("["):
            import ast

            val = ast.literal_eval(body)
            if isinstance(val, list) and val and not isinstance(val[0], list):
                val = [val]
            return [[int(x) for x in grp] for grp in val]
        # splat form dense<0> : tensor<1x1xi64> — a single singleton
        return [[int(body)]]
    except (ValueError, SyntaxError):
        return None


def collective_sites(artifact, kind: str) -> List[dict]:
    """Every ``kind`` collective in program order, as
    ``{"dtype": str|None, "replica_groups": [[int, ...], ...]|None}``
    — the per-site view :func:`count_collectives`'s ``axes=`` filter
    and :func:`assert_collective_axes` are built on.  ``dtype`` is the
    first operand's element type (as in :func:`operand_dtypes`);
    ``replica_groups`` indexes the mesh's logical device order (what
    shard_map lowers), None when the op carries no parseable groups."""
    txt = hlo_text(artifact)
    if kind in _REGION_OPS:
        dt_pat = re.compile(r'\}\)\s*:\s*\(tensor<[0-9x]*x?(\w+)>', re.S)
    else:
        dt_pat = re.compile(r':\s*\(tensor<[0-9x]*x?(\w+)>')
    sites = []
    for m in re.finditer(
            r'"?(?:stablehlo|mhlo)\.' + re.escape(kind) + r'\b', txt):
        window = txt[m.start():m.start() + _ATTR_WINDOW]
        dt = dt_pat.search(window)
        sites.append({
            "dtype": dt.group(1) if dt else None,
            "replica_groups": _parse_replica_groups(window),
        })
    return sites


def mesh_axis_groups(mesh, axes) -> List[List[int]]:
    """The ``replica_groups`` a collective over ``axes`` of ``mesh``
    lowers with: the partition of the mesh's logical device indices
    (row-major over ``mesh.axis_names``) that varies exactly the named
    axes and holds every other axis fixed — e.g. on
    ``Mesh((2, 2), ("dp_out", "dp_in"))``, ``("dp_in",)`` gives
    ``[[0, 1], [2, 3]]`` and ``("dp_out",)`` gives ``[[0, 2], [1, 3]]``."""
    import numpy as np

    names = list(mesh.axis_names)
    axes = [axes] if isinstance(axes, str) else list(axes)
    unknown = [a for a in axes if a not in names]
    if unknown:
        raise ValueError(f"axes {unknown} not on mesh {tuple(names)}")
    shape = [mesh.shape[n] for n in names]
    ids = np.arange(int(np.prod(shape))).reshape(shape)
    other = [i for i, n in enumerate(names) if n not in axes]
    coll = [names.index(a) for a in axes]
    group_size = int(np.prod([shape[i] for i in coll])) if coll else 1
    return ids.transpose(other + coll).reshape(-1, group_size).tolist()


def _groups_key(groups) -> Optional[frozenset]:
    """Order-insensitive identity of a replica-group partition (the
    lowering may emit groups, and ids within groups, in any order)."""
    if groups is None:
        return None
    return frozenset(frozenset(g) for g in groups)


#: the cross-device ops a schedule tracks, in one program-order scan.
#: ``reduce`` (local) is deliberately absent; ``collective_permute``
#: and ``collective_broadcast`` carry no replica_groups — their groups
#: entry is None and the kind/dtype/shape still pin the sequence.
_SCHEDULE_KINDS = (
    "all_gather", "all_reduce", "all_to_all", "collective_broadcast",
    "collective_permute", "reduce_scatter",
)


def collective_schedule(artifact, mesh=None) -> List[dict]:
    """The ordered cross-device communication sequence of a lowering:
    one entry per collective op in program order, each
    ``{"kind", "dtype", "shape", "groups"}`` — ``shape`` is the first
    operand's dims tuple (None when unparseable), ``groups`` the
    order-insensitive :func:`_groups_key` of its replica groups.

    Two processes that lower DIFFERENT schedules for the same step
    deadlock the pod: each rank blocks in its own next collective,
    device-side, with no error.  This is the thing
    ``assert_same_collective_schedule`` pins and the APX209/210/211
    divergence rules prove statically.

    With ``mesh=`` given, each entry also carries ``"axes"`` — the
    mesh-axis subset whose :func:`mesh_axis_groups` partition equals
    the op's groups (None when no subset matches, e.g. GSPMD-chosen
    groupings that cross axis boundaries)."""
    txt = hlo_text(artifact)
    axis_of = None
    if mesh is not None:
        import itertools

        names = list(mesh.axis_names)
        axis_of = {}
        for r in range(1, len(names) + 1):
            for combo in itertools.combinations(names, r):
                key = _groups_key(mesh_axis_groups(mesh, combo))
                axis_of.setdefault(key, combo)
    occurrences = []
    for kind in _SCHEDULE_KINDS:
        # StableHLO/MHLO dotted spelling (jit/shard_map lowerings)
        for m in re.finditer(
                r'"?(?:stablehlo|mhlo)\.' + re.escape(kind) + r'\b', txt):
            occurrences.append((m.start(), kind, "mlir", m))
        # compiled-HLO dashed spelling (post-SPMD-partitioning modules;
        # only the plain/-start op, never the async -done — same rule
        # as spmd_collective_sites)
        dashed = kind.replace("_", "-")
        for m in re.finditer(
                r'=\s*\(?([a-zA-Z0-9]+)\[([0-9,]*)\][^=\n]*?\s'
                + re.escape(dashed) + r'(?:-start)?\(', txt):
            occurrences.append((m.start(), kind, "hlo", m))
    occurrences.sort(key=lambda o: o[0])
    schedule = []
    for pos, kind, form, m in occurrences:
        dtype = shape = None
        if form == "mlir":
            window = txt[pos:pos + _ATTR_WINDOW]
            if kind in _REGION_OPS:
                tm = re.search(r'\}\)\s*:\s*\(tensor<([0-9a-zA-Z_x]*)>',
                               window, re.S)
            else:
                tm = re.search(r':\s*\(tensor<([0-9a-zA-Z_x]*)>', window)
            if tm is not None:
                parts = tm.group(1).split("x")
                dtype = parts[-1] or None
                try:
                    shape = tuple(int(d) for d in parts[:-1])
                except ValueError:
                    shape = None
            groups = _parse_replica_groups(window)
        else:
            dtype = m.group(1)
            try:
                shape = tuple(int(d) for d in m.group(2).split(",")
                              if d.strip())
            except ValueError:
                shape = None
            line_end = txt.find("\n", m.end())
            window = txt[m.end():
                         line_end if line_end != -1 else len(txt)]
            gm = re.search(
                r'replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|'
                r'\[[^\]]+\]<=\[[^\]]+\](?:T\([\d,]+\))?)', window)
            groups = _parse_hlo_groups(gm.group(1)) if gm else None
        entry = {
            "kind": kind,
            "dtype": dtype,
            "shape": shape,
            "groups": _groups_key(groups),
        }
        if axis_of is not None:
            entry["axes"] = axis_of.get(entry["groups"])
        schedule.append(entry)
    return schedule


def _schedule_entry_str(entry: dict) -> str:
    groups = entry["groups"]
    g = "-" if groups is None else \
        "|".join(",".join(str(i) for i in sorted(grp))
                 for grp in sorted(groups, key=min))
    axes = entry.get("axes")
    over = f" over {axes}" if axes else ""
    return (f"{entry['kind']}<{'x'.join(map(str, entry['shape'] or ()))}"
            f"x{entry['dtype']}> groups=[{g}]{over}")


def assert_same_collective_schedule(*artifacts, labels=None,
                                    mesh=None) -> List[List[dict]]:
    """Assert every lowering emits the IDENTICAL ordered collective
    sequence (kind, dtype, shape, replica groups, position by
    position).  This is the single-process proof of multi-process
    safety: rank-specialized variants of one step that lower different
    schedules WILL wedge a real pod, and this assertion names the
    first diverging op instead.  Returns the schedules (first is the
    reference)."""
    if len(artifacts) < 2:
        raise ValueError("need at least two lowerings to compare")
    if labels is None:
        labels = [f"variant[{i}]" for i in range(len(artifacts))]
    labels = list(labels)
    if len(labels) != len(artifacts):
        raise ValueError(f"{len(labels)} labels for "
                         f"{len(artifacts)} lowerings")
    schedules = [collective_schedule(a, mesh=mesh) for a in artifacts]
    ref, ref_label = schedules[0], labels[0]
    for label, sched in zip(labels[1:], schedules[1:]):
        for i, (a, b) in enumerate(zip(ref, sched)):
            assert a == b, (
                f"collective schedules diverge at op {i}: "
                f"{ref_label} lowers {_schedule_entry_str(a)}, "
                f"{label} lowers {_schedule_entry_str(b)} — on a pod "
                f"these ranks block in different collectives and the "
                f"step wedges device-side with no error")
        assert len(ref) == len(sched), (
            f"collective schedules diverge in length: {ref_label} "
            f"lowers {len(ref)} collective(s), {label} lowers "
            f"{len(sched)} — the longer program blocks in a "
            f"collective its peers never enter")
    return schedules


def count_collectives(artifact, kind: str, *,
                      minimum: Optional[int] = None,
                      maximum: Optional[int] = None,
                      axes=None, mesh=None) -> int:
    """Occurrences of one collective (``reduce_scatter``,
    ``all_gather``, ``all_reduce``, ``all_to_all``,
    ``collective_permute``, ...) in the lowering.  With ``minimum``/
    ``maximum`` given, asserts the count is inside the bounds — the
    per-bucket contract reads ``count_collectives(txt,
    "reduce_scatter", minimum=n_buckets, maximum=n_buckets)``.

    ``axes=`` (with ``mesh=``) counts only the occurrences whose
    ``replica_groups`` equal the partition a collective over exactly
    those mesh axes lowers with — the per-hop contract of the
    hierarchical sync plan reads ``count_collectives(txt,
    "reduce_scatter", axes=("dp_in",), mesh=mesh, minimum=n,
    maximum=n)``."""
    if axes is not None:
        if mesh is None:
            raise ValueError("axes= filtering needs mesh= (the groups "
                             "are computed from the mesh layout)")
        want = _groups_key(mesh_axis_groups(mesh, axes))
        sites = collective_sites(artifact, kind)
        n = sum(1 for s in sites
                if _groups_key(s["replica_groups"]) == want)
        label = f"{kind} over axes {tuple(axes) if not isinstance(axes, str) else (axes,)}"
    else:
        txt = hlo_text(artifact)
        n = len(_op_occurrences(txt, kind))
        label = kind
    if minimum is not None:
        assert n >= minimum, (
            f"expected >= {minimum} {label} collective(s) in the "
            f"lowering, found {n} — the per-bucket plan did not lower "
            f"to per-bucket collectives")
    if maximum is not None:
        assert n <= maximum, (
            f"expected <= {maximum} {label} collective(s) in the "
            f"lowering, found {n} — something introduced extra "
            f"collectives (a whole-tree sync path?)")
    return n


def assert_collective_axes(artifact, kind: str, axes, mesh, *,
                           minimum: Optional[int] = None,
                           maximum: Optional[int] = None,
                           dtype: Optional[str] = None) -> int:
    """The per-hop pin: count ``kind`` collectives running over exactly
    ``axes`` of ``mesh`` (bounds as in :func:`count_collectives`), and
    — with ``dtype`` — assert EVERY one of those carries that operand
    element type (the hop's wire dtype).  Returns the matched count."""
    n = count_collectives(artifact, kind, axes=axes, mesh=mesh,
                          minimum=minimum, maximum=maximum)
    if dtype is not None:
        want = _groups_key(mesh_axis_groups(mesh, axes))
        bad = [s["dtype"] for s in collective_sites(artifact, kind)
               if _groups_key(s["replica_groups"]) == want
               and s["dtype"] != dtype]
        assert not bad, (
            f"{kind} over axes {axes} must run in {dtype}, found "
            f"{bad} — a hop is not on its wire dtype")
    return n


#: the matmul spellings between which interleaving is measured: the
#: StableHLO/MHLO dotted op and the compiled-HLO ``dot(`` instruction.
_DOT_PATTERNS = (
    r'"?(?:stablehlo|mhlo)\.dot_general\b',
    r'=\s*\(?[a-zA-Z0-9]+\[[0-9,]*\][^=\n]*?\sdot\(',
)


def _dot_events(txt: str) -> List[tuple]:
    """``(position, weight)`` events for every matmul REACHABLE at a
    program point, in text order.  Inline ``dot_general`` ops weigh 1
    at their own position; a ``call @fn`` site weighs the TRANSITIVE
    dot count of its callee at the call's position — jax outlines
    ``lax.scan`` bodies (and remat blocks) into private functions, so
    the backward scan's matmuls are textually out-of-line and only
    reachable through the ``stablehlo.while`` region's call sites."""
    raw = sorted(p for pat in _DOT_PATTERNS
                 for p in (m.start() for m in re.finditer(pat, txt)))
    starts = [(m.start(), m.group(1)) for m in re.finditer(
        r'func\.func[^\n]*?@([\w.$-]+)\(', txt)]
    spans = {}
    for i, (pos, name) in enumerate(starts):
        end = starts[i + 1][0] if i + 1 < len(starts) else len(txt)
        spans[name] = (pos, end)
    calls = [(m.start(), m.group(1)) for m in re.finditer(
        r'\bcall\s+@([\w.$-]+)\(', txt)]
    memo = {}

    def total(fn, trail):
        if fn in memo:
            return memo[fn]
        if fn not in spans or fn in trail:
            return 0
        lo, hi = spans[fn]
        n = sum(1 for p in raw if lo <= p < hi)
        n += sum(total(callee, trail | {fn}) for cp, callee in calls
                 if lo <= cp < hi)
        memo[fn] = n
        return n

    events = [(p, 1) for p in raw]
    events += [(cp, total(callee, frozenset()))
               for cp, callee in calls if total(callee, frozenset())]
    events.sort()
    return events


def interleave_gaps(artifact, kind: str = "reduce_scatter", *,
                    axes=None, mesh=None,
                    dtype: Optional[str] = None) -> List[int]:
    """How many ``dot_general`` ops sit STRICTLY BETWEEN each pair of
    consecutive ``kind`` collectives, in program order: a list of
    ``n_sites - 1`` counts.  ``axes=`` (with ``mesh=``) and ``dtype=``
    narrow the collectives to one hop / one wire dtype, exactly as in
    :func:`count_collectives` / :func:`assert_collective_axes` — the
    dots counted between them are ALL dots, unfiltered, because any
    matmul between two syncs is compute the scheduler can overlap.

    This is the lowering-level evidence for backward-overlapped grad
    sync: an unoverlapped step traces every bucket's collective after
    the whole backward (all gaps 0), an overlapped one issues bucket
    k's sync before bucket k+1's backward dots (some gap > 0)."""
    txt = hlo_text(artifact)
    want = None
    if axes is not None:
        if mesh is None:
            raise ValueError("axes= filtering needs mesh= (the groups "
                             "are computed from the mesh layout)")
        want = _groups_key(mesh_axis_groups(mesh, axes))
    sites = []
    # StableHLO/MHLO dotted spelling (jit/shard_map lowerings)
    for m in re.finditer(
            r'"?(?:stablehlo|mhlo)\.' + re.escape(kind) + r'\b', txt):
        window = txt[m.start():m.start() + _ATTR_WINDOW]
        if want is not None and \
                _groups_key(_parse_replica_groups(window)) != want:
            continue
        if dtype is not None:
            if kind in _REGION_OPS:
                tm = re.search(r'\}\)\s*:\s*\(tensor<([0-9a-zA-Z_x]*)>',
                               window, re.S)
            else:
                tm = re.search(r':\s*\(tensor<([0-9a-zA-Z_x]*)>', window)
            if tm is None or tm.group(1).split("x")[-1] != dtype:
                continue
        sites.append(m.start())
    # compiled-HLO dashed spelling (post-SPMD-partitioning modules)
    dashed = kind.replace("_", "-")
    for m in re.finditer(
            r'=\s*\(?([a-zA-Z0-9]+)\[[0-9,]*\][^=\n]*?\s'
            + re.escape(dashed) + r'(?:-start)?\(', txt):
        if dtype is not None and m.group(1) != dtype:
            continue
        if want is not None:
            line_end = txt.find("\n", m.end())
            window = txt[m.end():
                         line_end if line_end != -1 else len(txt)]
            gm = re.search(
                r'replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|'
                r'\[[^\]]+\]<=\[[^\]]+\](?:T\([\d,]+\))?)', window)
            groups = _parse_hlo_groups(gm.group(1)) if gm else None
            if _groups_key(groups) != want:
                continue
        sites.append(m.start())
    sites.sort()
    if len(sites) < 2:
        raise ValueError(
            f"interleaving needs at least two {kind} collectives in "
            f"the lowering to have a between, found {len(sites)} "
            f"(after axes/dtype filtering)")
    events = _dot_events(txt)
    gaps = []
    for lo, hi in zip(sites, sites[1:]):
        gaps.append(sum(w for p, w in events if lo < p < hi))
    return gaps


def assert_interleaved(artifact, kind: str = "reduce_scatter", *,
                       axes=None, mesh=None, dtype: Optional[str] = None,
                       min_between: int = 1,
                       gaps: str = "any") -> List[int]:
    """Pin the compute/communication interleaving shape of a lowering.

    ``gaps="any"`` (the overlapped shape): assert at least one pair of
    consecutive ``kind`` collectives has >= ``min_between``
    ``dot_general`` ops between them — backward matmuls run between
    bucket syncs, so the latency-hiding scheduler CAN overlap them.
    ``gaps="none"`` (the unoverlapped shape): assert every consecutive
    pair has ZERO dots between — all collectives trace after the whole
    backward.  ``gaps="all"`` is deliberately absent: buckets that
    become ready at the same backward stage legitimately sync
    back-to-back.  Returns the gap list from :func:`interleave_gaps`."""
    counts = interleave_gaps(artifact, kind, axes=axes, mesh=mesh,
                             dtype=dtype)
    if gaps == "any":
        assert any(c >= min_between for c in counts), (
            f"no pair of consecutive {kind} collectives has >= "
            f"{min_between} dot_general between them (gaps={counts}) — "
            f"every sync traces after the whole backward, so the "
            f"scheduler has no compute to hide the collectives behind")
    elif gaps == "none":
        assert all(c == 0 for c in counts), (
            f"found dot_general ops between consecutive {kind} "
            f"collectives (gaps={counts}) — the unoverlapped step "
            f"should trace every bucket sync after the whole backward")
    else:
        raise ValueError(f'gaps must be "any" or "none", got {gaps!r}')
    return counts


def operand_dtypes(artifact, kind: str) -> List[str]:
    """Element dtype of each ``kind`` collective's first operand, in
    program order (``["bf16", "f32"]`` for a two-dtype bucket plan).
    Ops with reduction regions type after the region's ``})``;
    region-less ops type directly."""
    txt = hlo_text(artifact)
    if kind in _REGION_OPS:
        pat = (r'"?(?:stablehlo|mhlo)\.' + re.escape(kind)
               + r'\b.*?\}\)\s*:\s*\(tensor<[0-9x]*x?(\w+)>')
        return re.findall(pat, txt, re.S)
    # the literal "( " before tensor<> is load-bearing: it anchors the
    # match to the op's TYPE SIGNATURE, skipping `dense<...> :
    # tensor<NxMxi64>` replica_groups attributes inside the attr dict
    pat = (r'"?(?:stablehlo|mhlo)\.' + re.escape(kind)
           + r'\b.*?:\s*\(tensor<[0-9x]*x?(\w+)>')
    return re.findall(pat, txt)


def assert_collective_dtype(artifact, kind: str, dtype: str,
                            mode: str = "any") -> None:
    """Assert the wire dtype of ``kind`` collectives: ``mode="any"`` —
    at least one runs in ``dtype`` (the bf16 bucket syncs in bf16);
    ``mode="all"`` — every one does (grad_sync_dtype=fp32 forces the
    whole plan up); ``mode="none"`` — none does."""
    dts = operand_dtypes(artifact, kind)
    if mode == "any":
        assert dtype in dts, (
            f"no {kind} with {dtype} operands in the lowering "
            f"(found {dts or 'none'}) — the {dtype} bucket is not "
            f"syncing on its own wire type")
    elif mode == "all":
        assert dts and all(d == dtype for d in dts), (
            f"expected every {kind} in {dtype}, found {dts or 'none'}")
    elif mode == "none":
        assert dtype not in dts, (
            f"found a {kind} with {dtype} operands ({dts}) — "
            f"expected none")
    else:
        raise ValueError(f"mode must be any/all/none, got {mode!r}")


def assert_no_whole_tree_concat(artifact, total_elements: int,
                                dtype: str = "f32") -> None:
    """No concatenate producing the FULL flat tree (``total_elements``
    x ``dtype``) anywhere in the lowering — the signature of the
    pre-bucket ``_flatten`` stub (one whole-model HBM round trip per
    step) that the bucket plan exists to avoid."""
    txt = hlo_text(artifact)
    m = re.search(
        r'"?(?:stablehlo|mhlo)\.concatenate"?.*->\s*tensor<'
        + str(int(total_elements)) + r'x' + re.escape(dtype) + r'>', txt)
    assert m is None, (
        f"the lowering concatenates the whole tree to one "
        f"tensor<{total_elements}x{dtype}> — a full-model flatten is "
        f"back in the step (the pre-bucket _flatten shape)")


#: StableHLO ops that move data across the device/host boundary
_HOST_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv")

#: custom_call targets that round-trip through the host: Python
#: callbacks (io_callback / pure_callback / debug.print lower to
#: these) and explicit host-memory placement
_HOST_CALL_MARKERS = ("callback", "host")


def host_transfer_sites(artifact) -> List[str]:
    """Every host-transfer site in the lowering, as matched snippets:
    infeed/outfeed/send/recv ops plus ``custom_call`` targets naming a
    Python callback or host placement.  Empty list = the program runs
    entirely on device."""
    txt = hlo_text(artifact)
    sites = []
    for op in _HOST_TRANSFER_OPS:
        sites.extend(_op_occurrences(txt, op))
    # custom_call targets appear as `@target(` in pretty form and as
    # call_target_name = "target" in generic form
    targets = re.findall(
        r'custom_call\s*@([\w.\-]+)\(', txt)
    targets += re.findall(r'call_target_name\s*=\s*"([^"]+)"', txt)
    for t in targets:
        low = t.lower()
        if any(m in low for m in _HOST_CALL_MARKERS):
            sites.append(f"custom_call @{t}")
    return sites


def assert_no_host_transfer(artifact) -> None:
    """The lowering must contain ZERO host transfers — no infeed/
    outfeed/send/recv, no Python-callback or host-placement custom
    calls.  The decode-step contract (ROADMAP: "decode step pinned to
    zero host transfers"): one stray ``debug.print``, ``io_callback``,
    or host-pinned buffer inserts a device->host sync into a loop that
    runs tens of times per generated token."""
    sites = host_transfer_sites(artifact)
    assert not sites, (
        f"the lowering contains {len(sites)} host-transfer site(s): "
        f"{sites[:5]} — a compiled hot-loop step must run entirely on "
        f"device (drop the callback/debug print, or move the host work "
        f"between steps)")


def assert_no_recompile(fn, calls: Sequence = (), *,
                        label: Optional[str] = None) -> list:
    """The compile-once pin, generalized: drive a JITTED callable
    through a call matrix and assert its executable cache never grows
    past ONE entry.

    ``fn`` is anything carrying jax's ``_cache_size()`` (a
    ``jax.jit`` result); ``calls`` is an iterable of argument tuples —
    each is invoked in order, and the cache size is checked after
    EVERY call, so the failure message names the exact call whose
    occupancy/length/draft-hit/chunk-phase mix leaked into a traced
    shape.  With ``calls=()`` only the final state is asserted (the
    post-hoc spelling: run your scenario first, then pin).  Returns
    the per-call results.

    Born as the decode step's trace-count pin
    (tests/test_inference.py); every compile-once contract — decode,
    speculative verify, chunked prefill — now pins through this one
    helper.
    """
    size = getattr(fn, "_cache_size", None)
    if size is None or not callable(size):
        raise TypeError(
            f"assert_no_recompile needs a jitted callable exposing "
            f"_cache_size(); got {type(fn).__name__} — wrap the "
            f"function in jax.jit (or pass the scheduler's step "
            f"attribute, not its bound method)")
    name = label or getattr(fn, "__name__", repr(fn))
    results = []
    for i, args in enumerate(calls):
        results.append(fn(*args))
        n = size()
        assert n <= 1, (
            f"{name}: call {i} of the matrix grew the jit cache to {n} "
            f"compiled variants — an occupancy/length/draft/chunk "
            f"value leaked into a traced shape (argument shapes/dtypes "
            f"must be identical across the matrix)")
    n = size()
    assert n == 1, (
        f"{name}: expected exactly one compiled variant after the call "
        f"matrix, found {n} — "
        + ("the function was never called" if n == 0 else
           "shape-polymorphic retraces happened before this check"))
    return results


# ---------------------------------------------------------- GSPMD tier
# Checkers for the jit+NamedSharding step path (``make_train_step(
# spmd="auto")``): the LOWERING carries the program's sharding INTENT
# as ``mhlo.sharding`` attributes on the entry arguments, and the
# COMPILED module carries the collectives XLA's SPMD partitioner
# actually placed (the lowering of a GSPMD program has none — they
# only exist after partitioning).

def arg_shardings(artifact) -> List[dict]:
    """Per flattened entry argument of the lowering's ``@main``, in
    order: ``{"type": "8x16xf32", "sharding": str|None}`` — the MLIR
    tensor type and the ``mhlo.sharding`` HloSharding string (None for
    an unannotated argument)."""
    txt = hlo_text(artifact)
    m = re.search(r'func\.func\s+(?:public\s+)?@main\((.*?)\)\s*->', txt,
                  re.S)
    if m is None:
        raise ValueError("no @main function signature in the lowering "
                         "text — not a jax lowering artifact?")
    out = []
    # one %argN per entry: `%arg0: tensor<8x16xf32> {attrs...}`
    for am in re.finditer(
            r'%arg\d+:\s*tensor<([^>]*)>\s*(\{.*?\})?(?:,|$)',
            m.group(1), re.S):
        attrs = am.group(2) or ""
        sm = re.search(r'mhlo\.sharding\s*=\s*"([^"]*)"', attrs)
        out.append({"type": am.group(1),
                    "sharding": sm.group(1) if sm else None})
    return out


def _flat_arg_index(lowered, argpath) -> int:
    """Flattened entry-argument index of ``argpath``: an int passes
    through; a sequence of pytree keys (leading element = positional
    argnum) resolves through the lowering's ``in_tree`` — e.g.
    ``(0, "layers", "wq")`` for ``params["layers"]["wq"]`` of
    ``step.lower(params, ...)``.  The path must land on ONE leaf."""
    if isinstance(argpath, int):
        return argpath
    import jax.tree_util as jtu

    tree = lowered.in_tree
    args, _kwargs = jtu.tree_unflatten(tree, list(range(tree.num_leaves)))
    node = args
    for key in argpath:
        node = node[key]
    leaves = jtu.tree_leaves(node)
    if len(leaves) != 1:
        raise ValueError(
            f"argpath {argpath!r} names a subtree of {len(leaves)} "
            f"leaves — point it at one array (add the remaining keys)")
    return leaves[0]


def _aligned_sites(lowered) -> List[dict]:
    """:func:`arg_shardings` with the leaf alignment VERIFIED: the
    tensor-entry count must equal the lowering's pytree leaf count, or
    the flat-index mapping would silently read a neighboring
    argument's sharding (non-tensor entries — ``!stablehlo.token``
    from ordered effects — are skipped by the parser, which keeps
    alignment on current jax; this check makes any future drift loud
    instead of wrong)."""
    sites = arg_shardings(lowered)
    tree = getattr(lowered, "in_tree", None)
    if tree is not None and len(sites) != tree.num_leaves:
        raise ValueError(
            f"lowering has {len(sites)} tensor entry argument(s) but "
            f"the call's pytree has {tree.num_leaves} leaves — the "
            f"@main signature carries arguments this parser cannot "
            f"align (a token/effect arg lowered as a tensor?); the "
            f"argpath -> argument mapping would be unreliable")
    return sites


def sharding_of(lowered, argpath) -> Optional[str]:
    """The ``mhlo.sharding`` string the lowering records for one entry
    argument (see :func:`_flat_arg_index` for ``argpath``), or None
    when the argument carries no annotation."""
    sites = _aligned_sites(lowered)
    i = _flat_arg_index(lowered, argpath)
    if not 0 <= i < len(sites):
        raise IndexError(f"flat arg index {i} out of range "
                         f"({len(sites)} entry arguments)")
    return sites[i]["sharding"]


#: MLIR element type -> jnp dtype name, for re-lowering an argument's
#: aval when computing the EXPECTED sharding attribute
_MLIR_DTYPES = {
    "f64": "float64", "f32": "float32", "f16": "float16",
    "bf16": "bfloat16", "i64": "int64", "i32": "int32", "i16": "int16",
    "i8": "int8", "ui8": "uint8", "ui32": "uint32", "i1": "bool",
    "f8E4M3FN": "float8_e4m3fn", "f8E5M2": "float8_e5m2",
}


def _aval_of_type(mlir_type: str):
    """shape/dtype ShapeDtypeStruct of one ``8x16xf32`` MLIR tensor
    type."""
    parts = mlir_type.split("x")
    dims, dt = parts[:-1], parts[-1]
    if dt not in _MLIR_DTYPES:
        raise ValueError(f"unrecognized MLIR element type {dt!r} in "
                         f"tensor<{mlir_type}> — extend _MLIR_DTYPES")
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(int(d) for d in dims),
                                getattr(jnp, _MLIR_DTYPES[dt]))


def assert_sharding(lowered, argpath, mesh, spec) -> None:
    """The annotation pin: the lowering's ``mhlo.sharding`` for
    ``argpath`` must equal what ``NamedSharding(mesh, spec)`` lowers to
    on that argument's shape — computed by lowering a one-argument
    identity with that in_sharding and reading ITS attribute, so the
    expectation self-calibrates to the running jax's HloSharding
    spelling instead of hard-coding it."""
    from jax.sharding import NamedSharding

    sites = _aligned_sites(lowered)
    i = _flat_arg_index(lowered, argpath)
    got = sites[i]["sharding"]
    s = NamedSharding(mesh, spec)
    aval = _aval_of_type(sites[i]["type"])
    ref = jax.jit(lambda x: x, in_shardings=s).lower(
        jax.ShapeDtypeStruct(aval.shape, aval.dtype, sharding=s))
    want = arg_shardings(ref)[0]["sharding"]
    assert got == want, (
        f"entry arg {argpath!r} (flat #{i}, tensor<{sites[i]['type']}>) "
        f"lowered with sharding {got!r} but NamedSharding(mesh, "
        f"{spec}) lowers to {want!r} — the step's annotation drifted "
        f"from the intended layout")


def _compiled_text(artifact) -> str:
    """The post-SPMD-partitioning HLO text: a str passes through, a
    ``Lowered`` is compiled (collectives only exist after
    partitioning), anything else with ``as_text()`` is rendered."""
    if isinstance(artifact, str):
        return artifact
    if hasattr(artifact, "compile"):
        return artifact.compile().as_text()
    return hlo_text(artifact)


def _parse_hlo_groups(attr: str) -> Optional[List[List[int]]]:
    """Compiled-HLO ``replica_groups`` in either spelling: the literal
    ``{{0,1},{2,3}}`` or the iota ``[4,2]<=[8]`` /
    ``[2,4]<=[4,2]T(1,0)`` form."""
    attr = attr.strip()
    if attr.startswith("{"):
        groups = re.findall(r'\{([\d,\s]*)\}', attr)
        try:
            return [[int(x) for x in g.split(",") if x.strip()]
                    for g in groups]
        except ValueError:
            return None
    m = re.match(r'\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?', attr)
    if m is None:
        return None
    import numpy as np

    n_groups, group_size = int(m.group(1)), int(m.group(2))
    dims = [int(d) for d in m.group(3).split(",")]
    ids = np.arange(int(np.prod(dims))).reshape(dims)
    if m.group(4):
        ids = ids.transpose([int(p) for p in m.group(4).split(",")])
    return ids.reshape(n_groups, group_size).tolist()


def spmd_collective_sites(artifact, kind: str) -> List[dict]:
    """Every ``kind`` collective the SPMD partitioner placed in the
    compiled module, in program order, as ``{"dtype": str|None,
    "replica_groups": [[int, ...], ...]|None}``.  ``kind`` uses the
    underscore spelling (``all_reduce``); compiled HLO prints dashes
    and may split async pairs — only the ``-start``/plain op counts,
    never the ``-done``."""
    txt = _compiled_text(artifact)
    dashed = kind.replace("_", "-")
    sites = []
    for m in re.finditer(
            r'=\s*\(?([a-zA-Z0-9]+)\[[^\]]*\][^=\n]*?\s'
            + re.escape(dashed) + r'(?:-start)?\(', txt):
        line_end = txt.find("\n", m.end())
        window = txt[m.end(): line_end if line_end != -1 else len(txt)]
        gm = re.search(r'replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|'
                       r'\[[^\]]+\]<=\[[^\]]+\](?:T\([\d,]+\))?)', window)
        sites.append({
            "dtype": m.group(1),
            "replica_groups": _parse_hlo_groups(gm.group(1)) if gm else None,
        })
    return sites


def assert_spmd_collectives(artifact, kind: str, axes=None, mesh=None, *,
                            minimum: Optional[int] = None,
                            maximum: Optional[int] = None,
                            dtype: Optional[str] = None) -> int:
    """The GSPMD program's collective-structure pin: count the ``kind``
    collectives XLA's partitioner placed (in the COMPILED module — a
    jit+NamedSharding lowering contains none), optionally filtered to
    the ones whose ``replica_groups`` equal a collective over exactly
    ``axes`` of ``mesh`` (the per-axis filtering of
    :func:`assert_collective_axes`, on the compiled-HLO spellings),
    with ``minimum``/``maximum`` bounds and an optional all-sites
    ``dtype`` pin.  Returns the matched count."""
    sites = spmd_collective_sites(artifact, kind)
    label = kind
    if axes is not None:
        if mesh is None:
            raise ValueError("axes= filtering needs mesh= (the groups "
                             "are computed from the mesh layout)")
        want = _groups_key(mesh_axis_groups(mesh, axes))
        sites = [s for s in sites
                 if _groups_key(s["replica_groups"]) == want]
        label = (f"{kind} over axes "
                 f"{tuple(axes) if not isinstance(axes, str) else (axes,)}")
    n = len(sites)
    if minimum is not None:
        assert n >= minimum, (
            f"expected >= {minimum} partitioner-placed {label} "
            f"collective(s) in the compiled module, found {n} — the "
            f"sharding annotations no longer induce the sync they "
            f"were written for (silent replication?)")
    if maximum is not None:
        assert n <= maximum, (
            f"expected <= {maximum} partitioner-placed {label} "
            f"collective(s) in the compiled module, found {n} — the "
            f"annotations induce extra data movement (a resharding "
            f"crept into the step)")
    if dtype is not None:
        bad = [s["dtype"] for s in sites if s["dtype"] != dtype]
        assert not bad, (
            f"every matched {label} must run in {dtype}, found {bad}")
    return n


def donated_buffer_count(artifact) -> int:
    """Buffers the LOWERING declares donatable: ``jax.buffer_donor``
    (shard_map inputs) plus ``tf.aliasing_output`` (plain-jit donated
    args pre-aliased to outputs)."""
    txt = hlo_text(artifact)
    return txt.count("jax.buffer_donor") + txt.count("tf.aliasing_output")


def _expected_leaves(donated_trees: Sequence, extra: int) -> int:
    return extra + sum(
        len(jax.tree_util.tree_leaves(t)) for t in donated_trees)


def assert_donation_covers(lowered, *donated_trees, extra: int = 0,
                           compiled: bool = True) -> None:
    """Every leaf of ``donated_trees`` (plus ``extra`` buffers) must be
    donated through the step: the lowering declares at least that many
    donatable buffers, and — with ``compiled=True`` — the compiled
    module's ``input_output_alias`` table actually aliases them to
    outputs.  Donation that LOWERS but does not ALIAS is the silent
    failure mode (XLA drops donations it cannot use, keeping the ~3x
    param-bytes peak the donation was written to avoid), so prefer the
    compiled check whenever the test budget allows; ``compiled=False``
    skips the XLA compile and pins only the declaration."""
    n = _expected_leaves(donated_trees, extra)
    assert n > 0, "no donated leaves to check — pass the donated trees"
    declared = donated_buffer_count(lowered)
    assert declared >= n, (
        f"{declared} buffer(s) declared donatable in the lowering but "
        f"the donated trees hold {n} leaves — donate_argnums is not "
        f"covering the state (dropped arg? tuple index drift?)")
    if not compiled:
        return
    hdr = lowered.compile().as_text().splitlines()[0]
    assert "input_output_alias=" in hdr, (
        f"compiled module has no input_output_alias table at all — "
        f"every donation was dropped: {hdr}")
    aliased = hdr.count("may-alias") + hdr.count("must-alias")
    assert aliased >= n, (
        f"only {aliased} aliased buffer(s) in input_output_alias for "
        f"{n} donated leaves — XLA dropped donations (dtype/layout "
        f"mismatch between the donated input and every output?)")
