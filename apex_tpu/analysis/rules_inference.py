"""Serving-path mutation hygiene rules.

- APX110: an in-place scatter (``.at[...].set/.add/...``) into a
  kv/pool-named buffer whose page index is not provably routed through
  the allocator/clamp seam — the COW-bypass hazard class.

The paged-KV pool has exactly one safe mutation discipline
(``inference/kv_cache.py``): every destination page index is either
(a) a device value routed through ``jnp.clip`` into the pool and/or a
``jnp.where`` that re-routes masked rows to the reserved garbage page
(the APX107 read-side contract, applied to writes), or (b) a HOST int
handed out by :class:`~apex_tpu.inference.kv_cache.PageAllocator` —
recognizable by the ``int(...)`` normalization at the seam
(``copy_page``).  A scatter that bypasses both is the class of bug
prefix sharing makes catastrophic: with refcounted pages, writing
through an unrouted index does not just corrupt ONE sequence's cache —
it mutates a page other sequences (and the prefix trie) still read,
silently changing *their* logits.  Copy-on-write only protects writes
that go through the scheduler's COW pass; a raw ``pool.at[idx].set``
is invisible to it.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, last_name,
)

#: identifier substrings that mark a KV page-pool buffer (the decode
#: path's shared mutable state) — the APX110 scope guard
_POOL_NAMES = ("pool", "kv_cache", "kvcache")

#: calls whose results count as "routed through the seam": device-side
#: clamp/re-route (clip/where — the garbage-page discipline) and the
#: host-int normalization the allocator seam applies (int)
_SEAM_CALLS = ("clip", "where", "int")

#: ``.at[...]`` verbs that WRITE (jnp's functional scatter family) —
#: ``.get`` is a read and stays out of reach
_MUTATION_VERBS = frozenset(
    {"set", "add", "subtract", "multiply", "divide", "power", "min",
     "max", "apply"})


def _mentions_pool(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        name = None
        if isinstance(sub, ast.Name):
            name = sub.id
        elif isinstance(sub, ast.Attribute):
            name = sub.attr
        if name is not None \
                and any(m in name.lower() for m in _POOL_NAMES):
            return True
    return False


def _contains_seam_call(node: ast.AST, routed: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and last_name(sub.func) in _SEAM_CALLS:
            return True
        if isinstance(sub, ast.Name) and sub.id in routed:
            return True
    return False


def _routed_names(fn: ast.AST) -> Set[str]:
    """Names assigned (directly, or through arithmetic on an already-
    routed name) from a clip/where/int call anywhere in the function —
    the write-side twin of ``rules_precision._clipped_names``."""
    routed: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            pairs = []
            if isinstance(tgt, ast.Name):
                pairs = [(tgt, node.value)]
            elif isinstance(tgt, ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(tgt.elts) == len(node.value.elts):
                # src, dst = int(src), int(dst) — element-wise
                pairs = list(zip(tgt.elts, node.value.elts))
            for t, v in pairs:
                if isinstance(t, ast.Name) and t.id not in routed \
                        and _contains_seam_call(v, routed):
                    routed.add(t.id)
                    changed = True
    return routed


class KvPoolScatterBypassesSeam(Rule):
    """APX110: ``pool.at[idx].set(...)`` where ``idx`` is neither
    clamped/garbage-routed device data nor an allocator-normalized
    host int."""

    rule_id = "APX110"
    severity = "error"
    fix_hint = (
        "route the page index through the seam: clamp device indices "
        "into the pool and re-route masked rows to the garbage page "
        "(dest = jnp.where(mask, jnp.clip(rows, 0, num_pages - 1), "
        "GARBAGE_PAGE)), or normalize allocator-issued host ids with "
        "int(...) — or better, scatter through the kv_cache seam "
        "helpers (write_decode_kv / write_prompt_kv / copy_page), "
        "which the scheduler's copy-on-write pass knows about")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Subscript):
                continue
            at = node.value
            if not (isinstance(at, ast.Attribute) and at.attr == "at"):
                continue
            if not _mentions_pool(at.value):
                continue
            # the mutation verb lives on the call ENCLOSING the
            # subscript: pool.at[idx].set(x) — bare pool.at[idx] and
            # .at[idx].get(...) (a read) mutate nothing
            attr = ctx.parent(node)
            if not (isinstance(attr, ast.Attribute)
                    and attr.attr in _MUTATION_VERBS
                    and isinstance(ctx.parent(attr), ast.Call)):
                continue
            fn = ctx.enclosing_function(node)
            routed = _routed_names(fn) if fn is not None else set()
            if _contains_seam_call(node.slice, routed):
                continue
            if self._index_is_static(node.slice):
                continue
            yield self.finding(
                ctx, node,
                f"kv/pool buffer scattered through `.at[...].{attr.attr}` "
                f"with a page index not routed through the "
                f"allocator/clamp seam: with refcounted prefix-shared "
                f"pages this write can mutate a page OTHER sequences "
                f"(and the prefix trie) still read — invisible to the "
                f"scheduler's copy-on-write pass, corrupting their "
                f"logits silently")

    @staticmethod
    def _index_is_static(slice_node: ast.AST) -> bool:
        """Literal-only indices (constants, slices of constants) carry
        no corruptible page indirection."""
        for sub in ast.walk(slice_node):
            if isinstance(sub, ast.Name):
                return False
            if isinstance(sub, ast.Call):
                return False
        return True
