"""Donation/aliasing rules (the ``donate_argnums`` class).

``donate_argnums`` tells XLA it may reuse an input buffer's memory for
the outputs.  That is the difference between fitting and halving the
batch at 345M scale (see ``bench.py``'s GPT sections) — and it is also
the one jit option whose misuse is INVISIBLE everywhere but the chip:
on CPU donation is a no-op, so a test that reads a donated buffer after
the step passes locally and reads garbage (or crashes with "array has
been deleted") on TPU.

- APX103: a Python name passed at a donated position is *read again*
  after the call without first being rebound — either from the call's
  own results (the safe ``params, state = step(params, state)`` idiom)
  or by a later assignment.  The usual shapes: logging a param norm
  from the pre-step tree, or rebinding the step's result to a NEW name
  while the stale donated name stays live.

Only statically certain cases are flagged: literal ``donate_argnums``
(a tuple/int of constants), plain-name arguments, no ``*args``
splatting at the call site.  Values threaded through variables are
trusted, same contract as the tiling and collective rules.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, last_name,
)

__all__ = ["DonatedBufferReuse"]


def _literal_argnums(node: ast.AST) -> Optional[Set[int]]:
    """The donated positions if the donate_argnums value is a literal
    int or tuple/list of ints; None when it is computed (trusted)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.add(elt.value)
        return out
    return None


def _donate_kw(call: ast.Call) -> Optional[Set[int]]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _literal_argnums(kw.value)
    return None


def _is_jit(node: ast.AST) -> bool:
    return last_name(node) == "jit"


def _scoped_names(root: ast.AST) -> Iterator[ast.Name]:
    """Name nodes in ``root``'s OWN scope: descent stops at nested
    function/class/lambda definitions.  A same-named parameter or local
    in a nested scope is a different variable, not the donated buffer
    (flagging it was a reproduced false positive), and a true closure
    read's execution time is not statically certain — both sides of the
    only-statically-certain contract say stop at the scope boundary.
    ``root`` itself may be a def (the enclosing function): only nested
    scopes are skipped."""
    stack: List[ast.AST] = [root]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.Name):
                yield child
            stack.append(child)


def _scope_bound_names(scope: ast.AST) -> Set[str]:
    """Names this scope binds: its parameters, Store/Del targets in its
    own body (nested scopes excluded), and the names of defs/classes
    declared directly in it."""
    names: Set[str] = set()
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
        a = scope.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs,
                  *([a.vararg] if a.vararg else []),
                  *([a.kwarg] if a.kwarg else [])):
            names.add(p.arg)
    stack: List[ast.AST] = [scope]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                names.add(child.name)  # the def binds its name HERE,
                continue               # its body is another scope
            if isinstance(child, ast.Lambda):
                continue
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                names.add(child.id)
            stack.append(child)
    return names


def _target_names(stmt: ast.AST) -> Set[str]:
    """Names a statement (re)binds, for the safe-rebind check."""
    names: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign, ast.For)):
        targets = [stmt.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
    return names


class DonatedBufferReuse(Rule):
    """APX103: donated argument read after the donating call."""

    rule_id = "APX103"
    severity = "error"
    fix_hint = ("rebind the donated name from the call's own results "
                "(`params, state, ... = step(params, state, ...)`) or "
                "move the read before the call — after donation XLA may "
                "have reused the buffer for the outputs, so the old name "
                "is garbage on TPU even though CPU tests pass")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        donated = self._donated_bindings(ctx)
        if not donated:
            return
        bound_cache: Dict[int, Set[str]] = {}
        for call in ast.walk(ctx.tree):
            if not isinstance(call, ast.Call):
                continue
            name = last_name(call.func)
            nums = self._resolve(ctx, call, name, donated, bound_cache)
            if nums is None:
                continue
            if any(isinstance(a, ast.Starred) for a in call.args):
                continue  # positions unknowable under *args splat
            yield from self._check_call(ctx, call, nums)

    # ------------------------------------------------------- bindings
    def _donated_bindings(
            self, ctx: ModuleContext) -> Dict[int, Dict[str, Set[int]]]:
        """Names bound to a jit with literal donate_argnums, keyed by
        the id() of the scope (function node or module) that binds them:
        via ``step = jax.jit(f, donate_argnums=...)`` assignment or a
        ``@partial(jax.jit, donate_argnums=...)`` / ``@jax.jit(...)``
        decorator on a def.  Scope-keyed so an unrelated same-named
        function in another scope is never flagged (a reproduced false
        positive of the module-wide name match)."""
        out: Dict[int, Dict[str, Set[int]]] = {}

        def record(binding_node, name, nums):
            scope = ctx.enclosing_function(binding_node) or ctx.tree
            out.setdefault(id(scope), {})[name] = nums

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and _is_jit(node.value.func):
                nums = _donate_kw(node.value)
                if nums:
                    record(node, node.targets[0].id, nums)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    # @jax.jit(donate_argnums=...) or
                    # @partial(jax.jit, donate_argnums=...)
                    if _is_jit(dec.func) or (
                            last_name(dec.func) == "partial" and dec.args
                            and _is_jit(dec.args[0])):
                        nums = _donate_kw(dec)
                        if nums:
                            record(node, node.name, nums)
        return out

    def _resolve(self, ctx: ModuleContext, call: ast.Call, name: str,
                 donated: Dict[int, Dict[str, Set[int]]],
                 bound_cache: Dict[int, Set[str]]) -> Optional[Set[int]]:
        """Python name resolution over the call's scope chain, innermost
        first: the nearest scope that binds ``name`` decides — donated
        positions if its binding is the donating one, None if ``name``
        is shadowed there by a parameter/local/def of the same name."""
        scope: Optional[ast.AST] = ctx.enclosing_function(call)
        while True:
            node = scope if scope is not None else ctx.tree
            nums = donated.get(id(node), {}).get(name)
            if nums is not None:
                return nums
            key = id(node)
            if key not in bound_cache:
                bound_cache[key] = _scope_bound_names(node)
            if name in bound_cache[key]:
                return None  # shadowed: a different, non-donating binding
            if scope is None:
                return None
            scope = ctx.enclosing_function(scope)

    # ------------------------------------------------------- call sites
    def _check_call(self, ctx: ModuleContext, call: ast.Call,
                    positions: Set[int]) -> Iterator[Finding]:
        stmt = self._enclosing_stmt(ctx, call)
        if stmt is None:
            return
        if isinstance(stmt, (ast.Return, ast.Raise)):
            # the donating call's value leaves the function immediately:
            # no later line of this scope can run after it in the same
            # invocation, so a read in a sibling branch (the early-return
            # shape) is provably NOT a read of the donated buffer
            return
        scope = ctx.enclosing_function(call)
        body_root = scope if scope is not None else ctx.tree
        rebound_here = _target_names(stmt)
        stmt_end = getattr(stmt, "end_lineno", stmt.lineno)
        for pos in sorted(positions):
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if not isinstance(arg, ast.Name):
                continue  # only plain names are tracked
            if arg.id in rebound_here:
                continue  # the safe rebinding idiom
            # a read on the other arm of the same If can never execute
            # after the call in one invocation (and no enclosing loop
            # carries the donated buffer across): skip those, flag the
            # first reachable read
            bad = next(
                (b for b in self._reads_before_rebind(
                    body_root, arg.id, stmt_end)
                 if not self._exclusive_branches(ctx, call, b, body_root)),
                None)
            if bad is not None:
                yield self.finding(
                    ctx, bad,
                    f"`{arg.id}` is donated (donate_argnums position "
                    f"{pos}) at line {call.lineno} and read again here "
                    f"without being rebound — XLA may have reused its "
                    f"buffer for the outputs; works on CPU (donation is "
                    f"a no-op there), garbage or a deleted-array error "
                    f"on TPU")
                continue
            # loop-carried: a read textually BEFORE the call inside the
            # same loop runs AFTER it on the next iteration
            carried = self._loop_carried_read(ctx, call, arg.id, body_root)
            if carried is not None:
                yield self.finding(
                    ctx, carried,
                    f"`{arg.id}` is donated (donate_argnums position "
                    f"{pos}) at line {call.lineno} inside this loop and "
                    f"never rebound: on the NEXT iteration this read "
                    f"sees the donated buffer — works on CPU (donation "
                    f"is a no-op there), garbage or a deleted-array "
                    f"error on TPU")

    def _loop_carried_read(self, ctx: ModuleContext, call: ast.Call,
                           name: str, body_root: ast.AST
                           ) -> Optional[ast.AST]:
        """First Load of ``name`` in the call's nearest enclosing loop
        that executes after the donation via the NEXT iteration — i.e.
        any read in the loop body outside the donating call expression,
        with the name never stored in the loop (a store anywhere makes
        the next iteration's value uncertain: stay silent)."""
        loop: Optional[ast.AST] = None
        cur = ctx.parent(call)
        while cur is not None and cur is not body_root:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                loop = cur
                break
            cur = ctx.parent(cur)
        if loop is None:
            return None
        own = set(map(id, ast.walk(call)))
        first: Optional[ast.AST] = None
        for sub in _scoped_names(loop):
            if sub.id != name:
                continue
            if isinstance(sub.ctx, ast.Store):
                return None
            if isinstance(sub.ctx, ast.Load) and id(sub) not in own \
                    and (first is None or sub.lineno < first.lineno):
                first = sub
        return first

    def _exclusive_branches(self, ctx: ModuleContext, call: ast.AST,
                            read: ast.AST, body_root: ast.AST) -> bool:
        """True when ``call`` and ``read`` sit on different arms of the
        same ``if`` and no loop up to ``body_root`` can re-execute it —
        then the read provably never follows the donating call in one
        invocation (the assign-in-branch sibling of the early-return
        shape).  Inside a loop the flag stands: iteration 1 may donate
        and iteration 2 read the stale name."""
        anc_call = self._ancestors(ctx, call)
        anc_read = self._ancestors(ctx, read)
        for node in anc_call:
            if not isinstance(node, ast.If) or node not in anc_read:
                continue
            in_body_call = self._descends(node.body, anc_call)
            in_body_read = self._descends(node.body, anc_read)
            if in_body_call == in_body_read:
                continue  # same arm (or both under elif chains): not
                # exclusive at THIS If, but a deeper shared If may be
            cur = ctx.parent(node)
            while cur is not None and cur is not body_root:
                if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                    return False  # loop may carry the buffer across arms
                cur = ctx.parent(cur)
            return True
        return False

    @staticmethod
    def _ancestors(ctx: ModuleContext, node: ast.AST) -> List[ast.AST]:
        out: List[ast.AST] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            out.append(cur)
            cur = ctx.parent(cur)
        return out

    @staticmethod
    def _descends(arm: List[ast.stmt], ancestors: List[ast.AST]) -> bool:
        """Does the ancestor chain pass through one of this If arm's
        statements?"""
        chain = set(map(id, ancestors))
        return any(id(s) in chain for s in arm)

    def _enclosing_stmt(self, ctx: ModuleContext,
                        node: ast.AST) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None:
            parent = ctx.parent(cur)
            if isinstance(cur, ast.stmt) and isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Module, ast.For, ast.While, ast.If,
                             ast.With, ast.Try)):
                return cur
            cur = parent
        return None

    def _reads_before_rebind(self, body_root: ast.AST, name: str,
                             after_line: int) -> List[ast.AST]:
        """Loads of ``name`` after ``after_line`` that no Store precedes
        (straight-line approximation), in line order."""
        loads: List[ast.AST] = []
        first_store_line: Optional[int] = None
        for sub in _scoped_names(body_root):
            if sub.id != name or sub.lineno <= after_line:
                continue
            if isinstance(sub.ctx, ast.Store):
                if first_store_line is None or sub.lineno < first_store_line:
                    first_store_line = sub.lineno
            elif isinstance(sub.ctx, ast.Load):
                loads.append(sub)
        return sorted(
            (l for l in loads if first_store_line is None
             or l.lineno < first_store_line),
            key=lambda l: l.lineno)
