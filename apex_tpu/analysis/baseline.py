"""Baseline (suppression) file handling.

The analyzer's job is to keep NEW instances of each hazard class out of
the tree; a few existing findings are deliberate (a trace-time env read
that *is* the documented impl-selection mechanism, a gather whose ids
are in-range by construction).  Those live in a committed JSON baseline
where every entry MUST carry a justification — an unexplained
suppression is itself an error, because six months from now nobody can
tell a reviewed exception from a rubber stamp.

Entries match on rule id + path suffix + enclosing symbol + a substring
of the finding message (never on line numbers, which drift with every
edit above them).  Stale entries — suppressing nothing — are reported
so the baseline shrinks as code gets fixed.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Sequence, Tuple

from apex_tpu.analysis.core import Finding


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str           # suffix-matched, '/'-separated
    symbol: str         # enclosing qualname ("*" matches any)
    contains: str       # substring of the finding message ("" matches any)
    justification: str

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule
                and f.path.replace("\\", "/").endswith(self.path)
                and (self.symbol == "*" or f.symbol == self.symbol)
                and self.contains in f.message)


class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> List[BaselineEntry]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError as e:
        raise BaselineError(f"cannot read baseline {path}: {e}")
    except ValueError as e:
        raise BaselineError(f"baseline {path} is not valid JSON: {e}")
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(data.get("entries", [])):
        missing = {"rule", "path", "justification"} - set(raw)
        if missing:
            raise BaselineError(
                f"baseline entry #{i} missing {sorted(missing)}")
        if not str(raw["justification"]).strip():
            raise BaselineError(
                f"baseline entry #{i} ({raw['rule']} {raw['path']}): "
                f"empty justification — every suppression must explain "
                f"WHY the finding is acceptable")
        entries.append(BaselineEntry(
            rule=raw["rule"], path=raw["path"],
            symbol=raw.get("symbol", "*"),
            contains=raw.get("contains", ""),
            justification=raw["justification"]))
    return entries


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[BaselineEntry],
) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """(kept, suppressed, stale-entries)."""
    used: Dict[int, int] = {}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if e.matches(f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = used.get(hit, 0) + 1
            suppressed.append(f)
    stale = [e for i, e in enumerate(entries) if i not in used]
    return kept, suppressed, stale
