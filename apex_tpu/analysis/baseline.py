"""Baseline (suppression) file handling.

The analyzer's job is to keep NEW instances of each hazard class out of
the tree; a few existing findings are deliberate (a trace-time env read
that *is* the documented impl-selection mechanism, a gather whose ids
are in-range by construction).  Those live in a committed JSON baseline
where every entry MUST carry a justification — an unexplained
suppression is itself an error, because six months from now nobody can
tell a reviewed exception from a rubber stamp.

Entries match on rule id + path suffix + enclosing symbol + a substring
of the finding message (never on line numbers, which drift with every
edit above them).  Stale entries — suppressing nothing — are reported
so the baseline shrinks as code gets fixed.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Sequence, Tuple

from apex_tpu.analysis.core import Finding


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str           # suffix-matched, '/'-separated
    symbol: str         # enclosing qualname ("*" matches any)
    contains: str       # substring of the finding message ("" matches any)
    justification: str

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule
                and f.path.replace("\\", "/").endswith(self.path)
                and (self.symbol == "*" or f.symbol == self.symbol)
                and self.contains in f.message)


class BaselineError(ValueError):
    pass


def load_baseline(path: str,
                  allow_todo: bool = False) -> List[BaselineEntry]:
    """``allow_todo`` is for the --update-baseline path ONLY: the
    placeholder entries it is about to regenerate must not block the
    regeneration itself.  Every normal load rejects them."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError as e:
        raise BaselineError(f"cannot read baseline {path}: {e}")
    except ValueError as e:
        raise BaselineError(f"baseline {path} is not valid JSON: {e}")
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(data.get("entries", [])):
        missing = {"rule", "path", "justification"} - set(raw)
        if missing:
            raise BaselineError(
                f"baseline entry #{i} missing {sorted(missing)}")
        just = str(raw["justification"]).strip()
        if not just:
            raise BaselineError(
                f"baseline entry #{i} ({raw['rule']} {raw['path']}): "
                f"empty justification — every suppression must explain "
                f"WHY the finding is acceptable")
        if not allow_todo and (just.upper() == "TODO"
                               or just.upper().startswith("TODO:")):
            raise BaselineError(
                f"baseline entry #{i} ({raw['rule']} {raw['path']}): "
                f"justification is the '{just}' placeholder "
                f"--update-baseline writes — replace it with the actual "
                f"reason this finding is acceptable AS IS (a TODO "
                f"suppression is a rubber stamp)")
        entries.append(BaselineEntry(
            rule=raw["rule"], path=raw["path"],
            symbol=raw.get("symbol", "*"),
            contains=raw.get("contains", ""),
            justification=raw["justification"]))
    return entries


#: How much of a finding's message --update-baseline pins in the
#: ``contains`` matcher: enough to distinguish same-symbol findings,
#: short enough to survive wording tweaks elsewhere in the message.
_CONTAINS_CHARS = 60


def write_baseline(path: str, findings: Sequence[Finding],
                   entries: Sequence[BaselineEntry]) -> Tuple[int, int, int]:
    """Regenerate the baseline for the CURRENT findings: entries that
    still suppress something are kept verbatim (their justifications
    are reviewed text — never regenerate those), stale entries are
    dropped, and every unsuppressed finding gains a new entry whose
    justification is the literal placeholder ``"TODO"`` — which
    :func:`load_baseline` REJECTS, so the refreshed file fails loudly
    until a human replaces each placeholder with a real reason.
    Returns (kept, dropped, added)."""
    kept_f, suppressed, stale = apply_baseline(findings, entries)
    survivors = [e for e in entries if e not in stale]
    added = [
        BaselineEntry(
            rule=f.rule, path=f.path.replace("\\", "/"), symbol=f.symbol,
            contains=f.message[:_CONTAINS_CHARS], justification="TODO")
        for f in kept_f
    ]
    payload = {
        "_comment": (
            "Suppressions for `python -m apex_tpu.analysis` (see "
            "docs/static_analysis.md). Every entry MUST carry a "
            "justification explaining why the finding is acceptable AS "
            "IS — the loader rejects entries without one, and rejects "
            "the 'TODO' placeholder --update-baseline writes. Match is "
            "rule + path suffix + enclosing symbol + message substring "
            "(never line numbers). Remove entries when the code they "
            "cover is fixed; the CLI reports stale entries."),
        "entries": [dataclasses.asdict(e) for e in survivors + added],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return len(survivors), len(stale), len(added)


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[BaselineEntry],
) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """(kept, suppressed, stale-entries)."""
    used: Dict[int, int] = {}
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        hit = None
        for i, e in enumerate(entries):
            if e.matches(f):
                hit = i
                break
        if hit is None:
            kept.append(f)
        else:
            used[hit] = used.get(hit, 0) + 1
            suppressed.append(f)
    stale = [e for i, e in enumerate(entries) if i not in used]
    return kept, suppressed, stale
