"""Trace-time host-state rules (the ``bench.py:876`` class).

JAX traces a Python function ONCE per (shape, dtype, static-arg)
signature; everything the Python body reads from the host — env vars,
clocks, RNGs, mutated globals — is frozen into the jaxpr at that
moment.  The two failure shapes:

- APX101: a traced function *reads* host state.  The first trace wins
  forever; flipping the env var later does nothing (or worse, does
  something only for shapes not yet traced — a silent A/B corruption).
- APX102: code *mutates* ``os.environ`` mid-process to steer behavior.
  Even outside a traced function this desyncs with every jit cache
  entry built before the flip; the fix is threading an explicit
  argument (see ``GPTConfig.fused_ce_impl``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, dotted_name, last_name,
)

# host-state call patterns: dotted suffix -> what it captures
_HAZARD_CALLS = {
    "os.getenv": "environment variable",
    "os.environ.get": "environment variable",
    "time.time": "wall clock",
    "time.monotonic": "wall clock",
    "time.perf_counter": "wall clock",
    "time.time_ns": "wall clock",
    "datetime.now": "wall clock",
    "datetime.utcnow": "wall clock",
}

_RANDOM_MODULES = ("random.", "np.random.", "numpy.random.")
_ENV_MUTATORS = {"pop", "update", "setdefault", "clear"}


def _dotted(node: ast.AST) -> str:
    return dotted_name(node) or ""


def _is_os_environ(node: ast.AST) -> bool:
    return _dotted(node) in ("os.environ", "environ")


class TraceTimeHostStateRead(Rule):
    """APX101: host state read inside a trace-time function."""

    rule_id = "APX101"
    severity = "error"
    fix_hint = ("hoist the read out of the traced function and thread the "
                "value in as an argument (or a config field); for "
                "randomness use jax.random with an explicit key")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            hazard = self._hazard(node)
            if hazard is None:
                continue
            reason = ctx.traced_reason(node)
            if reason is None:
                continue
            yield self.finding(
                ctx, node,
                f"{hazard} read at trace time inside "
                f"`{ctx.enclosing_qualname(node)}` ({reason}); the value "
                f"is frozen into the first trace and silently stale for "
                f"every later call")

    def _hazard(self, node: ast.AST) -> Optional[str]:
        # os.environ["X"] / os.environ used as a value
        if isinstance(node, ast.Subscript) and _is_os_environ(node.value) \
                and isinstance(node.ctx, ast.Load):
            return "os.environ"
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            for suffix, what in _HAZARD_CALLS.items():
                if d == suffix or d.endswith("." + suffix):
                    return f"{what} ({d})"
            # bare-import spellings: `from os import environ, getenv`
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and _is_os_environ(node.func.value):
                return f"environment variable ({d})"
            if d == "getenv":
                return "environment variable (getenv)"
            if any(d.startswith(m) for m in _RANDOM_MODULES):
                return f"host RNG ({d})"
        return None


class ProcessGlobalEnvMutation(Rule):
    """APX102: os.environ mutated inside a function body.

    Module-level assignments (startup config before any tracing) are
    deliberately exempt — the hazard is mutation *mid-process*, after
    jit caches already captured the old value.
    """

    rule_id = "APX102"
    severity = "error"
    fix_hint = ("thread the override as an explicit function/config "
                "argument (e.g. GPTConfig.fused_ce_impl) instead of "
                "flipping process-global state already-traced functions "
                "captured")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            desc = self._mutation(node)
            if desc is None:
                continue
            if ctx.enclosing_function(node) is None:
                continue  # module-level startup config is fine
            yield self.finding(
                ctx, node,
                f"{desc} inside `{ctx.enclosing_qualname(node)}`: "
                f"functions traced before this line keep the OLD value "
                f"(trace-time capture), so the flip silently applies to "
                f"some call paths and not others")

    def _mutation(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                    return "os.environ[...] assignment"
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and _is_os_environ(t.value):
                    return "del os.environ[...]"
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _ENV_MUTATORS \
                    and _is_os_environ(f.value):
                return f"os.environ.{f.attr}(...)"
            d = _dotted(node.func)
            if d.endswith("os.putenv") or d == "putenv" \
                    or d.endswith("os.unsetenv") or d == "unsetenv":
                return f"{d}(...)"
        return None
