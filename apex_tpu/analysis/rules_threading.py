"""Host-concurrency rules (the GoodputAccountant/flightrec class,
scaled into an analyzer tier).

The system is genuinely multi-threaded on the host: the StepWatchdog
heartbeat thread, the PreemptionHandler signal path, the
AsyncCheckpointer worker, the supervisor, the fleet frontend, and the
metrics registry all mutate shared state concurrently — and the repo
has burned review passes hand-finding the races (the goodput persist
race PR 10 fixed, the flightrec dump-vs-checkpoint race PR 14 fixed,
histogram re-registration clashes).  These rules turn that review tax
into a CI gate, driven by :class:`~apex_tpu.analysis.dataflow.
ThreadIndex` (which functions can run off the main thread) and a
static lock-region model.

- **APX114**: a shared ``self.`` attribute is MUTATED from a
  thread-reachable method with no enclosing lock region, while at
  least one OTHER access site of the same attribute IS locked — the
  exact GoodputAccountant shape: the class declares lock discipline
  for this state (somebody takes the lock) and one thread-side writer
  skips it.  The asymmetry requirement is the false-positive killer:
  a class with no lock at all, or uniformly unlocked access, stays
  quiet (that is a design choice, not a missed site).
- **APX115**: lock-order inversion — the static lock-acquisition
  graph (lock B acquired while A is held; elsewhere A while B) has a
  cycle.  Both sites are named; with the watchdog or a signal handler
  on one side this is the classic ABBA deadlock that presents as a
  wedged pod, not a stack trace.
- **APX116**: a blocking call (a no-timeout ``.join()``/``.get()``/
  ``.wait()``, ``block_until_ready``, ``wait_until_finished``,
  checkpoint I/O, a host collective) executes while HOLDING a lock
  that a signal-handler- or watchdog-callback-reachable function also
  acquires — the drain-deadlock shape PR 8's re-entrancy guard fixed
  by hand: the async path fires mid-block, queues behind the held
  lock, and the process hangs in its own cleanup.

Lock-region model (shared by all three): a lock is an attribute or
module-level name assigned a ``threading.Lock``/``RLock``/
``Condition``/``Semaphore`` (or an ``apex_tpu.resilience.locks``
monitored lock), identified by ``Class.attr`` / module name — identity
is BY NAME, not by object (two instances of one class share an id;
documented limit).  A site is "locked" when lexically inside ``with
self._lock:`` (RLock-aware: nested re-entry of the same id adds
nothing) or between an ``.acquire()``/``.release()`` pair on the same
id in the same function.  Acquittal seam: a call to
:func:`~apex_tpu.resilience.locks.assert_lock_held` in the enclosing
function pins the site to the runtime lock contract ("my caller holds
it") and acquits APX114/APX116 — mirroring ``assert_uniform`` for the
divergence tier.

Known limits (documented, deliberate): the lock-acquisition graph and
the shared-attribute model are module-local (cross-module thread
REACHABILITY is linked, cross-module lock graphs are not); lock
identity is by name; ``acquire``/``release`` pairing is line-ranged
within one function (a release on another path is not modeled); and
attribute mutation through a local alias (``d = self._acc; d[k] = v``)
is out of reach.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import (
    ModuleContext, Rule, Finding, dotted_name, last_name,
)

#: Constructors that mint a lock object (matched by last dotted
#: component: ``threading.Lock``, ``Lock``, and the runtime seam's
#: ``monitored_lock`` all hit).
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "monitored_lock"}

#: The acquittal marker (apex_tpu.resilience.locks.assert_lock_held):
#: seeing one in the enclosing function acquits APX114/APX116 at that
#: site — the code is saying "my caller holds the lock by contract,
#: and here is where that contract is checked at runtime".
_LOCK_SEAMS = {"assert_lock_held"}

#: Mutating method names: calling one of these ON a shared attribute
#: counts as a write to it (``self._ring.append``, ``self._acc.update``).
_MUTATOR_METHODS = {"append", "appendleft", "extend", "insert", "add",
                    "update", "setdefault", "pop", "popleft", "popitem",
                    "remove", "discard", "clear", "sort", "reverse"}

#: Entry kinds whose acquirers make a held lock "contended by an async
#: interrupt" for APX116 (a signal handler may run between any two
#: bytecodes; an on_* callback runs on the watchdog/monitor thread).
_ASYNC_KINDS = ("signal", "callback")


def _acquitted(ctx: ModuleContext, node: ast.AST) -> bool:
    scope = ctx.enclosing_function(node) or ctx.tree
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Call) \
                and last_name(sub.func) in _LOCK_SEAMS:
            return True
    return False


def _enclosing_class(ctx: ModuleContext, node: ast.AST) -> Optional[str]:
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = ctx.parent(cur)
    return None


def _declared_locks(ctx: ModuleContext) -> Set[str]:
    """Canonical ids of every lock the module declares: ``self.X =
    threading.Lock()`` in class C → ``C.X``; ``NAME = Lock()`` at
    module level → ``NAME``.  Cached on the ctx (every rule asks)."""
    cached = getattr(ctx, "_declared_locks", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        if not (isinstance(node.value, ast.Call)
                and last_name(node.value.func) in _LOCK_CTORS):
            continue
        tgt = node.targets[0]
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self":
            cls = _enclosing_class(ctx, node)
            out.add(f"{cls}.{tgt.attr}" if cls else tgt.attr)
        elif isinstance(tgt, ast.Name) \
                and ctx.enclosing_function(node) is None:
            out.add(tgt.id)
    ctx._declared_locks = out
    return out


def _lock_id(ctx: ModuleContext, expr: ast.AST,
             declared: Set[str]) -> Optional[str]:
    """Canonical lock id of an expression at a use site (``with
    self._lock:``, ``self._lock.acquire()`` receiver), or None when it
    is not a recognizable lock: a declared id, or — fallback for locks
    constructed out of static reach — a name containing ``lock``/
    ``mutex``."""
    d = dotted_name(expr)
    if d is None:
        return None
    if d.startswith("self."):
        attr = d[len("self."):]
        if "." in attr:
            return None  # self.a.b: nested attribute, out of reach
        cls = _enclosing_class(ctx, expr)
        lid = f"{cls}.{attr}" if cls else attr
    else:
        lid = d
    leaf = lid.split(".")[-1].lower()
    if lid in declared or "lock" in leaf or "mutex" in leaf:
        return lid
    return None


def _acquire_ranges(ctx: ModuleContext, fn: ast.AST,
                    declared: Set[str]) -> Dict[str, Tuple[int, int]]:
    """lock id -> (first ``.acquire()`` line, last ``.release()`` line)
    inside one function — the explicit-pairing half of the lock-region
    model.  An acquire with no matching release yields nothing (the
    region never closes statically; claiming any extent would be a
    guess)."""
    acq: Dict[str, int] = {}
    rel: Dict[str, int] = {}
    for sub in ast.walk(fn):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)):
            continue
        if ctx.enclosing_function(sub) is not fn:
            continue
        meth = sub.func.attr
        if meth not in ("acquire", "release"):
            continue
        lid = _lock_id(ctx, sub.func.value, declared)
        if lid is None:
            continue
        if meth == "acquire":
            acq[lid] = min(acq.get(lid, sub.lineno), sub.lineno)
        else:
            rel[lid] = max(rel.get(lid, sub.lineno), sub.lineno)
    return {lid: (a, rel[lid]) for lid, a in acq.items() if lid in rel}


def _held_locks(ctx: ModuleContext, node: ast.AST,
                declared: Set[str]) -> Dict[str, ast.AST]:
    """lock id -> acquisition site for every lock provably held at
    ``node``: enclosing ``with`` items plus ``acquire``/``release``
    line ranges of the enclosing function."""
    out: Dict[str, ast.AST] = {}
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                lid = _lock_id(ctx, item.context_expr, declared)
                if lid is not None:
                    out.setdefault(lid, cur)
        cur = ctx.parent(cur)
    fn = ctx.enclosing_function(node)
    if fn is not None:
        line = getattr(node, "lineno", 0)
        for lid, (a, r) in _acquire_ranges(ctx, fn, declared).items():
            if a < line < r:
                out.setdefault(lid, fn)
    return out


# ------------------------------------------------------------ site model
class _Site:
    __slots__ = ("node", "qualname", "write", "locked", "thread_reason")

    def __init__(self, node, qualname, write, locked, thread_reason):
        self.node = node
        self.qualname = qualname
        self.write = write
        self.locked = locked            # frozenset of held lock ids
        self.thread_reason = thread_reason


def _attr_sites(ctx: ModuleContext, cls_node: ast.ClassDef,
                declared: Set[str]) -> Dict[str, List[_Site]]:
    """attr name -> access sites over one class body: direct loads,
    stores/augmented stores (including through one subscript hop),
    and mutator-method calls."""
    tidx = dataflow.thread_index(ctx)
    sites: Dict[str, List[_Site]] = {}

    def record(attr_node: ast.Attribute, write: bool) -> None:
        if not (isinstance(attr_node.value, ast.Name)
                and attr_node.value.id == "self"):
            return
        attr = attr_node.attr
        cls = _enclosing_class(ctx, attr_node)
        if cls is None or f"{cls}.{attr}" in declared:
            return  # the lock itself is not shared STATE
        qn = ctx.enclosing_qualname(attr_node)
        held = frozenset(_held_locks(ctx, attr_node, declared))
        sites.setdefault(attr, []).append(_Site(
            attr_node, qn, write, held, tidx.thread_reason(attr_node)))

    for node in ast.walk(cls_node):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Subscript):
                    tgt = tgt.value
                if isinstance(tgt, ast.Attribute):
                    record(tgt, write=True)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Attribute):
            record(node.func.value, write=True)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and not (isinstance(ctx.parent(node), ast.Attribute)
                         or isinstance(ctx.parent(node), ast.Call)
                         and ctx.parent(node).func is node):
            record(node, write=False)
    return sites


class SharedMutationWithoutLock(Rule):
    """APX114: a thread-reachable method mutates a shared attribute
    with no enclosing lock region, while another access site of the
    same attribute IS locked.

    The GoodputAccountant shape: the main-thread mutators take
    ``self._lock``, but ``finalize("wedge")`` — reachable from the
    watchdog's ``on_wedge`` callback, i.e. the monitor thread — writes
    the same accumulators bare.  The interleaving corrupts exactly
    when it matters (mid-wedge, mid-preemption), on the box you are
    not watching.  Both halves of the evidence are required: the
    mutation must be reachable off the main thread (ThreadIndex), and
    some OTHER site must hold a lock for this attribute (proving the
    class considers the state lock-protected — uniformly unlocked
    classes are a design choice, not a finding)."""

    rule_id = "APX114"
    severity = "error"
    fix_hint = ("take the same lock the other access sites hold "
                "(`with self._lock:` around the mutation — RLock if "
                "the locked paths re-enter), or document the contract "
                "with apex_tpu.resilience.locks.assert_lock_held(lock) "
                "if the caller already holds it")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tidx = dataflow.thread_index(ctx)
        if not tidx.reachable and not tidx.lambda_reachable:
            return
        declared = _declared_locks(ctx)
        if not declared:
            return
        for cls_node in ast.walk(ctx.tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            for attr, sites in sorted(
                    _attr_sites(ctx, cls_node, declared).items()):
                locked_sites = [s for s in sites if s.locked]
                if not locked_sites:
                    continue
                for s in sites:
                    if not s.write or s.locked \
                            or s.thread_reason is None:
                        continue
                    if _acquitted(ctx, s.node):
                        continue
                    other = next((o for o in locked_sites
                                  if o.node is not s.node), None)
                    if other is None:
                        continue
                    lock = sorted(other.locked)[0]
                    yield self.finding(
                        ctx, s.node,
                        f"`self.{attr}` is mutated with no lock held in "
                        f"`{s.qualname}`, which can run off the main "
                        f"thread ({s.thread_reason}), while "
                        f"`{other.qualname}` (line {other.node.lineno}) "
                        f"accesses it under `{lock}` — the unlocked "
                        f"thread-side write races every locked reader "
                        f"and corrupts the shared state exactly when "
                        f"the async path fires")
                    break  # one finding per attribute: the fix is one
                    # lock region, not N findings for N statements


# ------------------------------------------------------ lock-order graph
def _local_acquires(ctx: ModuleContext, declared: Set[str]
                    ) -> Dict[str, Set[str]]:
    """qualname -> lock ids the function body DIRECTLY acquires."""
    out: Dict[str, Set[str]] = {}
    for qn, info in ctx.functions.items():
        ids: Set[str] = set()
        for sub in ast.walk(info.node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    lid = _lock_id(ctx, item.context_expr, declared)
                    if lid is not None:
                        ids.add(lid)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "acquire":
                lid = _lock_id(ctx, sub.func.value, declared)
                if lid is not None:
                    ids.add(lid)
        if ids:
            out[qn] = ids
    return out


def _transitive_acquires(ctx: ModuleContext,
                         declared: Set[str]) -> Dict[str, Set[str]]:
    """qualname -> lock ids acquired by the function or any
    module-local callee (fixpoint over the call graph)."""
    acq = {qn: set(ids)
           for qn, ids in _local_acquires(ctx, declared).items()}
    changed = True
    while changed:
        changed = False
        for qn, info in ctx.functions.items():
            for sub in ast.walk(info.node):
                if not isinstance(sub, ast.Call):
                    continue
                callee = last_name(sub.func)
                if callee is None:
                    continue
                resolved = ctx.resolve_function(callee, qn)
                if resolved is None or resolved == qn:
                    continue
                callee_ids = acq.get(resolved)
                if not callee_ids:
                    continue
                cur = acq.setdefault(qn, set())
                before = len(cur)
                cur |= callee_ids
                if len(cur) != before:
                    changed = True
    return acq


def _acquisition_edges(ctx: ModuleContext, declared: Set[str]
                       ) -> Dict[Tuple[str, str], ast.AST]:
    """(held, acquired) -> first site where lock ``acquired`` is taken
    while ``held`` is held — directly, or through a module-local call
    whose (transitive) body takes it."""
    trans = _transitive_acquires(ctx, declared)
    edges: Dict[Tuple[str, str], ast.AST] = {}

    def add(held: Dict[str, ast.AST], acquired: Set[str],
            site: ast.AST) -> None:
        for h in held:
            for a in acquired:
                if a != h:
                    edges.setdefault((h, a), site)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            ids = {lid for item in node.items
                   for lid in [_lock_id(ctx, item.context_expr, declared)]
                   if lid is not None}
            if ids:
                add(_held_locks(ctx, node, declared), ids, node)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute):
            if node.func.attr == "acquire":
                lid = _lock_id(ctx, node.func.value, declared)
                if lid is not None:
                    held = _held_locks(ctx, node, declared)
                    held.pop(lid, None)  # RLock re-entry is not an edge
                    add(held, {lid}, node)
        if isinstance(node, ast.Call):
            callee = last_name(node.func)
            if callee is None:
                continue
            qn = ctx.enclosing_qualname(node)
            resolved = ctx.resolve_function(
                callee, "" if qn == "<module>" else qn)
            callee_ids = trans.get(resolved) if resolved else None
            if callee_ids:
                held = _held_locks(ctx, node, declared)
                add(held, callee_ids - set(held), node)
    return edges


class LockOrderInversion(Rule):
    """APX115: the module's static lock-acquisition graph has a cycle —
    somewhere lock B is taken while A is held, and somewhere else A
    while B is held.

    With both orders live, two threads interleaving at the wrong
    moment deadlock permanently (each holds the lock the other
    wants); with the watchdog or a signal handler on one side the hang
    presents as a wedged step the watchdog itself cannot report,
    because it is a party to the deadlock.  Edges follow module-local
    calls (a helper that takes B, called under A, is an A→B edge at
    the call site), so the cycle is found even when no function
    spells both ``with`` statements."""

    rule_id = "APX115"
    severity = "error"
    fix_hint = ("pick ONE global acquisition order for the two locks "
                "and re-nest the minority site (release before taking "
                "the other, or hoist the second acquisition out of the "
                "region); wrap both with apex_tpu.resilience.locks."
                "monitored_lock and run the suite with "
                "instrument_locks() to catch the inversion at runtime")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        declared = _declared_locks(ctx)
        if len(declared) < 2 and "lock" not in ctx.source.lower():
            return
        edges = _acquisition_edges(ctx, declared)
        seen: Set[Tuple[str, str]] = set()
        for (a, b), site in sorted(
                edges.items(),
                key=lambda kv: getattr(kv[1], "lineno", 0)):
            rev = edges.get((b, a))
            if rev is None or (b, a) in seen:
                continue
            seen.add((a, b))
            yield self.finding(
                ctx, site,
                f"lock-order inversion: `{b}` is acquired while "
                f"`{a}` is held here (line {site.lineno}), but line "
                f"{rev.lineno} ({ctx.enclosing_qualname(rev)}) "
                f"acquires `{a}` while holding `{b}` — two threads "
                f"interleaving across these sites deadlock "
                f"permanently, each holding the lock the other wants")


# ------------------------------------------------------- blocking calls
def _no_timeout(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    return not call.args


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call can block indefinitely, or None.  ``join``/``get``
    /``wait`` only count bare and timeout-less (``d.get(k)`` and
    ``t.join(2.0)`` are fine); the named seams block by contract."""
    name = last_name(call.func)
    if name in ("join", "get", "wait") \
            and isinstance(call.func, ast.Attribute) and _no_timeout(call):
        return f"timeout-less `.{name}()`"
    if name == "block_until_ready":
        return "`block_until_ready()` (device sync)"
    if name == "wait_until_finished":
        return "`wait_until_finished()` (checkpoint drain)"
    if name in ("save_checkpoint", "load_checkpoint"):
        return f"checkpoint I/O (`{name}`)"
    if name in ("process_allgather", "check_uniform"):
        return f"host collective (`{name}`)"
    return None


class BlockingCallUnderContendedLock(Rule):
    """APX116: a blocking call runs while holding a lock that a
    signal-handler- or watchdog-callback-reachable function also
    acquires.

    The drain-deadlock shape PR 8's re-entrancy guard fixed by hand:
    the main thread holds the lock across a checkpoint drain, the
    preemption signal (or the watchdog's ``on_wedge``) fires
    mid-block, its handler queues behind the held lock, and the
    process hangs inside its own cleanup — the supervisor sees a
    silent non-exit, not a crash.  The contention evidence is
    required: blocking under a lock nobody else async-acquires is
    merely slow, not a deadlock, and stays quiet."""

    rule_id = "APX116"
    severity = "warning"
    fix_hint = ("move the blocking call out of the lock region "
                "(snapshot the state under the lock, block after "
                "release), give the wait a timeout, or route the async "
                "path through a re-entrancy guard (the "
                "PreemptionHandler.drain Event pattern) so it never "
                "queues behind this lock")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        tidx = dataflow.thread_index(ctx)
        declared = _declared_locks(ctx)
        if not declared:
            return
        # locks acquired by signal/callback-reachable functions
        contended: Dict[str, str] = {}
        for qn, ids in _transitive_acquires(ctx, declared).items():
            kinds = tidx.kinds_of(qn)
            for k in _ASYNC_KINDS:
                if k in kinds:
                    for lid in ids:
                        contended.setdefault(
                            lid, f"`{qn}` ({kinds[k]})")
        if not contended:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            why = _blocking_reason(node)
            if why is None:
                continue
            held = _held_locks(ctx, node, declared)
            for lid in sorted(held):
                other = contended.get(lid)
                if other is None:
                    continue
                # the async acquirer being THIS function is not
                # contention — it cannot interrupt itself
                if other.startswith(
                        f"`{ctx.enclosing_qualname(node)}`"):
                    continue
                if _acquitted(ctx, node):
                    continue
                yield self.finding(
                    ctx, node,
                    f"blocking call ({why}) while holding `{lid}`, "
                    f"which {other} also acquires from a signal/"
                    f"watchdog path: if the async path fires "
                    f"mid-block it queues behind this lock and the "
                    f"process hangs in its own cleanup — a silent "
                    f"non-exit, not a crash")
                break
