"""Collective-axis consistency rules.

``lax.psum("tq")`` is a one-character typo away from ``"tp"`` and
nothing catches it before the chip: off-mesh axis names fail only when
the collective actually executes under ``shard_map``, and the tp=1 CI
configurations never execute it at all.  The registry of legal names
comes from ``transformer/parallel_state.py``'s ``*_AXIS`` constants
(discovered by the engine), so the linter tracks the mesh definition
instead of a hand-maintained list.

Three tiers of precision, each yielding EXACTLY ONE finding per
hazard:

- APX201 (registry): the axis name is not on the mesh at all.
- APX203/204 (dataflow): the name is registered, and the axis-scope
  pass (``dataflow.scopes_at``) PROVES how the collective's function is
  reached — only through ``jit``/``pjit`` with the axis unbound
  (APX203), or through a ``shard_map`` nest none of whose axes match
  (APX204).  Scalar axis spellings only: tuple-of-axes collectives
  (``psum(x, ("dp_out", "dp_in"))``, the hierarchical-sync spelling)
  belong to APX205, which judges the whole tuple at once.
- APX202 (heuristic): no scope information at all — the collective's
  callers are outside static reach, and the module shows no spmd
  machinery either; the old invisible-caller-contract warning.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import Finding, ModuleContext, Rule, last_name

# collective -> positional index of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "pshuffle": 1, "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
}

_SPMD_MARKERS = ("shard_map", "pmap", "xmap", "Mesh(", "mesh=")


def _axis_arg(call: ast.Call, pos: int) -> Optional[ast.AST]:
    """The axis-name argument expression of a collective call (the
    ``axis_name=`` keyword wins over the positional slot), or None."""
    arg = None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            arg = kw.value
    if arg is None and len(call.args) > pos:
        arg = call.args[pos]
    return arg


def _axis_literals(call: ast.Call, pos: int) -> List[Tuple[ast.AST, str]]:
    """(node, literal) pairs for every string literal in the axis-name
    argument — handles both ``"tp"`` and ``("dcn", "dp")``.  Dynamic
    axis names (parameters, variables) yield nothing: threading the
    axis as an argument is exactly the pattern we want."""
    arg = _axis_arg(call, pos)
    if arg is None:
        return []
    out: List[Tuple[ast.AST, str]] = []
    nodes = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
    for n in nodes:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append((n, n.value))
    return out


def _collective_calls(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = last_name(node.func)
            if name in _COLLECTIVES:
                yield node, name, _COLLECTIVES[name]


class UnknownCollectiveAxis(Rule):
    """APX201: collective with a literal axis name not in the mesh
    registry."""

    rule_id = "APX201"
    severity = "error"
    fix_hint = ("use an axis name registered in transformer/"
                "parallel_state.py (its *_AXIS constants define the "
                "mesh), or thread the axis in as an argument")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call, name, pos in _collective_calls(ctx):
            for node, literal in _axis_literals(call, pos):
                if literal not in ctx.axis_registry:
                    known = ", ".join(sorted(ctx.axis_registry))
                    yield self.finding(
                        ctx, node,
                        f"lax.{name} over unknown axis {literal!r}: the "
                        f"mesh registry defines only {{{known}}}, so "
                        f"this collective can never bind — it fails "
                        f"only when first executed under shard_map on "
                        f"the chip")


class CollectiveOutsideSpmdContext(Rule):
    """APX202: hard-coded collective axis in a module with no visible
    shard_map/pmap/mesh machinery — and no dataflow verdict either.

    A ``psum("dp")`` whose module never touches shard_map depends on a
    caller somewhere else binding "dp" — an invisible contract that
    breaks unexecuted (tp=1 CI never runs it).  Threading ``axis_name``
    as a parameter makes the contract explicit and silences this rule.

    Where the axis-scope pass has ANY scope information for the
    enclosing function, this heuristic yields to APX203/204 (which
    either prove the axis bound — no finding at all — or prove it
    unbound, a harder error): one hazard, one finding.
    """

    rule_id = "APX202"
    severity = "warning"
    fix_hint = ("accept axis_name as a parameter (making the caller's "
                "shard_map contract explicit) or bring the shard_map "
                "that binds this axis into the module")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.mentions(*_SPMD_MARKERS):
            return
        for call, name, pos in _collective_calls(ctx):
            if dataflow.scopes_at(ctx, call):
                continue  # the dataflow tier owns this call site
            for node, literal in _axis_literals(call, pos):
                if literal in ctx.axis_registry:
                    yield self.finding(
                        ctx, node,
                        f"lax.{name}({literal!r}) in a module with no "
                        f"shard_map/pmap/mesh in sight: nothing here "
                        f"binds {literal!r}, so correctness rests on an "
                        f"undocumented caller contract")


def _scope_verdict(ctx: ModuleContext, call: ast.Call,
                   axis: str) -> Optional[str]:
    """'jit' (APX203) / 'mismatch' (APX204) / None (bound, unknowable,
    or no scope info).  Union semantics: one reaching context that
    binds (or MAY bind — ``unknown``) the axis acquits the call site;
    the rules only speak when every known path fails."""
    scopes = dataflow.scopes_at(ctx, call)
    if not scopes:
        return None
    if any(s.binds(axis) for s in scopes):
        return None
    return "mismatch" if any(s.shard_map for s in scopes) else "jit"


def _bound_axes(scopes) -> str:
    axes = sorted(set().union(*(s.axes for s in scopes)))
    return ", ".join(axes) if axes else "(none)"


class CollectiveAxisUnboundUnderJit(Rule):
    """APX203: a registered-axis collective reachable ONLY from
    ``jit``/``pjit``-traced entry points, where no shard_map binds the
    axis.

    ``jit`` auto-sharding binds no axis names — ``lax.psum(x, "dp")``
    under plain jit is an unbound-axis error at trace time.  But for
    TPU-gated code the first trace happens on the chip, and the tp=1
    CI mesh may never execute the branch at all: the error is real,
    deferred, and this rule moves it to CI.  Subsumes APX202 wherever
    the dataflow pass can actually see the callers.
    """

    rule_id = "APX203"
    severity = "error"
    fix_hint = ("wrap the traced entry point in shard_map (binding the "
                "axis) instead of bare jit/pjit, or drop the collective "
                "— under jit auto-sharding XLA inserts the data "
                "movement itself")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call, name, pos in _collective_calls(ctx):
            if isinstance(_axis_arg(call, pos), (ast.Tuple, ast.List)):
                continue  # APX205 owns tuple-of-axes spellings
            for node, literal in _axis_literals(call, pos):
                if literal not in ctx.axis_registry:
                    continue  # APX201's finding
                if _scope_verdict(ctx, call, literal) == "jit":
                    yield self.finding(
                        ctx, node,
                        f"lax.{name}({literal!r}) is reachable only "
                        f"from jit/pjit-traced entry points: jit "
                        f"auto-sharding binds no axis names, so "
                        f"{literal!r} is unbound and the first real "
                        f"trace dies with an unbound-axis error — on "
                        f"the chip, after CPU CI passed")


class CollectiveAxisOutsideShardMapNest(Rule):
    """APX204: the collective's axis differs from every axis bound by
    the enclosing ``shard_map`` nest.

    The one-character-typo class APX201 cannot catch: ``"dp"`` and
    ``"tp"`` are both on the mesh, but the shard_map this function runs
    under binds only one of them.  The axis-scope pass knows the nest's
    full axis set only when the mesh itself is statically resolvable
    (``Mesh(devs, ("dp", "tp"))`` through a local alias); dynamic
    meshes mark the scope ``unknown`` and stay quiet.
    """

    rule_id = "APX204"
    severity = "error"
    fix_hint = ("use one of the axes the enclosing shard_map binds, or "
                "add the intended axis to the shard_map's mesh; if the "
                "function is meant to be generic, thread axis_name as "
                "a parameter")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call, name, pos in _collective_calls(ctx):
            if isinstance(_axis_arg(call, pos), (ast.Tuple, ast.List)):
                continue  # APX205 owns tuple-of-axes spellings
            for node, literal in _axis_literals(call, pos):
                if literal not in ctx.axis_registry:
                    continue  # APX201's finding
                if _scope_verdict(ctx, call, literal) == "mismatch":
                    scopes = dataflow.scopes_at(ctx, call)
                    yield self.finding(
                        ctx, node,
                        f"lax.{name}({literal!r}) runs under a "
                        f"shard_map nest that binds only "
                        f"{{{_bound_axes(scopes)}}}: {literal!r} is "
                        f"never bound on any reaching path, so the "
                        f"collective fails at trace time — on the "
                        f"chip, for TPU-gated kernels")


class CollectiveTupleAxisUnbound(Rule):
    """APX205: a collective invoked with a TUPLE of axis names —
    ``psum(x, ("dp_out", "dp_in"))``, the hierarchical-sync spelling —
    where some member axis is provably unbound on every reaching path.

    The scalar dataflow rules (APX203/204) yield tuple spellings to
    this rule: a tuple collective needs EVERY member bound in the SAME
    nest, and the one finding here names exactly the members that are
    not, instead of one scalar finding per member.  Unregistered
    members stay APX201's finding (the registry tier speaks whether or
    not dataflow has a verdict); members spelled dynamically leave the
    unbound check quiet for the whole call (the nest MAY bind them).
    """

    rule_id = "APX205"
    severity = "error"
    fix_hint = ("bind every member axis in the enclosing shard_map's "
                "mesh (a hierarchical (outer, inner) collective needs "
                "both hops on the mesh), or thread the axis tuple in "
                "as an argument")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call, name, pos in _collective_calls(ctx):
            arg = _axis_arg(call, pos)
            if not isinstance(arg, (ast.Tuple, ast.List)):
                continue
            if not all(isinstance(e, ast.Constant)
                       and isinstance(e.value, str) for e in arg.elts):
                continue  # a dynamic member may bind anything — quiet
            members = [e.value for e in arg.elts]
            registered = [m for m in members if m in ctx.axis_registry]
            verdicts = {m: _scope_verdict(ctx, call, m)
                        for m in registered}
            unbound = [m for m in registered if verdicts[m] is not None]
            if not unbound:
                continue
            scopes = dataflow.scopes_at(ctx, call)
            under = ("a shard_map nest that binds only "
                     f"{{{_bound_axes(scopes)}}}"
                     if any(s.shard_map for s in scopes)
                     else "jit/pjit-traced entry points only (jit "
                          "auto-sharding binds no axis names)")
            unreg = [m for m in members if m not in ctx.axis_registry]
            extra = (f" (members {unreg} are not in the mesh registry "
                     "at all — APX201's finding)" if unreg else "")
            yield self.finding(
                ctx, arg,
                f"lax.{name}({tuple(members)!r}) reaches "
                f"{under}: member axis(es) "
                f"{unbound} are never bound on any reaching path, so "
                f"the whole tuple collective fails at trace time — on "
                f"the chip, for TPU-gated kernels{extra}")
