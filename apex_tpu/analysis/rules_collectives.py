"""Collective-axis consistency rules.

``lax.psum("tq")`` is a one-character typo away from ``"tp"`` and
nothing catches it before the chip: off-mesh axis names fail only when
the collective actually executes under ``shard_map``, and the tp=1 CI
configurations never execute it at all.  The registry of legal names
comes from ``transformer/parallel_state.py``'s ``*_AXIS`` constants
(discovered by the engine), so the linter tracks the mesh definition
instead of a hand-maintained list.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from apex_tpu.analysis.core import Finding, ModuleContext, Rule, last_name

# collective -> positional index of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "pshuffle": 1, "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
}

_SPMD_MARKERS = ("shard_map", "pmap", "xmap", "Mesh(", "mesh=")


def _axis_literals(call: ast.Call, pos: int) -> List[Tuple[ast.AST, str]]:
    """(node, literal) pairs for every string literal in the axis-name
    argument — handles both ``"tp"`` and ``("dcn", "dp")``.  Dynamic
    axis names (parameters, variables) yield nothing: threading the
    axis as an argument is exactly the pattern we want."""
    arg = None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            arg = kw.value
    if arg is None and len(call.args) > pos:
        arg = call.args[pos]
    if arg is None:
        return []
    out: List[Tuple[ast.AST, str]] = []
    nodes = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
    for n in nodes:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append((n, n.value))
    return out


def _collective_calls(ctx: ModuleContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = last_name(node.func)
            if name in _COLLECTIVES:
                yield node, name, _COLLECTIVES[name]


class UnknownCollectiveAxis(Rule):
    """APX201: collective with a literal axis name not in the mesh
    registry."""

    rule_id = "APX201"
    severity = "error"
    fix_hint = ("use an axis name registered in transformer/"
                "parallel_state.py (its *_AXIS constants define the "
                "mesh), or thread the axis in as an argument")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call, name, pos in _collective_calls(ctx):
            for node, literal in _axis_literals(call, pos):
                if literal not in ctx.axis_registry:
                    known = ", ".join(sorted(ctx.axis_registry))
                    yield self.finding(
                        ctx, node,
                        f"lax.{name} over unknown axis {literal!r}: the "
                        f"mesh registry defines only {{{known}}}, so "
                        f"this collective can never bind — it fails "
                        f"only when first executed under shard_map on "
                        f"the chip")


class CollectiveOutsideSpmdContext(Rule):
    """APX202: hard-coded collective axis in a module with no visible
    shard_map/pmap/mesh machinery.

    A ``psum("dp")`` whose module never touches shard_map depends on a
    caller somewhere else binding "dp" — an invisible contract that
    breaks unexecuted (tp=1 CI never runs it).  Threading ``axis_name``
    as a parameter makes the contract explicit and silences this rule.
    """

    rule_id = "APX202"
    severity = "warning"
    fix_hint = ("accept axis_name as a parameter (making the caller's "
                "shard_map contract explicit) or bring the shard_map "
                "that binds this axis into the module")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.mentions(*_SPMD_MARKERS):
            return
        for call, name, pos in _collective_calls(ctx):
            for node, literal in _axis_literals(call, pos):
                if literal in ctx.axis_registry:
                    yield self.finding(
                        ctx, node,
                        f"lax.{name}({literal!r}) in a module with no "
                        f"shard_map/pmap/mesh in sight: nothing here "
                        f"binds {literal!r}, so correctness rests on an "
                        f"undocumented caller contract")
