"""Recovery-path hygiene rules (the silent-swallow class).

The resilience/io/inference modules ARE the error-handling layer: an
``except`` block there that does literally nothing (``pass``/``...``)
erases the one signal a postmortem needs — PR 10's review kept
hand-auditing exactly this pattern, because a swallowed drain error or
a silently-dropped shard-read failure turns "the run wedged and we know
why" into "the run wedged".  The tree's own convention is that every
recovery path reports: re-raise, ``log_structured`` (the greppable
``EVENT {json}`` contract), or a metrics record
(``apex_tpu.observability.metrics.inc/observe/set_gauge``).

- APX109: an ``except`` handler in a resilience/io/inference module
  whose body is ONLY ``pass``/``...``/a bare string — no re-raise, no
  logging, no metrics, no fallback value, nothing.  Handlers with ANY
  other statement (a ``return`` default, a log call, a counter bump, a
  flag set) are trusted: the rule targets the zero-information
  swallow, not defensive defaults.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from apex_tpu.analysis.core import Finding, ModuleContext, Rule

__all__ = ["SwallowedExceptionInRecoveryPath"]

#: Directory components that mark a module as recovery-path code: the
#: fault-handling runtime, the checkpoint/restore layer, and the
#: serving engine (whose error paths feed the supervisor's restart
#: decisions).  Matched as path SEGMENTS, so ``examples/gpt/...`` and
#: ``observability/...`` stay out of scope.
_RECOVERY_DIRS = frozenset({"resilience", "io", "inference"})


def _is_noop(stmt: ast.stmt) -> bool:
    """``pass``, ``...``, or a bare constant expression (a stray string
    used as a comment) — statements that observably do nothing."""
    if isinstance(stmt, ast.Pass):
        return True
    return isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant)


class SwallowedExceptionInRecoveryPath(Rule):
    """APX109: a do-nothing ``except`` in a recovery-path module — the
    error is swallowed with no re-raise, no structured log, and no
    metrics record, so the failure it caught is invisible to the
    supervisor, the goodput report, and the postmortem."""

    rule_id = "APX109"
    severity = "error"
    fix_hint = ("recovery paths must report what they survive: re-raise, "
                "emit a log_structured event (the greppable EVENT {json} "
                "contract), or record a metric "
                "(observability.metrics.inc/observe) — if the error is "
                "truly ignorable, say WHY in a handler that at least "
                "logs it; a bare `except: pass` in "
                "resilience/io/inference erases the one signal a wedged "
                "run's postmortem needs")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        dirs = re.split(r"[\\/]", ctx.path)[:-1]
        if not _RECOVERY_DIRS.intersection(dirs):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not node.body or not all(_is_noop(s) for s in node.body):
                continue
            caught = (ast.get_source_segment(ctx.source, node.type)
                      if node.type is not None else "BaseException (bare)")
            yield self.finding(
                ctx, node,
                f"except block swallows {caught} with a do-nothing body "
                f"in a recovery-path module ({os.path.basename(ctx.path)})"
                " — no re-raise, no log_structured, no metrics record")
