"""Recovery-path hygiene rules (the silent-swallow class).

The resilience/io/inference modules ARE the error-handling layer: an
``except`` block there that does literally nothing (``pass``/``...``)
erases the one signal a postmortem needs — PR 10's review kept
hand-auditing exactly this pattern, because a swallowed drain error or
a silently-dropped shard-read failure turns "the run wedged and we know
why" into "the run wedged".  The tree's own convention is that every
recovery path reports: re-raise, ``log_structured`` (the greppable
``EVENT {json}`` contract), or a metrics record
(``apex_tpu.observability.metrics.inc/observe/set_gauge``).

- APX109: an ``except`` handler in a resilience/io/inference module
  whose body is ONLY ``pass``/``...``/a bare string — no re-raise, no
  logging, no metrics, no fallback value, nothing.  Handlers with ANY
  other statement (a ``return`` default, a log call, a counter bump, a
  flag set) are trusted: the rule targets the zero-information
  swallow, not defensive defaults.
- APX113: a hot retry loop in the same modules — ``while True:`` (any
  truthy-constant test) wrapping a ``try`` whose handlers neither
  re-raise, ``break``, nor ``return``, with NO backoff anywhere in the
  loop (no call whose name mentions sleep/backoff/wait/delay/jitter).
  That shape spins at CPU speed against whatever is failing — a dead
  coordinator, a wedged replica, a full disk — turning one fault into
  a busy-wait that starves the very recovery it is waiting for.  The
  fleet/elastic convention is a typed ``Overloaded``-style retry-after
  or an explicit ``time.sleep``/backoff between attempts.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from apex_tpu.analysis.core import Finding, ModuleContext, Rule

__all__ = ["RetryWithoutBackoff", "SwallowedExceptionInRecoveryPath"]

#: Directory components that mark a module as recovery-path code: the
#: fault-handling runtime, the checkpoint/restore layer, and the
#: serving engine (whose error paths feed the supervisor's restart
#: decisions).  Matched as path SEGMENTS, so ``examples/gpt/...`` and
#: ``observability/...`` stay out of scope.
_RECOVERY_DIRS = frozenset({"resilience", "io", "inference"})


def _is_noop(stmt: ast.stmt) -> bool:
    """``pass``, ``...``, or a bare constant expression (a stray string
    used as a comment) — statements that observably do nothing."""
    if isinstance(stmt, ast.Pass):
        return True
    return isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant)


class SwallowedExceptionInRecoveryPath(Rule):
    """APX109: a do-nothing ``except`` in a recovery-path module — the
    error is swallowed with no re-raise, no structured log, and no
    metrics record, so the failure it caught is invisible to the
    supervisor, the goodput report, and the postmortem."""

    rule_id = "APX109"
    severity = "error"
    fix_hint = ("recovery paths must report what they survive: re-raise, "
                "emit a log_structured event (the greppable EVENT {json} "
                "contract), or record a metric "
                "(observability.metrics.inc/observe) — if the error is "
                "truly ignorable, say WHY in a handler that at least "
                "logs it; a bare `except: pass` in "
                "resilience/io/inference erases the one signal a wedged "
                "run's postmortem needs")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        dirs = re.split(r"[\\/]", ctx.path)[:-1]
        if not _RECOVERY_DIRS.intersection(dirs):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not node.body or not all(_is_noop(s) for s in node.body):
                continue
            caught = (ast.get_source_segment(ctx.source, node.type)
                      if node.type is not None else "BaseException (bare)")
            yield self.finding(
                ctx, node,
                f"except block swallows {caught} with a do-nothing body "
                f"in a recovery-path module ({os.path.basename(ctx.path)})"
                " — no re-raise, no log_structured, no metrics record")


#: Call-name fragments that count as pacing the loop: an explicit
#: sleep/backoff helper, a blocking wait with a timeout
#: (``child.wait(timeout=...)``, ``event.wait(...)``), or jittered
#: delay computation.  Substring match on the called name, lowercased —
#: ``time.sleep``, ``_backoff_s``, ``child.wait`` all acquit.
_PACING_TOKENS = ("sleep", "backoff", "wait", "delay", "jitter")

#: Blocking primitives that also pace a loop, matched by EXACT call
#: name with no positional arguments: ``q.get()`` (queue dequeue),
#: ``lock.acquire()``, ``thread.join()`` all park the thread until
#: something external happens — a worker loop built on one is not a
#: busy-spin.  The no-positional-args restriction keeps ``dict.get(k)``
#: from acquitting anything.
_BLOCKING_CALLS = frozenset({"get", "acquire", "join"})


def _is_truthy_const(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _call_name(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id.lower()
    if isinstance(fn, ast.Attribute):
        return fn.attr.lower()
    return ""


def _loop_is_paced(node: ast.While) -> bool:
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = _call_name(sub)
        if any(tok in name for tok in _PACING_TOKENS):
            return True
        if name in _BLOCKING_CALLS and not sub.args:
            return True
    return False


def _handler_escapes(handler: ast.ExceptHandler) -> bool:
    """Does the handler leave the loop (``raise``/``break``/``return``)
    instead of swallowing and re-iterating?"""
    return any(isinstance(sub, (ast.Raise, ast.Break, ast.Return))
               for sub in ast.walk(handler))


class RetryWithoutBackoff(Rule):
    """APX113: an unpaced hot retry loop in a recovery-path module —
    ``while True:`` around a ``try`` that swallows the failure and
    immediately re-attempts, with no sleep/backoff/wait anywhere in the
    loop.  Against a persistent fault (dead coordinator, wedged
    replica, full disk) this busy-spins, hammering the failing
    dependency exactly when it needs room to recover."""

    rule_id = "APX113"
    severity = "error"
    fix_hint = ("pace the retry: time.sleep a (jittered, capped) "
                "backoff between attempts, honor the typed retry-after "
                "(fleet.Overloaded.retry_after_s is that signal), or "
                "escape the loop (re-raise / break / return) after a "
                "bounded attempt budget — resilience.elastic's "
                "supervisor and io's retry helpers show both shapes")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        dirs = re.split(r"[\\/]", ctx.path)[:-1]
        if not _RECOVERY_DIRS.intersection(dirs):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While) \
                    or not _is_truthy_const(node.test):
                continue
            if _loop_is_paced(node):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Try) or not sub.handlers:
                    continue
                if any(_handler_escapes(h) for h in sub.handlers):
                    continue
                yield self.finding(
                    ctx, sub,
                    f"unpaced retry: `while True:` re-attempts after a "
                    f"swallowed exception with no sleep/backoff/wait in "
                    f"the loop ({os.path.basename(ctx.path)}) — a "
                    f"persistent fault becomes a busy-spin against the "
                    f"failing dependency")
