"""Precision and indexing hygiene rules.

- APX401: unclamped ``take_along_axis`` (the ``gpt.py:447`` class).
  Under jit, JAX's gather clamps *some* out-of-bounds reads and fills
  others depending on mode and sign — a negative id silently WRAPS.
  Three loss-head implementations that disagree on out-of-range ids
  diverge only on corrupt data, the hardest moment to debug; one
  explicit ``jnp.clip`` pins one semantic everywhere.
- APX402: an explicitly-materialized fp32 constant meeting a bf16
  operand.  Binary-op promotion silently upcasts the whole bf16 tensor
  to fp32 — doubling its HBM traffic in a compute path someone already
  paid to keep in bf16.
- APX303: a scratch buffer or local accumulator whose dtype is
  NARROWER than the ``preferred_element_type`` of the dot accumulated
  into it.  The MXU computes the requested fp32 partials, then every
  store re-rounds them to bf16 — the accumulation quality the
  ``preferred_element_type`` was written to buy is silently thrown
  away, and the loss only shows on long reduction chains on real data.
  Dtypes resolve through the local-assignment lattice
  (``dataflow.dtype_env``), and scratch refs are matched to their
  ``pallas_call``'s ``scratch_shapes`` declarations positionally (the
  trailing kernel parameters, by the Pallas calling convention).
- APX107: page-table gather without a clamp/mask — the APX401
  unclamped-gather family extended to the decode path.  A page table
  maps logical sequence positions onto pool pages; its entries are
  host-maintained mutable state (admission/eviction rewrites them
  every step), so a stale or corrupt entry is a WHEN, not an if.  An
  unclamped ``take``/subscript gather through one wraps negative ids
  and clamps-or-fills past-end ids depending on gather mode — reading
  (or worse, scattering into) a LIVE sequence's page instead of the
  reserved garbage page.
- APX306: KV-cache storage read into a wider attention accumulator
  without an explicit widen.  The cache pool is deliberately stored
  narrow (bf16 by default — half the HBM); a dot that declares
  ``preferred_element_type=f32`` but feeds the narrow cache buffer in
  directly leaves the widening decision to the backend — Mosaic and
  XLA agree today, but the decode kernels' contract is the EXPLICIT
  ``.astype`` at the read seam, where the intent is visible and the
  interpret-mode tests exercise the same arithmetic as the chip.
- APX305: quantized-sync state narrower than its contract.  Inside a
  function that casts to a quantized WIRE dtype (int8/fp8 — the
  compressed grad-sync idiom), a ``scale``-named buffer provably
  narrower than fp32, or a ``residual``-named buffer provably AT the
  wire width.  A half-precision scale re-quantizes the quantizer
  (every dequantize multiplies by a rounded scale, a bias error
  feedback cannot see), and a wire-width residual throws away exactly
  the error-feedback information it exists to carry — the residual
  must live in the bucket's storage dtype (a >= 2-byte float).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, _is_partial, dotted_name, last_name,
)

_F32_FACTORIES = {"array", "asarray", "full", "ones", "zeros", "arange",
                  "linspace", "full_like", "ones_like", "zeros_like"}
_BINOPS = (ast.BinOp,)


def _contains_clip(node: ast.AST, clipped: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and last_name(sub.func) == "clip":
            return True
        if isinstance(sub, ast.Name) and sub.id in clipped:
            return True
    return False


def _clipped_names(fn: ast.AST) -> Set[str]:
    """Names assigned (directly or through arithmetic on a clipped
    name) from a ``clip`` call anywhere in the function."""
    clipped: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name not in clipped \
                        and _contains_clip(node.value, clipped):
                    clipped.add(name)
                    changed = True
    return clipped


class UnclampedTakeAlongAxis(Rule):
    """APX401: take_along_axis with indices that are never clamped."""

    rule_id = "APX401"
    severity = "error"
    fix_hint = ("clamp the ids first (t = jnp.clip(t, 0, V - 1)) or pass "
                "an explicit mode=; all loss-head paths must share one "
                "out-of-range semantic")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and last_name(node.func) == "take_along_axis"):
                continue
            if any(kw.arg == "mode" for kw in node.keywords):
                continue  # explicit out-of-bounds semantic chosen
            indices = None
            for kw in node.keywords:
                if kw.arg == "indices":
                    indices = kw.value
            if indices is None and len(node.args) > 1:
                indices = node.args[1]
            if indices is None:
                continue
            fn = ctx.enclosing_function(node)
            clipped = _clipped_names(fn) if fn is not None else set()
            if _contains_clip(indices, clipped):
                continue
            yield self.finding(
                ctx, node,
                "take_along_axis with unclamped indices: under jit a "
                "negative id silently WRAPS and a past-end id is "
                "clamped/filled depending on gather mode — corrupt "
                "targets produce plausible-looking wrong losses instead "
                "of failing")


#: identifier substrings that mark a page-table value (the decode
#: path's host-maintained page indirection) — the APX107 scope guard
_PAGE_TABLE_NAMES = ("page_table", "block_table")


def _mentions_page_table(node: ast.AST) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) \
                and any(m in sub.id.lower() for m in _PAGE_TABLE_NAMES):
            return sub.id
    return None


class PageTableGatherUnclamped(Rule):
    """APX107: a ``take`` or subscript gather through a page table
    whose indices (or whose table values, when the table itself IS the
    index) are never clamped/masked."""

    rule_id = "APX107"
    severity = "error"
    fix_hint = ("clamp page-table reads into the pool "
                "(jnp.clip(table, 0, num_pages - 1)) and route masked "
                "writes to the reserved garbage page — a stale table "
                "entry must read/write garbage, never wrap into a live "
                "sequence's page")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_take(ctx, node)
            elif isinstance(node, ast.Subscript):
                yield from self._check_subscript(ctx, node)

    def _clipped(self, ctx: ModuleContext, node: ast.AST) -> Set[str]:
        fn = ctx.enclosing_function(node)
        return _clipped_names(fn) if fn is not None else set()

    def _check_take(self, ctx: ModuleContext,
                    node: ast.Call) -> Iterator[Finding]:
        if last_name(node.func) != "take":
            return
        if any(kw.arg == "mode" for kw in node.keywords):
            return  # explicit out-of-bounds semantic chosen
        indices = node.args[1] if len(node.args) > 1 else None
        for kw in node.keywords:
            if kw.arg == "indices":
                indices = kw.value
        if indices is None or not node.args:
            return
        src = node.args[0]
        table = (src.id if isinstance(src, ast.Name)
                 and any(m in src.id.lower() for m in _PAGE_TABLE_NAMES)
                 else _mentions_page_table(indices))
        if table is None:
            return
        if _contains_clip(indices, self._clipped(ctx, node)):
            return
        yield self.finding(
            ctx, node,
            f"unclamped take through page table `{table}`: a stale or "
            "corrupt table entry (or position index) WRAPS under jit "
            "instead of hitting the reserved garbage page — reading, or "
            "scattering into, a live sequence's page")

    @staticmethod
    def _at_mode_chosen(ctx: ModuleContext, node: ast.Subscript) -> bool:
        """``pool.at[table].set(x, mode="drop")`` — the explicit
        out-of-bounds semantic lives on the ``.set``/``.get`` call
        ENCLOSING the subscript, not on the subscript itself."""
        attr = ctx.parent(node)
        if not isinstance(attr, ast.Attribute):
            return False
        call = ctx.parent(attr)
        return isinstance(call, ast.Call) \
            and any(kw.arg == "mode" for kw in call.keywords)

    def _check_subscript(self, ctx: ModuleContext,
                         node: ast.Subscript) -> Iterator[Finding]:
        # pool[page_table] / pool.at[page_table, slot] — the table's
        # VALUES are the gather/scatter indices
        table = _mentions_page_table(node.slice)
        if table is None:
            return
        if _contains_clip(node.slice, self._clipped(ctx, node)):
            return
        if self._at_mode_chosen(ctx, node):
            return  # explicit out-of-bounds semantic chosen
        yield self.finding(
            ctx, node,
            f"page table `{table}` used as a gather/scatter index "
            "without a clamp or an explicit mode=: out-of-range page "
            "ids get backend-chosen out-of-bounds semantics (a gather "
            "WRAPS negative ids into the pool — a LIVE sequence's "
            "page; scatter behavior differs again) — the "
            "silent-corruption class the reserved garbage page exists "
            "to absorb")


_DOT_NAMES = {"dot", "dot_general"}
_ACC_FACTORIES = {"zeros", "ones", "full", "empty"}


def _dots_with_preferred(expr: ast.AST,
                         env: Dict[str, str]) -> List[Tuple[ast.Call, str]]:
    """(dot_call, preferred_dtype_name) for every dot/dot_general under
    ``expr`` that declares a resolvable ``preferred_element_type``."""
    out = []
    for sub in ast.walk(expr):
        if not (isinstance(sub, ast.Call)
                and last_name(sub.func) in _DOT_NAMES):
            continue
        pref = None
        for kw in sub.keywords:
            if kw.arg == "preferred_element_type":
                pref = dataflow.dtype_literal(kw.value, env)
        if pref is not None:
            out.append((sub, pref))
    return out


def _subscript_base(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name):
        return node.value.id
    return None


class ScratchAccumDtypeMismatch(Rule):
    """APX303: scratch/accumulator dtype narrower than the declared
    accumulation dtype of the dot stored into it."""

    rule_id = "APX303"
    severity = "error"
    fix_hint = ("declare the scratch/accumulator in the dot's "
                "preferred_element_type (fp32 for bf16 MXU dots) and "
                "cast once at the final store, or drop "
                "preferred_element_type if narrow accumulation is "
                "really intended")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in self._pallas_calls(ctx):
            yield from self._check_scratch(ctx, call)
        for info in ctx.functions.values():
            yield from self._check_local_accumulators(ctx, info.node)

    # ------------------------------------------------- scratch-ref side
    @staticmethod
    def _pallas_calls(ctx: ModuleContext) -> Iterator[ast.Call]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and last_name(node.func) == "pallas_call":
                yield node

    def _check_scratch(self, ctx: ModuleContext,
                       call: ast.Call) -> Iterator[Finding]:
        scratch = dataflow.scratch_entries(call)
        if not scratch:
            return
        kernel = self._resolve_kernel(ctx, call)
        if kernel is None:
            return
        args = kernel.args
        if args.vararg is not None:
            return  # dynamic parameter list: refs unmappable
        params = [a.arg for a in
                  list(getattr(args, "posonlyargs", [])) + list(args.args)]
        if len(params) < len(scratch):
            return
        # scratch dtype expressions evaluate at the CALL site, the
        # preferred_element_type ones inside the kernel — each side
        # resolves against its own function's env
        launcher = ctx.enclosing_function(call)
        call_env = dataflow.dtype_env(ctx, launcher)
        env = dataflow.dtype_env(ctx, kernel)
        ref_dtypes: Dict[str, Tuple[str, ast.AST]] = {}
        for name, (entry, _shape, dtype_node) in zip(
                params[len(params) - len(scratch):], scratch):
            d = dataflow.dtype_literal(dtype_node, call_env)
            if d is not None:
                ref_dtypes[name] = (d, entry)
        if not ref_dtypes:
            return
        for stmt in ast.walk(kernel):
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AugAssign):
                targets, value = [stmt.target], stmt.value
            else:
                continue
            hit = next((ref_dtypes[b] for t in targets
                        if (b := _subscript_base(t)) in ref_dtypes), None)
            if hit is None:
                continue
            scratch_dtype, _entry = hit
            yield from self._judge(ctx, value, scratch_dtype, env,
                                   what=f"scratch ref (declared "
                                        f"{scratch_dtype} in "
                                        f"scratch_shapes)")

    def _resolve_kernel(self, ctx: ModuleContext,
                        call: ast.Call) -> Optional[ast.AST]:
        """The kernel FunctionDef a pallas_call launches: a direct
        Name, an inline ``partial(f, ...)``, or a local alias to
        either."""
        if not call.args:
            return None
        node = call.args[0]
        scope = ctx.enclosing_qualname(call)
        scope = "" if scope == "<module>" else scope
        for _hop in range(2):
            if isinstance(node, ast.Call) and _is_partial(node) and node.args:
                node = node.args[0]
            if isinstance(node, ast.Name):
                qn = ctx.resolve_function(node.id, scope)
                if qn is not None:
                    return ctx.functions[qn].node
                # one local-alias hop: kernel = partial(_fwd_kernel, ...)
                aliased = self._alias_value(ctx, call, node.id)
                if aliased is None or aliased is node:
                    return None
                node = aliased
            else:
                return None
        return None

    @staticmethod
    def _alias_value(ctx: ModuleContext, call: ast.Call,
                     name: str) -> Optional[ast.AST]:
        """The value ``name`` was last assigned in the pallas_call's
        OWN enclosing function (two launchers both naming their
        partial ``kernel`` must not cross-resolve), module level as
        the fallback."""
        scopes = []
        fn = ctx.enclosing_function(call)
        if fn is not None:
            scopes.append(fn)
        scopes.append(ctx.tree)
        for scope in scopes:
            hit = None
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and node.targets[0].id == name \
                        and (scope is not ctx.tree
                             or ctx.enclosing_function(node) is None):
                    if hit is None or (node.lineno, node.col_offset) > \
                            (hit.lineno, hit.col_offset):
                        hit = node
            if hit is not None:
                return hit.value
        return None

    # --------------------------------------------- local-accumulator side
    def _check_local_accumulators(self, ctx: ModuleContext,
                                  fn: ast.AST) -> Iterator[Finding]:
        env = dataflow.dtype_env(ctx, fn)
        acc_dtypes: Dict[str, str] = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            if ctx.enclosing_function(node) is not fn:
                continue  # a nested def's local — judged under ITS entry
            v = node.value
            if isinstance(v, ast.Call) \
                    and last_name(v.func) in _ACC_FACTORIES:
                dtype_node = None
                for kw in v.keywords:
                    if kw.arg == "dtype":
                        dtype_node = kw.value
                if dtype_node is None and len(v.args) > 1:
                    dtype_node = v.args[-1]
                d = dataflow.dtype_literal(dtype_node, env)
                if d is not None:
                    acc_dtypes[node.targets[0].id] = d
        if not acc_dtypes:
            return
        for stmt in ast.walk(fn):
            if isinstance(stmt, (ast.Assign, ast.AugAssign)) \
                    and ctx.enclosing_function(stmt) is not fn:
                continue
            if isinstance(stmt, ast.AugAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.target.id in acc_dtypes:
                name, value = stmt.target.id, stmt.value
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id in acc_dtypes \
                    and any(isinstance(s, ast.Name)
                            and s.id == stmt.targets[0].id
                            for s in ast.walk(stmt.value)):
                name, value = stmt.targets[0].id, stmt.value
            else:
                continue
            yield from self._judge(
                ctx, value, acc_dtypes[name], env,
                what=f"accumulator `{name}` (initialized {acc_dtypes[name]})")

    def _judge(self, ctx: ModuleContext, value: ast.AST, store_dtype: str,
               env: Dict[str, str], what: str) -> Iterator[Finding]:
        store_size = dataflow.itemsize(store_dtype)
        if store_size is None:
            return
        for dot, pref in _dots_with_preferred(value, env):
            pref_size = dataflow.itemsize(pref)
            if pref_size is not None and store_size < pref_size:
                yield self.finding(
                    ctx, dot,
                    f"{store_dtype} {what} accumulates a dot with "
                    f"preferred_element_type={pref}: every store "
                    f"re-rounds the {pref} partials to {store_dtype}, "
                    f"silently discarding the accumulation precision "
                    f"the preferred_element_type was written to buy")


#: quantized wire dtypes — the presence of a cast to one of these is
#: what marks a function as quantized-sync code (the scoping guard:
#: the repo is full of ``loss_scale``-style names that have nothing to
#: do with wire quantization and must stay out of APX305's reach)
_WIRE_DTYPES = {"int8", "uint8", "float8_e4m3fn", "float8_e5m2",
                "float8_e4m3", "float8_e4m3fnuz", "float8_e5m2fnuz"}


def _cast_dtype(value: ast.AST, env: Dict[str, str]) -> Optional[str]:
    """The dtype an assignment's value provably creates: an
    ``x.astype(DT)`` cast, or a ``zeros/full/...`` factory with a
    ``dtype=`` (positional trailing arg included)."""
    if not isinstance(value, ast.Call):
        return None
    if isinstance(value.func, ast.Attribute) and value.func.attr == "astype" \
            and value.args:
        return dataflow.dtype_literal(value.args[0], env)
    if last_name(value.func) in _ACC_FACTORIES | _F32_FACTORIES:
        dtype_node = None
        for kw in value.keywords:
            if kw.arg == "dtype":
                dtype_node = kw.value
        if dtype_node is None and len(value.args) > 1:
            dtype_node = value.args[-1]
        return dataflow.dtype_literal(dtype_node, env)
    return None


class QuantizedSyncStateDtype(Rule):
    """APX305: error-feedback residual or scale buffer narrower than
    its quantized-sync contract (scales fp32; residuals storage-width,
    never the wire dtype)."""

    rule_id = "APX305"
    severity = "error"
    fix_hint = ("keep quantization scales in float32 (the dequantize "
                "multiplies by them — a rounded scale biases every "
                "block) and store error-feedback residuals in the "
                "bucket's storage dtype (bfloat16/float16/float32), "
                "never the int8/fp8 wire dtype")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for info in ctx.functions.values():
            if isinstance(info.node, ast.Lambda):
                continue
            yield from self._check_fn(ctx, info.node)

    def _assigns(self, ctx: ModuleContext, fn: ast.AST):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and ctx.enclosing_function(node) is fn:
                yield node

    def _check_fn(self, ctx: ModuleContext, fn: ast.AST) -> Iterator[Finding]:
        env = dataflow.dtype_env(ctx, fn)
        # the scoping marker: this function DIRECTLY casts something to
        # a wire dtype (assignment or not — `return q.astype(jnp.int8)`
        # counts; a cast inside a nested def marks only the nested def,
        # which is checked on its own — the outer function's scale
        # names must not be judged by its helper's wire)
        if not any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype" and node.args
            and ctx.enclosing_function(node) is fn
            and dataflow.dtype_literal(node.args[0], env) in _WIRE_DTYPES
            for node in ast.walk(fn)
        ):
            return
        for node in self._assigns(ctx, fn):
            name = node.targets[0].id.lower()
            d = _cast_dtype(node.value, env)
            if d is None:
                continue
            size = dataflow.itemsize(d)
            if "scale" in name and size is not None and size < 4:
                yield self.finding(
                    ctx, node,
                    f"quantization scale `{node.targets[0].id}` created "
                    f"as {d} in a quantized-sync function: scales must "
                    "stay float32 — every dequantize multiplies by them, "
                    "so a rounded scale injects a per-block bias the "
                    "error-feedback residual cannot observe")
            elif "resid" in name and d in _WIRE_DTYPES:
                yield self.finding(
                    ctx, node,
                    f"error-feedback residual `{node.targets[0].id}` "
                    f"created as the wire dtype {d}: the residual exists "
                    "to carry the part of the gradient the wire could "
                    "NOT represent — storing it at wire width re-rounds "
                    "it away; use the bucket's storage dtype")


#: identifier substrings that mark a KV-cache buffer (the decode
#: path's paged pools) — the APX306 scope guard
_KV_CACHE_NAMES = ("kv", "cache", "pool")


class KvCacheReadDtypeMismatch(Rule):
    """APX306: a KV-cache-named buffer provably NARROWER than the
    ``preferred_element_type`` of a dot it feeds, with no explicit
    widen at the read."""

    rule_id = "APX306"
    severity = "error"
    fix_hint = ("widen the cache read explicitly at the seam "
                "(k = k_pool[...].astype(jnp.float32), or .astype the "
                "dot operand) — the narrow storage dtype is a deliberate "
                "HBM trade, and the widen point must be visible where "
                "the accumulator contract is declared")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for info in ctx.functions.values():
            if isinstance(info.node, ast.Lambda):
                continue
            yield from self._check_fn(ctx, info.node)

    def _check_fn(self, ctx: ModuleContext, fn: ast.AST) -> Iterator[Finding]:
        env = dataflow.dtype_env(ctx, fn)
        caches: Dict[str, str] = {}
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and ctx.enclosing_function(node) is fn):
                continue
            name = node.targets[0].id
            if not any(m in name.lower() for m in _KV_CACHE_NAMES):
                continue
            d = _cast_dtype(node.value, env)
            if d is not None:
                caches[name] = d
        if not caches:
            return
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and last_name(call.func) in _DOT_NAMES):
                continue
            pref = None
            for kw in call.keywords:
                if kw.arg == "preferred_element_type":
                    pref = dataflow.dtype_literal(kw.value, env)
            pref_size = dataflow.itemsize(pref)
            if pref_size is None:
                continue
            for arg in call.args[:2]:
                hit = self._narrow_cache_operand(arg, caches, env, pref_size)
                if hit is not None:
                    name, d = hit
                    yield self.finding(
                        ctx, call,
                        f"KV-cache buffer `{name}` is stored as {d} but "
                        f"feeds a dot with preferred_element_type={pref} "
                        f"without an explicit widen at the read: the "
                        f"narrow->wide conversion point is invisible, and "
                        f"a backend that honors the operand dtype over "
                        f"the accumulator request loses the precision "
                        f"the cache's attention contract promises")

    @staticmethod
    def _narrow_cache_operand(arg: ast.AST, caches: Dict[str, str],
                              env: Dict[str, str],
                              pref_size: int) -> Optional[Tuple[str, str]]:
        """(cache_name, dtype) when ``arg`` reads a tracked narrow
        cache without widening; None otherwise.  An ``astype`` wrapper
        resolving to >= the preferred width is the explicit widen."""
        if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute) \
                and arg.func.attr == "astype" and arg.args:
            d = dataflow.dtype_literal(arg.args[0], env)
            size = dataflow.itemsize(d)
            if size is None or size >= pref_size:
                # an explicit cast sits at the read: either it provably
                # widens, or its dtype is unresolvable (a parameter, a
                # config attribute) — the intent is SPELLED, and the
                # quiet-when-unprovable convention applies.  Only a
                # provably-NARROW explicit cast still flags.
                return None
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in caches:
                d = caches[sub.id]
                size = dataflow.itemsize(d)
                if size is not None and size < pref_size:
                    return (sub.id, d)
        return None


class Fp32ConstantInBf16Path(Rule):
    """APX402: materialized fp32 array meets an explicit bf16 cast in
    one arithmetic op — promotion upcasts the bf16 side."""

    rule_id = "APX402"
    severity = "warning"
    fix_hint = ("build the constant in the compute dtype (dtype=x.dtype "
                "or the config's compute_dtype) so promotion cannot "
                "silently upcast the bf16 operand to fp32")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.BinOp):
                continue
            sides = (node.left, node.right)
            if any(self._is_bf16_cast(s) for s in sides) \
                    and any(self._is_f32_factory(s) for s in sides):
                yield self.finding(
                    ctx, node,
                    "fp32-materialized constant combined with an "
                    "explicitly bf16-cast operand: dtype promotion "
                    "upcasts the whole bf16 tensor to fp32, doubling "
                    "its HBM traffic in a path someone already paid to "
                    "keep in bf16")

    @staticmethod
    def _is_bf16_cast(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and any("bfloat16" == (last_name(a) or "")
                        or (isinstance(a, ast.Constant)
                            and a.value == "bfloat16")
                        for a in node.args))

    @staticmethod
    def _is_f32_factory(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if last_name(node.func) not in _F32_FACTORIES:
            return False
        for kw in node.keywords:
            if kw.arg == "dtype" and (last_name(kw.value) or "") == "float32":
                return True
        return False
