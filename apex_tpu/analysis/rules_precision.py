"""Precision and indexing hygiene rules.

- APX401: unclamped ``take_along_axis`` (the ``gpt.py:447`` class).
  Under jit, JAX's gather clamps *some* out-of-bounds reads and fills
  others depending on mode and sign — a negative id silently WRAPS.
  Three loss-head implementations that disagree on out-of-range ids
  diverge only on corrupt data, the hardest moment to debug; one
  explicit ``jnp.clip`` pins one semantic everywhere.
- APX402: an explicitly-materialized fp32 constant meeting a bf16
  operand.  Binary-op promotion silently upcasts the whole bf16 tensor
  to fp32 — doubling its HBM traffic in a compute path someone already
  paid to keep in bf16.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, dotted_name, last_name,
)

_F32_FACTORIES = {"array", "asarray", "full", "ones", "zeros", "arange",
                  "linspace", "full_like", "ones_like", "zeros_like"}
_BINOPS = (ast.BinOp,)


def _contains_clip(node: ast.AST, clipped: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and last_name(sub.func) == "clip":
            return True
        if isinstance(sub, ast.Name) and sub.id in clipped:
            return True
    return False


def _clipped_names(fn: ast.AST) -> Set[str]:
    """Names assigned (directly or through arithmetic on a clipped
    name) from a ``clip`` call anywhere in the function."""
    clipped: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if name not in clipped \
                        and _contains_clip(node.value, clipped):
                    clipped.add(name)
                    changed = True
    return clipped


class UnclampedTakeAlongAxis(Rule):
    """APX401: take_along_axis with indices that are never clamped."""

    rule_id = "APX401"
    severity = "error"
    fix_hint = ("clamp the ids first (t = jnp.clip(t, 0, V - 1)) or pass "
                "an explicit mode=; all loss-head paths must share one "
                "out-of-range semantic")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and last_name(node.func) == "take_along_axis"):
                continue
            if any(kw.arg == "mode" for kw in node.keywords):
                continue  # explicit out-of-bounds semantic chosen
            indices = None
            for kw in node.keywords:
                if kw.arg == "indices":
                    indices = kw.value
            if indices is None and len(node.args) > 1:
                indices = node.args[1]
            if indices is None:
                continue
            fn = ctx.enclosing_function(node)
            clipped = _clipped_names(fn) if fn is not None else set()
            if _contains_clip(indices, clipped):
                continue
            yield self.finding(
                ctx, node,
                "take_along_axis with unclamped indices: under jit a "
                "negative id silently WRAPS and a past-end id is "
                "clamped/filled depending on gather mode — corrupt "
                "targets produce plausible-looking wrong losses instead "
                "of failing")


class Fp32ConstantInBf16Path(Rule):
    """APX402: materialized fp32 array meets an explicit bf16 cast in
    one arithmetic op — promotion upcasts the bf16 side."""

    rule_id = "APX402"
    severity = "warning"
    fix_hint = ("build the constant in the compute dtype (dtype=x.dtype "
                "or the config's compute_dtype) so promotion cannot "
                "silently upcast the bf16 operand to fp32")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.BinOp):
                continue
            sides = (node.left, node.right)
            if any(self._is_bf16_cast(s) for s in sides) \
                    and any(self._is_f32_factory(s) for s in sides):
                yield self.finding(
                    ctx, node,
                    "fp32-materialized constant combined with an "
                    "explicitly bf16-cast operand: dtype promotion "
                    "upcasts the whole bf16 tensor to fp32, doubling "
                    "its HBM traffic in a path someone already paid to "
                    "keep in bf16")

    @staticmethod
    def _is_bf16_cast(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and any("bfloat16" == (last_name(a) or "")
                        or (isinstance(a, ast.Constant)
                            and a.value == "bfloat16")
                        for a in node.args))

    @staticmethod
    def _is_f32_factory(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if last_name(node.func) not in _F32_FACTORIES:
            return False
        for kw in node.keywords:
            if kw.arg == "dtype" and (last_name(kw.value) or "") == "float32":
                return True
        return False
