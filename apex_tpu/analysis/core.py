"""Rule engine for the apex_tpu static analyzer.

Pure-stdlib ``ast`` analysis — importing this package must never import
jax (the analyzer has to run in a crippled CI container, in a pre-commit
hook, and against a tree that does not even import).  Each rule is a
class with an ``id``, ``severity``, and ``fix_hint`` that visits one
:class:`ModuleContext` and yields :class:`Finding`s; the contexts carry
the per-module facts every rule family needs — above all the
*traced-function index*, the set of functions whose bodies execute at
JAX trace time (jitted, ``custom_vjp``'d, passed to ``pl.pallas_call``
or a ``lax`` control-flow combinator, or reachable from one of those
through the module-local call graph).

Why trace-reachability is the load-bearing fact: Apex's CUDA extensions
fail at build time, but this rebuild's failure modes are deferred —
host state read during tracing is frozen into the jaxpr and silently
stale forever after.  The index turns "is this ``os.environ.get`` a
bug?" into a static question.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

SEVERITIES = ("error", "warning", "info")

# Entry points whose function-valued arguments are traced.  Last dotted
# component only: ``jax.jit``, ``jit``, and ``api.jit`` all match — a
# linter that misses ``from jax import jit`` is worse than one that
# over-asks, and the baseline absorbs deliberate cases.
TRACE_ENTRYPOINTS: Set[str] = {
    "jit", "pallas_call", "custom_vjp", "custom_jvp", "defvjp", "defjvp",
    "checkpoint", "remat", "grad", "value_and_grad", "vmap", "pmap",
    "shard_map", "xmap", "scan", "while_loop", "fori_loop", "cond",
    "switch", "named_call", "eval_shape",
}

# Decorators that make the decorated function traced.
TRACE_DECORATORS: Set[str] = {
    "jit", "custom_vjp", "custom_jvp", "checkpoint", "remat", "vmap",
    "pmap", "shard_map",
}

# Default collective-axis registry, used only when no parallel_state.py
# is found among the scanned roots (its ``*_AXIS`` constants are the
# source of truth; see discover_axis_registry).
DEFAULT_AXES: Tuple[str, ...] = ("dp", "pp", "cp", "tp", "dcn")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    symbol: str          # enclosing function qualname, or "<module>"
    message: str
    fix_hint: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.severity}: {self.message}\n    fix: {self.fix_hint}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """One checkable invariant.  Subclasses set the class attributes and
    implement :meth:`check`."""

    rule_id: str = "APX000"
    severity: str = "error"
    fix_hint: str = ""

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST,
                message: str, fix_hint: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.rule_id, severity=self.severity, path=ctx.path,
            line=getattr(node, "lineno", 0), col=getattr(node, "col_offset", 0),
            symbol=ctx.enclosing_qualname(node),
            message=message, fix_hint=fix_hint or self.fix_hint)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_name(node: ast.AST) -> Optional[str]:
    """Final dotted component of a Name/Attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_partial(call: ast.Call) -> bool:
    return last_name(call.func) == "partial"


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                 # FunctionDef | AsyncFunctionDef | Lambda
    qualname: str
    params: Set[str]


class ModuleContext:
    """Everything the rules need to know about one parsed module."""

    def __init__(self, path: str, source: str, tree: ast.Module,
                 axis_registry: Set[str], module_name: str = "",
                 is_package: bool = False):
        self.path = path
        self.source = source
        self.tree = tree
        self.axis_registry = axis_registry
        #: dotted module name (``apex_tpu.ops.fused_ce``) — what the
        #: cross-module linker resolves imports against; empty for
        #: single-file analysis (no linking possible)
        self.module_name = module_name
        #: True for a package ``__init__.py``: its level-1 relative
        #: imports resolve against the package ITSELF, not its parent
        self.is_package = is_package
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.functions: Dict[str, FunctionInfo] = {}
        self._collect_functions()
        self._collect_imports()
        # qualname -> human-readable reason the function is traced
        self.traced: Dict[str, str] = {}
        # Lambda node -> reason (lambdas have no qualname; tracked by
        # identity so `jax.jit(lambda x: ...)` bodies are still scanned)
        self.traced_lambdas: Dict[ast.Lambda, str] = {}
        self._build_traced_index()

    # -------------------------------------------------------- structure
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self._parents.get(cur)
        return None

    def enclosing_qualname(self, node: ast.AST) -> str:
        fn = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else \
            self.enclosing_function(node)
        while fn is not None:
            for info in self.functions.values():
                if info.node is fn:
                    return info.qualname
            fn = self.enclosing_function(fn)
        return "<module>"

    def _collect_functions(self) -> None:
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    params = {a.arg for a in (
                        child.args.posonlyargs + child.args.args +
                        child.args.kwonlyargs)}
                    self.functions[qn] = FunctionInfo(child, qn, params)
                    visit(child, qn + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)

        visit(self.tree, "")

    def _collect_imports(self) -> None:
        """Local-name → module bindings, for the cross-module linker.
        Function-local imports count too (the fused_ce shape: ``from
        ...fused_ce_pallas import fused_ce_fwd_pallas`` inside the
        traced closure)."""
        self.import_aliases: Dict[str, str] = {}      # alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, attr)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.import_aliases[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    parts = self.module_name.split(".") if self.module_name \
                        else []
                    # level=1 in pkg/mod.py → pkg; in pkg/__init__.py →
                    # pkg itself (python relative-import semantics)
                    keep = len(parts) - node.level + (1 if self.is_package
                                                      else 0)
                    base = ".".join(parts[: max(0, keep)])
                    mod = f"{base}.{node.module}" if node.module and base \
                        else (node.module or base)
                else:
                    mod = node.module or ""
                for a in node.names:
                    self.from_imports[a.asname or a.name] = (mod, a.name)

    def cross_module_calls(self):
        """``(module, func_name, reason)`` for every call inside traced
        code that resolves through this module's imports instead of the
        module-local call graph — the seeds the cross-module linker
        plants into OTHER modules' traced indexes."""
        out: List[Tuple[str, str, str]] = []
        src = self.module_name or self.path

        def scan(body_node, scope, reason):
            for sub in ast.walk(body_node):
                if not isinstance(sub, ast.Call):
                    continue
                d = dotted_name(sub.func)
                if d is None:
                    continue
                parts = d.split(".")
                if len(parts) == 1:
                    if self.resolve_function(parts[0], scope) is not None:
                        continue  # module-local binding shadows the import
                    tgt = self.from_imports.get(parts[0])
                    if tgt is not None:
                        out.append((*self._from_target(tgt), reason))
                    continue
                head, attr = parts[:-1], parts[-1]
                if head[0] in self.import_aliases:
                    mod = ".".join([self.import_aliases[head[0]]] + head[1:])
                elif head[0] in self.from_imports:
                    m0, a0 = self.from_imports[head[0]]
                    mod = ".".join([f"{m0}.{a0}" if m0 else a0] + head[1:])
                else:
                    # plain `import a.b.c` binds `a`; the dotted call
                    # carries the full module path already
                    mod = ".".join(head)
                out.append((mod, attr, reason))

        for qn in list(self.traced):
            info = self.functions.get(qn)
            if info is not None:
                scan(info.node, qn,
                     f"called (cross-module) from traced {src}:{qn}")
        for lam in list(self.traced_lambdas):
            scope = self.enclosing_qualname(lam)
            scan(lam, "" if scope == "<module>" else scope,
                 f"called (cross-module) from a traced lambda in {src}")
        return out

    @staticmethod
    def _from_target(tgt: Tuple[str, str]) -> Tuple[str, str]:
        mod, attr = tgt
        return (mod, attr) if mod else (attr, "")

    def mark_external(self, qualname: str, reason: str) -> bool:
        """Seed a function as traced from ANOTHER module's call graph
        and re-run local propagation; True if anything new was marked."""
        if qualname not in self.functions or qualname in self.traced:
            return False
        self.traced[qualname] = reason
        self._propagate()
        return True

    def resolve_function(self, name: str,
                         from_qualname: str = "") -> Optional[str]:
        """Bare name -> qualname: innermost lexical match first."""
        scope = from_qualname
        while True:
            candidate = f"{scope}.{name}" if scope else name
            if candidate in self.functions:
                return candidate
            if "." not in scope:
                break
            scope = scope.rsplit(".", 1)[0]
        return name if name in self.functions else None

    # ---------------------------------------------------- traced index
    def _function_args_of_call(self, call: ast.Call) -> Iterator[ast.AST]:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            yield arg

    def _mark(self, qualname: Optional[str], reason: str) -> None:
        if qualname is not None and qualname not in self.traced:
            self.traced[qualname] = reason

    def _mark_value(self, value: ast.AST, reason: str, scope: str,
                    aliases: Dict[str, str]) -> None:
        """Mark the function a call argument refers to: a bare Name, a
        ``partial(f, ...)`` wrapper, or a name previously aliased to
        either (``kernel = functools.partial(_fwd_kernel, ...)``)."""
        if isinstance(value, ast.Lambda):
            self.traced_lambdas.setdefault(value, reason)
        elif isinstance(value, ast.Name):
            target = aliases.get(value.id, value.id)
            self._mark(self.resolve_function(target, scope), reason)
        elif isinstance(value, ast.Call) and _is_partial(value) and value.args:
            inner = value.args[0]
            if isinstance(inner, ast.Name):
                target = aliases.get(inner.id, inner.id)
                self._mark(self.resolve_function(target, scope), reason)
        elif isinstance(value, ast.Attribute):
            name = last_name(value)
            if name:
                self._mark(self.resolve_function(name, scope), reason)

    def _build_traced_index(self) -> None:
        # 1. decorator seeds
        for qn, info in self.functions.items():
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = last_name(target)
                if name in TRACE_DECORATORS:
                    self._mark(qn, f"decorated @{name}")
                elif name == "partial" and isinstance(dec, ast.Call) and dec.args:
                    inner = last_name(dec.args[0])
                    if inner in TRACE_DECORATORS:
                        self._mark(qn, f"decorated @partial({inner}, ...)")

        # 2. alias map (name -> function name via `x = f` / `x = partial(f,..)`)
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                tgt = node.targets[0].id
                if isinstance(node.value, ast.Name):
                    aliases[tgt] = node.value.id
                elif isinstance(node.value, ast.Call) \
                        and _is_partial(node.value) and node.value.args \
                        and isinstance(node.value.args[0], ast.Name):
                    aliases[tgt] = node.value.args[0].id

        # 3. call-site seeds: f passed to jit/pallas_call/scan/defvjp/...
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            entry = last_name(node.func)
            if entry not in TRACE_ENTRYPOINTS:
                continue
            scope = self.enclosing_qualname(node)
            scope = "" if scope == "<module>" else scope
            for arg in self._function_args_of_call(node):
                self._mark_value(arg, f"passed to {entry}", scope, aliases)

        # 4. fixpoint propagation: lexical nesting + module-local calls
        self._propagate()

    def _propagate(self) -> None:
        """The traced-index fixpoint (lexical nesting + module-local
        calls) — separated from seeding so the cross-module linker can
        re-run it after planting external seeds."""
        changed = True
        while changed:
            changed = False
            for lam, reason in list(self.traced_lambdas.items()):
                scope = self.enclosing_qualname(lam)
                scope = "" if scope == "<module>" else scope
                for sub in ast.walk(lam.body):
                    if isinstance(sub, ast.Call):
                        callee = last_name(sub.func)
                        resolved = callee and self.resolve_function(
                            callee, scope)
                        if resolved and resolved not in self.traced:
                            self.traced[resolved] = \
                                f"called from traced lambda ({reason})"
                            changed = True
            for qn in list(self.traced):
                reason = self.traced[qn]
                info = self.functions.get(qn)
                if info is None:
                    continue
                # nested defs run under the same trace
                for other_qn in self.functions:
                    if other_qn.startswith(qn + ".") \
                            and other_qn not in self.traced:
                        self.traced[other_qn] = f"nested in traced {qn}"
                        changed = True
                # module-local callees are traced too
                for sub in ast.walk(info.node):
                    if isinstance(sub, ast.Call):
                        callee = last_name(sub.func)
                        if callee is None:
                            continue
                        resolved = self.resolve_function(callee, qn)
                        if resolved is not None \
                                and resolved not in self.traced:
                            self.traced[resolved] = \
                                f"called from traced {qn} ({reason})"
                            changed = True

    def traced_reason(self, node: ast.AST) -> Optional[str]:
        """Why the function (or lambda) enclosing ``node`` executes at
        trace time, or None if it does not (as far as this module
        shows).  Walks the whole lexical chain so code nested anywhere
        under a traced def/lambda is covered."""
        fn = self.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, ast.Lambda):
                if fn in self.traced_lambdas:
                    return self.traced_lambdas[fn]
            else:
                qn = self.enclosing_qualname(fn)
                if qn in self.traced:
                    return self.traced[qn]
            fn = self.enclosing_function(fn)
        return None

    def mentions(self, *needles: str) -> bool:
        return any(n in self.source for n in needles)


# ------------------------------------------------------------------ engine
def discover_axis_registry(paths: Iterable[str]) -> Set[str]:
    """Mesh axis names from ``*_AXIS = "..."`` constants in any
    ``parallel_state.py`` under the scanned roots — the same constants
    ``initialize_model_parallel`` builds the Mesh from, so the linter
    and the runtime cannot drift.  Falls back to the well-known set."""
    axes: Set[str] = set()
    for ps in _find_files(paths, basename="parallel_state.py"):
        try:
            tree = ast.parse(open(ps, encoding="utf-8").read())
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name) and tgt.id.endswith("_AXIS") \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    axes.add(node.value.value)
    return axes or set(DEFAULT_AXES)


def _find_files(paths: Iterable[str], basename: Optional[str] = None,
                suffix: str = ".py") -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(suffix) and (basename is None
                                       or os.path.basename(p) == basename):
                out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(suffix) and (basename is None
                                               or f == basename):
                        out.append(os.path.join(root, f))
    return out


def _load_module(path: str, display: str, axis_registry: Set[str],
                 module_name: str = "", is_package: bool = False):
    """Parse one file into a :class:`ModuleContext`, or the APX000
    :class:`Finding` describing why it could not be parsed — the ONE
    read/parse/error shape both entry points share."""
    try:
        source = open(path, encoding="utf-8").read()
    except OSError as e:
        return Finding("APX000", "error", display, 0, 0,
                       "<module>", f"unreadable: {e}", "fix file access")
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return Finding("APX000", "error", display,
                       e.lineno or 0, e.offset or 0, "<module>",
                       f"syntax error: {e.msg}", "fix the syntax error")
    return ModuleContext(display, source, tree, axis_registry,
                         module_name=module_name, is_package=is_package)


def analyze_file(path: str, rules: Iterable[Rule], axis_registry: Set[str],
                 display_path: Optional[str] = None) -> List[Finding]:
    loaded = _load_module(path, display_path or path, axis_registry)
    if isinstance(loaded, Finding):
        return [loaded]
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(loaded))
    return findings


def _module_name_for(file: str, root: str) -> str:
    """Dotted module name of ``file`` as imported from ``root``'s
    parent: a package root (dir with ``__init__.py``) contributes its
    own name (``apex_tpu/ops/x.py`` scanned via root ``apex_tpu`` →
    ``apex_tpu.ops.x``); a bare dir's files are top-level modules; a
    file root is its own module (``bench.py`` → ``bench``)."""
    if os.path.isfile(root):
        rel = os.path.basename(file)
    else:
        rel = os.path.relpath(file, root)
        if os.path.isfile(os.path.join(root, "__init__.py")):
            rel = os.path.join(
                os.path.basename(os.path.abspath(root.rstrip(os.sep))), rel)
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _link_cross_module(ctxs: Dict[str, Optional["ModuleContext"]]) -> None:
    """Global traced-reachability fixpoint: a function called from a
    traced function in ANOTHER module is traced too (the per-module
    index misses exactly this — e.g. ``fused_ce_pallas.
    _default_dot_dtype``'s env read reached from ``fused_ce._fwd``).
    ``None`` entries mark ambiguous module names (two scanned files
    claimed the same dotted name) — never linked through, so a seed
    cannot land in the wrong file.  Each module's call list is
    recomputed only when its traced set grew (``cross_module_calls``
    walks every traced body — a per-round full rescan would be
    O(rounds × corpus))."""
    live = [c for c in ctxs.values() if c is not None]
    memo: Dict[int, Tuple[int, list]] = {}
    changed = True
    while changed:
        changed = False
        for ctx in live:
            n = len(ctx.traced) + len(ctx.traced_lambdas)
            if memo.get(id(ctx), (-1,))[0] != n:
                memo[id(ctx)] = (n, ctx.cross_module_calls())
            for mod, attr, reason in memo[id(ctx)][1]:
                target = ctxs.get(mod)
                if target is None or target is ctx:
                    continue
                if target.mark_external(attr, reason):
                    changed = True


def _load_module_task(args):
    """Process-pool worker: parse one file AND build its traced index
    and axis-scope index (the per-file fixpoints are the expensive
    half of a scan) so ``--jobs N`` parallelizes real work, not just
    ``ast.parse``.  Top-level so it pickles under the spawn start
    method."""
    path, display, registry, module_name, is_package = args
    loaded = _load_module(path, display, set(registry),
                          module_name=module_name, is_package=is_package)
    if not isinstance(loaded, Finding):
        from apex_tpu.analysis import dataflow

        dataflow.scope_index(loaded)
        dataflow.taint_index(loaded)
        dataflow.thread_index(loaded)
    return loaded


def _load_all(tasks, jobs: int):
    """The per-file parse/index pass, serial or process-parallel.  The
    parallel path degrades to serial on ANY pool failure (a module
    whose AST defeats pickling, a sandbox without multiprocessing) —
    ``--jobs`` may never change results, only wall time."""
    if jobs <= 1 or len(tasks) <= 1:
        return [_load_module_task(t) for t in tasks]
    try:
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(max_workers=jobs) as pool:
            return list(pool.map(_load_module_task, tasks))
    except Exception:
        return [_load_module_task(t) for t in tasks]


def analyze_paths(paths: Iterable[str], rules: Iterable[Rule],
                  axis_registry: Optional[Set[str]] = None,
                  rel_to: Optional[str] = None, jobs: int = 1,
                  timings: Optional[Dict[str, float]] = None
                  ) -> List[Finding]:
    """Run every rule over every ``*.py`` under ``paths``; findings are
    sorted by (path, line, rule) for stable output and baselines.

    Unlike :func:`analyze_file`, this multi-file entry point links the
    per-module traced indexes across modules first (import-resolved
    call-graph reachability), so trace-time hazards in helpers reached
    only from another module's jitted code are still flagged.

    ``jobs``: parallelize the per-file parse + index build across N
    worker processes (the module linking and rule checks stay
    single-pass in this process — they need the full module set).
    ``timings``: pass a dict to collect per-rule wall seconds (the
    CLI's ``--timing``); keys are rule ids plus ``"<load>"`` and
    ``"<link>"`` for the two shared phases."""
    import time as _time

    paths = list(paths)
    registry = axis_registry if axis_registry is not None \
        else discover_axis_registry(paths)
    rules = list(rules)
    findings: List[Finding] = []
    ctxs: Dict[str, Optional[ModuleContext]] = {}
    ordered: List[ModuleContext] = []
    tasks = []
    for root in paths:
        for f in _find_files([root]):
            display = os.path.relpath(f, rel_to) if rel_to else f
            tasks.append((f, display, tuple(sorted(registry)),
                          _module_name_for(f, root),
                          os.path.basename(f) == "__init__.py"))
    t0 = _time.monotonic()
    for loaded in _load_all(tasks, jobs):
        if isinstance(loaded, Finding):
            findings.append(loaded)
            continue
        if loaded.module_name in ctxs:
            # two scanned files claim one dotted name (e.g. utils.py
            # under two bare roots): linking through the name would
            # plant seeds in whichever file happened to win — mark
            # ambiguous and never link through it
            ctxs[loaded.module_name] = None
        else:
            ctxs[loaded.module_name] = loaded
        ordered.append(loaded)
    if timings is not None:
        timings["<load>"] = _time.monotonic() - t0
    t0 = _time.monotonic()
    _link_cross_module(ctxs)
    # the axis-scope dataflow runs its own cross-module fixpoint so the
    # collective rules see shard_map wrappers that live in other files
    # (imported here, not at module top: dataflow imports core)
    from apex_tpu.analysis import dataflow
    dataflow.link_axis_scopes(ctxs)
    # ... and the host-divergence taint lattice runs ITS cross-module
    # fixpoint (imported taint-returning helpers, taint cycles)
    dataflow.link_taint(ctxs)
    # ... and the thread-reachability index links thread targets and
    # on_*-callback seams handed across module boundaries
    dataflow.link_threads(ctxs)
    if timings is not None:
        timings["<link>"] = _time.monotonic() - t0
    for rule in rules:
        t0 = _time.monotonic()
        for ctx in ordered:
            findings.extend(rule.check(ctx))
        if timings is not None:
            timings[rule.rule_id] = timings.get(rule.rule_id, 0.0) \
                + _time.monotonic() - t0
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings
