"""Multi-process divergence rules (the ``registry_engaged`` class,
scaled into an analyzer tier).

One pod, N processes, ONE SPMD program: every host must lower the same
computation in the same order, or the first mismatched collective
wedges every device with no error — the deadlock the watchdog can only
report after the fact.  The repo has hit this class three times by
hand (the per-process kernel degrade ``registry_engaged`` disengages,
rank-gated goodput accounting, per-rank elastic paths); these rules
prove it absent statically, driven by the host-divergence taint
lattice (``dataflow.taint_reason``: ``process_index``/
``process_count``, env/hostname/clock/RNG/filesystem reads, and
values assigned under rank-divergent branches).

- **APX209**: a rank-divergent predicate guards the LAUNCH of a traced
  computation that reaches a registered-axis collective — the static
  deadlock proof: processes where the predicate differs skip the
  launch while their peers block in the collective forever.  Quiet
  when both branches launch the SAME traced functions (a uniform
  program with divergent inputs is fine).
- **APX210**: a rank-divergent value flows into something that SHAPES
  the compiled program — a jit static argument, ``Mesh`` construction,
  or a bucketing/sync plan — so peers compile DIFFERENT programs from
  identical source; the divergence surfaces as a wedge or a sharding
  mismatch, never at the sink.
- **APX211**: a rank-divergent predicate gates engine/fallback/kernel
  dispatch in a module that is multi-process aware (mentions
  ``process_count``) — the generalized ``registry_engaged`` invariant:
  a per-process impl choice lowers divergent collective programs
  across the pod.

Acquittal seam (all three rules): a call to ``assert_uniform``/
``check_uniform``/``register_uniform``
(:mod:`apex_tpu.resilience.uniformity`) in the enclosing function pins
the decision to the runtime uniformity contract — the divergence is
then detected loudly at startup/cadence instead of wedging, which is
exactly the remediation these rules' fix hints prescribe.

Known limits (documented, deliberate): launch reachability is
module-local (a collective hidden behind an import stays quiet —
cross-module taint is linked, cross-module CALL GRAPHS for the
collective walk are not); the early-return spelling (``if rank: return``
before an unconditional launch) is control divergence this pass does
not model.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from apex_tpu.analysis import dataflow
from apex_tpu.analysis.core import (
    TRACE_ENTRYPOINTS, Finding, ModuleContext, Rule, dotted_name,
    last_name,
)
from apex_tpu.analysis.rules_collectives import _COLLECTIVES, _axis_literals

#: Calls that pin a host decision to the runtime uniformity contract —
#: seeing one in the enclosing function acquits the divergence rules.
_UNIFORMITY_SEAMS = {"assert_uniform", "check_uniform", "register_uniform"}

#: jit spellings whose static args shape the compiled program.
_JIT_NAMES = {"jit", "pjit"}

#: Bucketing/sync plan builders whose inputs shape the collective
#: program (``contrib.optimizers``: per-bucket reduce-scatters).
_PLAN_BUILDERS = {"plan_of", "plan_of_shapes", "hierarchical_plan"}

#: Keyword names that size a plan wherever they appear — a divergent
#: cap/world splits buckets differently on one rank.
_PLAN_SHAPE_KWARGS = {"cap_bytes", "bucket_cap_mb", "world_size",
                      "shard_pad"}

#: Engine/impl dispatch markers for APX211 (lowercased substring match
#: on the dispatched callable's dotted name).
_DISPATCH_MARKERS = ("engine", "fallback", "kernel", "impl", "pallas")


def _acquitted(ctx: ModuleContext, node: ast.AST) -> bool:
    scope = ctx.enclosing_function(node) or ctx.tree
    for sub in ast.walk(scope):
        if isinstance(sub, ast.Call) \
                and last_name(sub.func) in _UNIFORMITY_SEAMS:
            return True
    return False


def _reaches_registered_collective(ctx: ModuleContext, qn: str,
                                   seen: Set[str]) -> bool:
    """Module-local transitive walk: does ``qn``'s body (or a local
    callee's) invoke a collective over a registered axis literal?"""
    if qn in seen:
        return False
    seen.add(qn)
    info = ctx.functions.get(qn)
    if info is None:
        return False
    for sub in ast.walk(info.node):
        if not isinstance(sub, ast.Call):
            continue
        name = last_name(sub.func)
        if name in _COLLECTIVES:
            for _node, lit in _axis_literals(sub, _COLLECTIVES[name]):
                if lit in ctx.axis_registry:
                    return True
            continue
        if name is None:
            continue
        resolved = ctx.resolve_function(name, qn)
        if resolved is not None \
                and _reaches_registered_collective(ctx, resolved, seen):
            return True
    return False


def _traced_target(ctx: ModuleContext, call: ast.Call) -> Optional[str]:
    """The traced function a host call site launches: a direct call to
    a traced def, ``jit(f)(...)``/``shard_map(f, ...)(...)`` inline, or
    a name value-aliased to such an entry call (``step = jit(f)``)."""
    func = call.func
    if isinstance(func, ast.Call) \
            and last_name(func.func) in TRACE_ENTRYPOINTS and func.args:
        func = func.args[0]
    name = last_name(func) if func is not None else None
    if name is None:
        return None
    val = dataflow.value_aliases(ctx).get(name)
    if isinstance(val, ast.Call) \
            and last_name(val.func) in TRACE_ENTRYPOINTS and val.args:
        inner = last_name(val.args[0])
        if inner is not None:
            name = inner
    scope = ctx.enclosing_qualname(call)
    scope = "" if scope == "<module>" else scope
    idx = dataflow.scope_index(ctx)
    qn = ctx.resolve_function(idx._fn_aliases.get(name, name), scope)
    if qn is None or qn not in ctx.traced:
        return None
    return qn


def _collective_launches(ctx: ModuleContext,
                         stmts: List[ast.stmt]) -> Dict[str, ast.Call]:
    """traced-qualname -> first launching call, for launches under the
    given statements that reach a registered-axis collective."""
    out: Dict[str, ast.Call] = {}
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            qn = _traced_target(ctx, sub)
            if qn is not None and qn not in out \
                    and _reaches_registered_collective(ctx, qn, set()):
                out[qn] = sub
    return out


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _following_stmts(ctx: ModuleContext, node: ast.If) -> List[ast.stmt]:
    parent = ctx.parent(node)
    for field in ("body", "orelse", "finalbody"):
        stmts = getattr(parent, field, None)
        if isinstance(stmts, list) and node in stmts:
            i = stmts.index(node)
            return stmts[i + 1:]
    return []


def _divergent_launch(ctx: ModuleContext,
                      node: ast.If) -> Optional[Tuple[str, ast.Call]]:
    """The (qualname, call) a divergent branch launches: the taken and
    not-taken paths' collective-launch SETS differ, so one rank's
    program contains a collective its peer's does not.  A branch that
    does not terminate (return/raise/continue/break) falls through to
    the statements after the If, so ``if p: return step(x)`` followed
    by ``return step(y)`` compares {step} against {step} — a uniform
    program with divergent inputs — and stays quiet.  Launch COUNTS
    are not compared (a documented limit): the sets are by traced
    qualname."""
    body = _collective_launches(ctx, node.body)
    orelse = _collective_launches(ctx, node.orelse)
    following = _collective_launches(ctx, _following_stmts(ctx, node))
    taken = dict(body) if _terminates(node.body) \
        else {**following, **body}
    not_taken = dict(orelse) if node.orelse and _terminates(node.orelse) \
        else {**following, **orelse}
    if set(taken) == set(not_taken):
        return None
    only = {qn: c for qn, c in taken.items() if qn not in not_taken} \
        or {qn: c for qn, c in not_taken.items() if qn not in taken}
    qn = sorted(only)[0]
    return qn, only[qn]


class TaintedPredicateGuardsCollective(Rule):
    """APX209: a rank-divergent predicate guards the launch of a traced
    computation that reaches a registered-axis collective — the static
    pod-deadlock proof.

    ``if jax.process_index() == 0: step(batch)`` launches the
    collective-bearing step on ONE process; its peers' devices block in
    the matching all-reduce forever, with no error, no timeout, no
    stack — the exact wedge the flight recorder can only describe
    post-mortem.  Host code only: inside a trace the predicate is a
    traced value and ``lax.cond`` territory.  Quiet when both branches
    launch the same traced functions, when the predicate is uniform,
    or when the enclosing function pins the decision through
    ``assert_uniform``."""

    rule_id = "APX209"
    severity = "error"
    fix_hint = ("launch the step on every process and branch on a "
                "traced value inside it (lax.cond), or pin the host "
                "decision through apex_tpu.resilience.uniformity."
                "assert_uniform so divergence fails loudly at the seam "
                "instead of wedging in the collective")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if ctx.traced_reason(node) is not None:
                continue
            reason = dataflow.taint_reason(ctx, node.test)
            if reason is None:
                continue
            hit = _divergent_launch(ctx, node)
            if hit is None or _acquitted(ctx, node):
                continue
            qn, _call = hit
            yield self.finding(
                ctx, node.test,
                f"rank-divergent predicate ({reason}) guards the "
                f"launch of traced `{qn}`, which lowers a "
                f"registered-axis collective: processes where the "
                f"predicate differs skip the launch while their peers "
                f"block in the collective — the pod wedges "
                f"device-side with no error")


class TaintedValueShapesCompiledProgram(Rule):
    """APX210: a rank-divergent value flows into something that shapes
    the compiled program — a jit static argument, ``Mesh``
    construction, or a bucketing/sync plan.

    A static arg is baked into the jaxpr: two processes tracing with
    different values compile DIFFERENT programs from identical source,
    and the divergence surfaces as mismatched collective schedules (a
    wedge) or a sharding error far from this line.  Same story for a
    mesh built from per-rank state and for bucket plans whose
    cap/world differs across ranks (per-bucket reduce-scatters change
    COUNT)."""

    rule_id = "APX210"
    severity = "error"
    fix_hint = ("derive the value from replicated config (the same "
                "literal on every process), or gate it through "
                "apex_tpu.resilience.uniformity.assert_uniform so a "
                "divergent rank fails loudly before compiling a "
                "different program")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            for arg_node, sink, reason in self._sink_hits(ctx, node):
                if _acquitted(ctx, node):
                    continue
                yield self.finding(
                    ctx, arg_node,
                    f"rank-divergent value ({reason}) flows into "
                    f"{sink}: each process bakes its own value into "
                    f"the compiled program, so peers lower DIFFERENT "
                    f"programs from identical source — the mismatch "
                    f"surfaces as a pod wedge or sharding error, "
                    f"never here")

    # ------------------------------------------------------------- sinks
    def _sink_hits(self, ctx: ModuleContext, call: ast.Call):
        name = last_name(call.func)
        values = list(call.args) + [kw.value for kw in call.keywords]
        if name in dataflow._MESH_CTORS:
            for v in values:
                r = dataflow.taint_reason(ctx, v)
                if r is not None:
                    yield v, f"`{name}(...)` mesh construction", r
                    return
        if name in _PLAN_BUILDERS:
            for v in values:
                r = dataflow.taint_reason(ctx, v)
                if r is not None:
                    yield v, f"the `{name}(...)` bucket/sync plan", r
                    return
        else:
            for kw in call.keywords:
                if kw.arg in _PLAN_SHAPE_KWARGS:
                    r = dataflow.taint_reason(ctx, kw.value)
                    if r is not None:
                        yield (kw.value,
                               f"plan shape argument `{kw.arg}=`", r)
                        return
        spec = self._static_spec(ctx, call)
        if spec is not None:
            nums, names = spec
            for pos in nums:
                if pos < len(call.args):
                    r = dataflow.taint_reason(ctx, call.args[pos])
                    if r is not None:
                        yield (call.args[pos],
                               f"jit static argument {pos}", r)
                        return
            for kw in call.keywords:
                if kw.arg in names:
                    r = dataflow.taint_reason(ctx, kw.value)
                    if r is not None:
                        yield (kw.value,
                               f"jit static argument `{kw.arg}=`", r)
                        return

    def _static_spec(self, ctx: ModuleContext, call: ast.Call
                     ) -> Optional[Tuple[List[int], List[str]]]:
        """(static_argnums, static_argnames) of the jit the called
        object was built by — inline ``jit(f, static_argnums=...)(..)``,
        a value alias (``step = jit(f, ...)``), or a
        ``@jit``/``@partial(jit, ...)`` decorator on the callee."""
        jit_call = None
        func = call.func
        if isinstance(func, ast.Call) \
                and last_name(func.func) in _JIT_NAMES:
            jit_call = func
        elif isinstance(func, ast.Name):
            val = dataflow.value_aliases(ctx).get(func.id)
            if isinstance(val, ast.Call) \
                    and last_name(val.func) in _JIT_NAMES:
                jit_call = val
            else:
                scope = ctx.enclosing_qualname(call)
                scope = "" if scope == "<module>" else scope
                qn = ctx.resolve_function(func.id, scope)
                info = ctx.functions.get(qn) if qn else None
                for dec in getattr(getattr(info, "node", None),
                                   "decorator_list", []):
                    if not isinstance(dec, ast.Call):
                        continue
                    tgt = last_name(dec.func)
                    if tgt in _JIT_NAMES or (
                            tgt == "partial" and dec.args
                            and last_name(dec.args[0]) in _JIT_NAMES):
                        jit_call = dec
        if jit_call is None:
            return None
        nums: List[int] = []
        names: List[str] = []
        for kw in jit_call.keywords:
            if kw.arg == "static_argnums":
                nums = _int_literals(kw.value)
            elif kw.arg == "static_argnames":
                names = _str_literals(kw.value)
        if not nums and not names:
            return None
        return nums, names


def _int_literals(node: ast.AST) -> List[int]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool):
            out.append(sub.value)
    return out


def _str_literals(node: ast.AST) -> List[str]:
    return [sub.value for sub in ast.walk(node)
            if isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)]


class TaintedEngineDispatchDivergence(Rule):
    """APX211: a rank-divergent predicate gates engine/fallback/kernel
    dispatch in a multi-process-aware module — the ``registry_engaged``
    invariant, generalized.

    A per-process impl choice (env var, clock, rank, filesystem probe)
    lowers one host's fallback program against its peers' kernel
    program; when either side carries collectives the pod wedges, and
    even collective-free divergence silently breaks every A/B
    comparison across the fleet.  Scoped to modules that mention
    ``process_count`` (the multi-process-reachable heuristic: code
    that never thinks about process topology gets APX101's trace-time
    verdict instead); APX209 owns the If when the divergent branch
    itself launches a collective."""

    rule_id = "APX211"
    severity = "error"
    fix_hint = ("thread the impl choice through replicated config "
                "(the registry_engaged pattern: disengage per-process "
                "degradation when process_count() > 1), or pin it "
                "through apex_tpu.resilience.uniformity.assert_uniform "
                "so one divergent rank fails loudly at the seam")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.mentions("process_count"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if ctx.traced_reason(node) is not None:
                continue
            reason = dataflow.taint_reason(ctx, node.test)
            if reason is None:
                continue
            if _divergent_launch(ctx, node) is not None:
                continue  # APX209 owns the collective-launch shape
            site = self._dispatch_site(node.body) \
                or self._dispatch_site(node.orelse)
            if site is None or _acquitted(ctx, node):
                continue
            _sub, label = site
            yield self.finding(
                ctx, node.test,
                f"rank-divergent predicate ({reason}) gates dispatch "
                f"of `{label}` in a multi-process-aware module: each "
                f"process picks its own impl, so peers lower "
                f"divergent SPMD programs — mismatched collective "
                f"schedules wedge the pod device-side")

    @staticmethod
    def _dispatch_site(stmts: List[ast.stmt]
                       ) -> Optional[Tuple[ast.AST, str]]:
        for stmt in stmts:
            for sub in ast.walk(stmt):
                d = None
                if isinstance(sub, ast.Call):
                    d = dotted_name(sub.func)
                elif isinstance(sub, ast.Return) and sub.value is not None:
                    d = dotted_name(sub.value)
                if d is None:
                    continue
                low = d.lower()
                if any(m in low for m in _DISPATCH_MARKERS):
                    return sub, d
        return None
