"""apex_tpu.analysis — JAX/Pallas-aware static linter for TPU hazards.

Catches, before code ever reaches the chip, the failure classes that
are silent and deferred on TPU (each found at least once by a human
reviewer in this repo's history — the rules scale those findings into
machine-checked invariants):

- **APX101/102** trace-time host-state capture and process-global env
  mutation (``rules_trace``) — the ``bench.py:876`` class.
- **APX103** donated-buffer reuse: a ``donate_argnums`` argument read
  after the donating call without a rebind (``rules_donation``) — a
  no-op on CPU, garbage or a deleted-array error on TPU.
- **APX104** non-atomic checkpoint write (``rules_io``): a direct
  ``open(..., "wb")`` on a checkpoint path bypassing the
  ``io.native.atomic_output`` tmp+fsync+rename helper — the
  torn-write class ``io.validate_checkpoint`` exists to detect.
- **APX109** swallowed exception in a recovery path
  (``rules_resilience``): an ``except`` whose body is only
  ``pass``/``...`` inside resilience/io/inference modules — no
  re-raise, no ``log_structured``, no metrics record, so the failure
  is invisible to the supervisor and the postmortem.
- **APX113** retry without backoff (``rules_resilience``): a
  ``while True:`` in the same recovery-path modules whose ``try``
  swallows the failure and re-attempts with no sleep/backoff/wait
  anywhere in the loop — a persistent fault becomes a busy-spin
  against the dependency that needs room to recover (the serving
  fleet's typed ``Overloaded.retry_after_s`` is the paced spelling).
- **APX201/202** collective-axis consistency against the
  ``parallel_state.py`` mesh registry (``rules_collectives``).
- **APX203/204/205** axis-scope dataflow (``dataflow`` + ``rules_collectives``):
  a registered-axis collective reachable only from ``jit``/``pjit``
  (no axis bound), or under a ``shard_map`` nest that binds only OTHER
  axes.
- **APX209/210/211** multi-process divergence (``rules_divergence`` +
  the ``dataflow`` host-divergence taint lattice): a rank-divergent
  predicate (``process_index``, env/hostname/clock/RNG/filesystem
  reads, per-rank branches) guarding the launch of a collective-
  bearing traced step (a static pod-deadlock proof), a rank-divergent
  value baked into a jit static arg / ``Mesh`` / bucket plan
  (divergent compiled programs), and rank-divergent engine/fallback
  dispatch in multi-process-aware code (the ``registry_engaged``
  class, generalized).  Acquittal seam:
  ``apex_tpu.resilience.uniformity.assert_uniform``.
- **APX206/207/208** sharding-annotation consistency
  (``rules_sharding`` — the GSPMD tier): a ``PartitionSpec`` axis no
  reaching mesh binds (a ``with_sharding_constraint`` from a STALE
  mesh object compiles and silently replicates; a typo'd axis against
  the annotation's own mesh raises only when the TPU-gated builder
  first runs — on the chip), a spec provably longer than the annotated
  array's rank, and a donated jit argument whose in/out shardings can
  never alias (XLA drops the donation with only a UserWarning).
- **APX301/302** Mosaic dtype-dependent tiling contracts for Pallas
  block shapes (``rules_tiling``) — the ``_ceil_block(..., 8)``-on-bf16
  class.
- **APX303** scratch/accumulator dtype narrower than the dot's
  ``preferred_element_type`` (``rules_precision`` + the ``dataflow``
  dtype lattice) — fp32 accumulation silently re-rounded to bf16.
- **APX304** provable per-``pallas_call`` VMEM footprint over budget
  (``rules_tiling``, warning).
- **APX305** quantized-sync state dtype (``rules_precision`` + the
  dtype lattice): in int8/fp8 wire-cast code, a ``scale`` buffer
  narrower than fp32 or a ``residual`` buffer at wire width — the
  compressed-grad-sync contract of
  ``contrib.optimizers._quantized_sync``.
- **APX401/402** indexing/precision hygiene: unclamped vocab gathers
  and fp32 constants in bf16 paths (``rules_precision``) — the
  ``gpt.py:447`` class.
- **APX107/306** decode-path hygiene (``rules_precision``): a
  page-table ``take``/subscript gather with no clamp (the APX401
  family extended to the serving path's mutable page indirection),
  and a KV-cache buffer provably narrower than the
  ``preferred_element_type`` of a dot it feeds with no explicit widen
  at the read (the ``inference.kv_cache`` storage-dtype contract).
- **APX110** kv/pool scatter bypassing the allocator/clamp seam
  (``rules_inference``): an ``.at[...].set`` into a pool-named buffer
  whose page index is neither clamped/garbage-routed device data nor
  an allocator-normalized host int — with refcounted prefix-shared
  pages, a write the copy-on-write pass cannot see mutates pages OTHER
  sequences still read.
- **APX108** blocking host sync in a step loop (``rules_host_sync``):
  ``float()``/``.item()``/``np.asarray``/f-string formatting of a
  proven device array inside a ``for``/``while`` loop that dispatches
  a compiled step — the per-step sync barrier
  ``apex_tpu.observability.stepstats`` (the allowed async-fetch
  spelling) exists to remove.
- **APX114/115/116** host-concurrency races (``rules_threading`` +
  the ``dataflow.ThreadIndex`` thread-reachability fixpoint): a
  shared attribute mutated lock-free from a thread-reachable method
  while another site holds the lock (the GoodputAccountant persist
  race), a lock-order inversion in the static acquisition graph
  (ABBA deadlock naming both sites), and a timeout-less blocking
  call under a lock a signal-/watchdog-reachable path also acquires
  (the drain-deadlock class).  Acquittal seam:
  ``apex_tpu.resilience.locks.assert_lock_held``; runtime sanitizer:
  ``instrument_locks()``.
- **APX112** unseamed dispatch timing (``rules_host_sync``): a
  ``time.time()``/``perf_counter()``/``monotonic()`` delta spanning a
  proven step dispatch with no ``block_until_ready``/host-read/
  async-fetch seam in between — async dispatch makes the delta an
  enqueue time, not a step time (host-side tracing spans say so
  explicitly: see ``apex_tpu.observability.tracing``).

CLI: ``python -m apex_tpu.analysis [paths] [--baseline FILE]`` — see
``docs/static_analysis.md`` for rule details, the baseline format, and
how to add a rule.  This package imports NO jax: it must run in
containers where jax is broken and over trees that do not import.
(The jax-importing lowered-artifact tier lives in
``apex_tpu.analysis.lowered`` and is deliberately NOT imported here —
``import apex_tpu.analysis.lowered`` is an explicit, test-suite-side
opt-in.)
"""

from apex_tpu.analysis.baseline import (
    BaselineEntry, BaselineError, apply_baseline, load_baseline,
    write_baseline,
)
from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, analyze_file, analyze_paths,
    discover_axis_registry,
)
from apex_tpu.analysis.rules_collectives import (
    CollectiveAxisOutsideShardMapNest, CollectiveAxisUnboundUnderJit,
    CollectiveOutsideSpmdContext, CollectiveTupleAxisUnbound,
    UnknownCollectiveAxis,
)
from apex_tpu.analysis.rules_divergence import (
    TaintedEngineDispatchDivergence, TaintedPredicateGuardsCollective,
    TaintedValueShapesCompiledProgram,
)
from apex_tpu.analysis.rules_donation import DonatedBufferReuse
from apex_tpu.analysis.rules_sharding import (
    DonatedShardingMismatch, ShardingSpecAxisUnbound,
    ShardingSpecRankMismatch,
)
from apex_tpu.analysis.rules_host_sync import (
    BlockingHostSyncInStepLoop, UnseamedDispatchTiming,
)
from apex_tpu.analysis.rules_inference import KvPoolScatterBypassesSeam
from apex_tpu.analysis.rules_io import NonAtomicCheckpointWrite
from apex_tpu.analysis.rules_resilience import (
    RetryWithoutBackoff, SwallowedExceptionInRecoveryPath,
)
from apex_tpu.analysis.rules_precision import (
    Fp32ConstantInBf16Path, KvCacheReadDtypeMismatch,
    PageTableGatherUnclamped, QuantizedSyncStateDtype,
    ScratchAccumDtypeMismatch, UnclampedTakeAlongAxis,
)
from apex_tpu.analysis.rules_threading import (
    BlockingCallUnderContendedLock, LockOrderInversion,
    SharedMutationWithoutLock,
)
from apex_tpu.analysis.rules_tiling import (
    BlockShapeTilingViolation, BlockSpecIndexMapArity,
    HardCodedSublaneAlignment, VmemFootprintOverBudget,
)
from apex_tpu.analysis.rules_trace import (
    ProcessGlobalEnvMutation, TraceTimeHostStateRead,
)


def default_rules(vmem_budget_bytes=None):
    """Every shipped rule, instantiated — the one place that knows the
    full set.  ``vmem_budget_bytes`` overrides APX304's 16 MiB default
    (the CLI's ``--vmem-budget-mib``)."""
    vmem = VmemFootprintOverBudget() if vmem_budget_bytes is None \
        else VmemFootprintOverBudget(budget_bytes=vmem_budget_bytes)
    return (
        TraceTimeHostStateRead(),
        ProcessGlobalEnvMutation(),
        DonatedBufferReuse(),
        NonAtomicCheckpointWrite(),
        SwallowedExceptionInRecoveryPath(),
        RetryWithoutBackoff(),
        BlockingHostSyncInStepLoop(),
        UnseamedDispatchTiming(),
        UnknownCollectiveAxis(),
        CollectiveOutsideSpmdContext(),
        CollectiveAxisUnboundUnderJit(),
        CollectiveAxisOutsideShardMapNest(),
        CollectiveTupleAxisUnbound(),
        ShardingSpecAxisUnbound(),
        ShardingSpecRankMismatch(),
        DonatedShardingMismatch(),
        TaintedPredicateGuardsCollective(),
        TaintedValueShapesCompiledProgram(),
        TaintedEngineDispatchDivergence(),
        BlockShapeTilingViolation(),
        BlockSpecIndexMapArity(),
        HardCodedSublaneAlignment(),
        vmem,
        ScratchAccumDtypeMismatch(),
        QuantizedSyncStateDtype(),
        KvCacheReadDtypeMismatch(),
        UnclampedTakeAlongAxis(),
        PageTableGatherUnclamped(),
        KvPoolScatterBypassesSeam(),
        Fp32ConstantInBf16Path(),
        SharedMutationWithoutLock(),
        LockOrderInversion(),
        BlockingCallUnderContendedLock(),
    )


#: The default instantiation — the CLI's and the test suite's single
#: source of truth for "what does a full run check".
DEFAULT_RULES = default_rules()

__all__ = [
    "BaselineEntry", "BaselineError", "DEFAULT_RULES", "Finding",
    "ModuleContext", "Rule", "analyze_file", "analyze_paths",
    "apply_baseline", "default_rules", "discover_axis_registry",
    "load_baseline", "write_baseline",
]
