"""apex_tpu.analysis — JAX/Pallas-aware static linter for TPU hazards.

Catches, before code ever reaches the chip, the failure classes that
are silent and deferred on TPU (each found at least once by a human
reviewer in this repo's history — the rules scale those findings into
machine-checked invariants):

- **APX101/102** trace-time host-state capture and process-global env
  mutation (``rules_trace``) — the ``bench.py:876`` class.
- **APX103** donated-buffer reuse: a ``donate_argnums`` argument read
  after the donating call without a rebind (``rules_donation``) — a
  no-op on CPU, garbage or a deleted-array error on TPU.
- **APX201/202** collective-axis consistency against the
  ``parallel_state.py`` mesh registry (``rules_collectives``).
- **APX301/302** Mosaic dtype-dependent tiling contracts for Pallas
  block shapes (``rules_tiling``) — the ``_ceil_block(..., 8)``-on-bf16
  class.
- **APX401/402** indexing/precision hygiene: unclamped vocab gathers
  and fp32 constants in bf16 paths (``rules_precision``) — the
  ``gpt.py:447`` class.

CLI: ``python -m apex_tpu.analysis [paths] [--baseline FILE]`` — see
``docs/static_analysis.md`` for rule details, the baseline format, and
how to add a rule.  This package imports NO jax: it must run in
containers where jax is broken and over trees that do not import.
"""

from apex_tpu.analysis.baseline import (
    BaselineEntry, BaselineError, apply_baseline, load_baseline,
)
from apex_tpu.analysis.core import (
    Finding, ModuleContext, Rule, analyze_file, analyze_paths,
    discover_axis_registry,
)
from apex_tpu.analysis.rules_collectives import (
    CollectiveOutsideSpmdContext, UnknownCollectiveAxis,
)
from apex_tpu.analysis.rules_donation import DonatedBufferReuse
from apex_tpu.analysis.rules_precision import (
    Fp32ConstantInBf16Path, UnclampedTakeAlongAxis,
)
from apex_tpu.analysis.rules_tiling import (
    BlockShapeTilingViolation, BlockSpecIndexMapArity,
    HardCodedSublaneAlignment,
)
from apex_tpu.analysis.rules_trace import (
    ProcessGlobalEnvMutation, TraceTimeHostStateRead,
)

#: Every shipped rule, instantiated — the CLI's and the test suite's
#: single source of truth for "what does a full run check".
DEFAULT_RULES = (
    TraceTimeHostStateRead(),
    ProcessGlobalEnvMutation(),
    DonatedBufferReuse(),
    UnknownCollectiveAxis(),
    CollectiveOutsideSpmdContext(),
    BlockShapeTilingViolation(),
    BlockSpecIndexMapArity(),
    HardCodedSublaneAlignment(),
    UnclampedTakeAlongAxis(),
    Fp32ConstantInBf16Path(),
)

__all__ = [
    "BaselineEntry", "BaselineError", "DEFAULT_RULES", "Finding",
    "ModuleContext", "Rule", "analyze_file", "analyze_paths",
    "apply_baseline", "discover_axis_registry", "load_baseline",
]
