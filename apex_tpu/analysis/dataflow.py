"""Symbolic dataflow for the analyzer: axis-binding scopes + a dtype
lattice.  Pure-stdlib ``ast`` like the rest of the package — no jax.

Two facts the pattern-matching rules cannot compute:

- **Axis-binding scopes** (:class:`Scope`, :func:`scopes_at`): for each
  function, the set of trace contexts it is reachable from, each
  carrying the mesh axis names *provably bound* there.  ``shard_map``
  and ``pmap`` bind axes (collectives legal); ``jit``/``pjit``
  auto-sharding binds none — a ``lax.psum("dp")`` reachable only
  through ``jit`` fails at trace time, but only on the code path that
  traces it, which for TPU-gated code is the chip.  Scopes propagate
  through the module-local call graph exactly like the traced index,
  and :func:`link_axis_scopes` runs the same import-resolved
  cross-module fixpoint, so a helper whose only shard_map wrapper
  lives in another file still gets its axes.

- **Dtype lattice** (:func:`dtype_literal`, :func:`dtype_env`,
  :func:`itemsize`): dtype names resolved through local assignments
  (``dot_dtype = jnp.bfloat16`` … ``jnp.zeros(s, dot_dtype)``), so the
  precision rules can compare a Pallas scratch dtype against the
  ``preferred_element_type`` of the dot that accumulates into it, and
  the tiling rules can price VMEM blocks whose dims thread through
  ``bn = 256``-style aliases.

Approximations (all fail QUIET, never loud): a binding whose axes
cannot be read statically (dynamic mesh, spec variables) is recorded
as ``unknown`` and silences the collective rules for that path; a
function with no computed scope at all is host code as far as this
pass can see, and the rules fall back to nothing (APX202's module
heuristic covers the literal-collective-with-invisible-caller case).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from apex_tpu.analysis.core import (
    TRACE_ENTRYPOINTS, ModuleContext, _is_partial, dotted_name, last_name,
)

# ----------------------------------------------------------------- scopes
#: Entry points that establish a fresh *non-binding* trace root: under
#: jit/pjit auto-sharding no mesh axis name is bound, whatever the
#: in_shardings say — collectives need shard_map/pmap.
_JIT_ROOTS = {"jit", "pjit"}

#: Entry points that bind mesh axes over their function argument.
_BINDING_ROOTS = {"shard_map", "pmap", "xmap"}

#: Mesh constructors whose axis-name argument names every bindable axis.
_MESH_CTORS = {"Mesh", "AbstractMesh", "make_mesh"}


@dataclasses.dataclass(frozen=True)
class Scope:
    """One trace context a function is reachable from.

    ``axes``: axis names provably bound on this path.  ``unknown``:
    additional axes *may* be bound (a dynamic mesh / non-literal
    axis_name somewhere in the nest) — rules must stay quiet.
    ``shard_map``: a shard_map/pmap/xmap participates in the nest (the
    APX203-vs-204 discriminator).

    ``mesh_axes``/``mesh_unknown``: the GSPMD half (the sharding tier,
    APX206): the axis names of the mesh the enclosing ``jit``'s
    ``in_shardings=``/``out_shardings=`` annotations are built on, when
    every ``NamedSharding`` there resolves to a static mesh — the
    "reaching mesh" a ``with_sharding_constraint`` inside the traced
    function must agree with.  ``None`` = no mesh information on this
    path (an unannotated jit); ``mesh_unknown`` = some annotation's
    mesh could not be read statically — rules must stay quiet.  jit
    still binds NO collective axes (``axes`` stays empty): mesh_axes
    name what XLA *shards over*, not what ``lax.psum`` may name."""

    axes: FrozenSet[str] = frozenset()
    unknown: bool = False
    shard_map: bool = False
    mesh_axes: Optional[FrozenSet[str]] = None
    mesh_unknown: bool = False

    def binds(self, axis: str) -> bool:
        return axis in self.axes or self.unknown


def _str_constants(node: ast.AST) -> List[str]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append(sub.value)
    return out


def _mesh_axes(node: Optional[ast.AST],
               aliases: Dict[str, ast.AST]) -> Optional[FrozenSet[str]]:
    """The full axis-name set of a ``Mesh(devs, ("dp", "tp"))`` /
    ``make_mesh(shape, axis_names)`` expression (resolved through one
    local-alias hop), or None when it cannot be read statically.  A
    resolvable mesh is the only way to know EVERY axis shard_map binds
    — in_specs only name the partitioned subset."""
    if isinstance(node, ast.Name):
        node = aliases.get(node.id)
    if not (isinstance(node, ast.Call)
            and last_name(node.func) in _MESH_CTORS):
        return None
    names = None
    for kw in node.keywords:
        if kw.arg == "axis_names":
            names = kw.value
    if names is None and len(node.args) > 1:
        names = node.args[1]
    if names is None:
        return None
    if isinstance(names, ast.Constant) and isinstance(names.value, str):
        return frozenset({names.value})
    if isinstance(names, (ast.Tuple, ast.List)):
        if all(isinstance(e, ast.Constant) and isinstance(e.value, str)
               for e in names.elts):
            return frozenset(e.value for e in names.elts)
    return None


def _spec_axes(nodes: Iterable[ast.AST]) -> FrozenSet[str]:
    """Axis names mentioned in ``P(...)``/``PartitionSpec(...)`` calls
    under the given spec expressions — a LOWER bound on what the mesh
    binds (replicated axes never appear in specs)."""
    axes: Set[str] = set()
    for node in nodes:
        if node is None:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and last_name(sub.func) in ("P", "PartitionSpec"):
                for arg in sub.args:
                    axes.update(_str_constants(arg))
    return frozenset(axes)


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _binding_axes(entry: str, call: ast.Call,
                  aliases: Dict[str, ast.AST]
                  ) -> Tuple[FrozenSet[str], bool]:
    """(axes, unknown) bound by one shard_map/pmap/xmap call site."""
    if entry == "shard_map":
        mesh = _kwarg(call, "mesh")
        if mesh is None and len(call.args) > 1:
            mesh = call.args[1]
        axes = _mesh_axes(mesh, aliases)
        if axes is not None:
            return axes, False
        specs = [_kwarg(call, "in_specs"), _kwarg(call, "out_specs")]
        specs += call.args[2:4]
        return _spec_axes(specs), True
    if entry == "pmap":
        name = _kwarg(call, "axis_name")
        if name is None and len(call.args) > 1:
            name = call.args[1]
        if name is None:
            # unnamed mapped axis: spmd context, but no NAME is bound
            return frozenset(), False
        if isinstance(name, ast.Constant) and isinstance(name.value, str):
            return frozenset({name.value}), False
        return frozenset(), True
    return frozenset(), True  # xmap: axes out of static reach


def _vmap_axis(call: ast.Call) -> Optional[Tuple[FrozenSet[str], bool]]:
    """vmap with a literal ``axis_name`` binds that axis (collectives
    over a vmapped named axis are legal); without one it is pure
    vectorization — neutral, handled by the caller."""
    name = _kwarg(call, "axis_name")
    if name is None:
        return None
    if isinstance(name, ast.Constant) and isinstance(name.value, str):
        return frozenset({name.value}), False
    return frozenset(), True


class AxisScopeIndex:
    """Per-module axis-binding scopes, built like the traced index:
    decorator + call-site seeds, then a call-graph fixpoint.  Lambdas
    are tracked by identity (no qualname)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.scopes: Dict[str, Set[Scope]] = {}
        self.lambda_scopes: Dict[ast.Lambda, Set[Scope]] = {}
        # name -> function-name aliases (x = f / x = partial(f, ...))
        self._fn_aliases: Dict[str, str] = {}
        # name -> value-node aliases for mesh resolution; lexically
        # LAST assignment wins (the APX105 house rule: ast.walk order
        # is breadth-first, not source order)
        self._value_aliases: Dict[str, ast.AST] = {}
        assigns = [
            node for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Assign) and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ]
        for node in sorted(assigns, key=lambda n: (n.lineno, n.col_offset)):
            tgt = node.targets[0].id
            self._value_aliases[tgt] = node.value
            if isinstance(node.value, ast.Name):
                self._fn_aliases[tgt] = node.value.id
            elif isinstance(node.value, ast.Call) \
                    and _is_partial(node.value) and node.value.args \
                    and isinstance(node.value.args[0], ast.Name):
                self._fn_aliases[tgt] = node.value.args[0].id
        self._entry_sites: List[Tuple[ast.Call, str]] = [
            (node, last_name(node.func))
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call)
            and last_name(node.func) in TRACE_ENTRYPOINTS
        ]
        self._seed_decorators()
        self._fixpoint()

    # ------------------------------------------------------------- sizes
    def size(self) -> int:
        return (sum(len(s) for s in self.scopes.values())
                + sum(len(s) for s in self.lambda_scopes.values()))

    # ------------------------------------------------------------ seeding
    def _add(self, qualname: str, scopes: Set[Scope]) -> bool:
        cur = self.scopes.setdefault(qualname, set())
        before = len(cur)
        cur |= scopes
        return len(cur) != before

    def _add_lambda(self, lam: ast.Lambda, scopes: Set[Scope]) -> bool:
        cur = self.lambda_scopes.setdefault(lam, set())
        before = len(cur)
        cur |= scopes
        return len(cur) != before

    def _jit_mesh(self, call: Optional[ast.Call]
                  ) -> Tuple[Optional[FrozenSet[str]], bool]:
        """``(mesh_axes, unknown)`` of one jit call's sharding
        annotations: the union of the axis names of every
        ``NamedSharding(mesh, ...)`` mesh in its ``in_shardings=``/
        ``out_shardings=`` kwargs, resolved through the local value
        aliases.  ``(None, False)`` when the call carries no sharding
        annotations at all (an unannotated jit has no mesh opinion);
        ``unknown`` when some annotation's mesh is out of static
        reach."""
        if call is None:
            return None, False
        axes: Set[str] = set()
        saw = False
        unknown = False
        for kw in call.keywords:
            if kw.arg not in ("in_shardings", "out_shardings"):
                continue
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Call) \
                        and last_name(sub.func) == "NamedSharding":
                    saw = True
                    mesh = sub.args[0] if sub.args else _kwarg(sub, "mesh")
                    m = _mesh_axes(mesh, self._value_aliases)
                    if m is None:
                        unknown = True
                    else:
                        axes |= m
        if not saw:
            return None, False
        return frozenset(axes), unknown

    def _extend(self, entry: str, call: ast.Call,
                base: Optional[Set[Scope]]) -> Set[Scope]:
        """Scopes the function-valued arguments of this entry call run
        under: the caller's scopes, extended by whatever the entry
        binds.  An empty base is host code — jit establishes a fresh
        non-binding root there, a binding entry a fresh spmd root, and
        a neutral combinator (scan/pallas_call/grad/...) an UNKNOWN
        context (its caller is outside this pass's reach)."""
        if entry in _JIT_ROOTS:
            maxes, munk = self._jit_mesh(call)
            srcs = base or {Scope()}
            if maxes is None and not munk:
                return set(srcs)
            # the innermost annotated jit's mesh wins over an outer one
            return {dataclasses.replace(s, mesh_axes=maxes,
                                        mesh_unknown=munk) for s in srcs}
        binding = None
        smap = False
        if entry in _BINDING_ROOTS:
            binding = _binding_axes(entry, call, self._value_aliases)
            smap = True
        elif entry == "vmap":
            binding = _vmap_axis(call)
        if binding is not None:
            axes, unk = binding
            srcs = base or {Scope()}
            # replace, not positional rebuild: the mesh_axes half must
            # survive a vmap(axis_name=...) nested under an annotated
            # jit, or APX206 goes quiet on that path
            return {dataclasses.replace(
                s, axes=s.axes | axes, unknown=s.unknown or unk,
                shard_map=s.shard_map or smap) for s in srcs}
        return set(base) if base else {Scope(unknown=True)}

    def _base(self, node: ast.AST) -> Optional[Set[Scope]]:
        fn = self.ctx.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, ast.Lambda):
                ss = self.lambda_scopes.get(fn)
            else:
                ss = self.scopes.get(self.ctx.enclosing_qualname(fn))
            if ss:
                return ss
            fn = self.ctx.enclosing_function(fn)
        return None

    def _seed_decorators(self) -> None:
        for qn, info in self.ctx.functions.items():
            for dec in getattr(info.node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = last_name(target)
                inner_call = dec if isinstance(dec, ast.Call) else None
                if name == "partial" and inner_call is not None \
                        and inner_call.args:
                    name = last_name(inner_call.args[0])
                if name in _JIT_ROOTS:
                    maxes, munk = self._jit_mesh(inner_call)
                    self._add(qn, {Scope(mesh_axes=maxes,
                                         mesh_unknown=munk)})
                elif name in _BINDING_ROOTS:
                    axes, unk = _binding_axes(
                        name, inner_call or ast.Call(
                            func=ast.Name(id=name), args=[], keywords=[]),
                        self._value_aliases)
                    self._add(qn, {Scope(axes, unk, True)})
                # neutral decorators (checkpoint/custom_vjp/...) add no
                # scope: the body runs wherever the caller traces it,
                # which plain-call propagation already models

    def _seed_value(self, value: ast.AST, scopes: Set[Scope],
                    scope: str) -> bool:
        """Plant ``scopes`` on the function a call argument refers to
        (Name / partial(f, ..) / lambda / attribute), module-locally.
        Cross-module targets are handled by :meth:`exports`."""
        if isinstance(value, ast.Lambda):
            return self._add_lambda(value, scopes)
        if isinstance(value, ast.Call) and _is_partial(value) and value.args:
            return self._seed_value(value.args[0], scopes, scope)
        name = None
        if isinstance(value, ast.Name):
            name = self._fn_aliases.get(value.id, value.id)
        elif isinstance(value, ast.Attribute):
            name = last_name(value)
        if name is None:
            return False
        resolved = self.ctx.resolve_function(name, scope)
        if resolved is not None:
            return self._add(resolved, scopes)
        return False

    # ----------------------------------------------------------- fixpoint
    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for call, entry in self._entry_sites:
                ext = self._extend(entry, call, self._base(call))
                scope = self.ctx.enclosing_qualname(call)
                scope = "" if scope == "<module>" else scope
                for arg in list(call.args) + [kw.value
                                              for kw in call.keywords]:
                    if self._seed_value(arg, ext, scope):
                        changed = True
            if self._propagate():
                changed = True

    def _propagate(self) -> bool:
        """Callees and nested defs inherit their caller's scopes — the
        scope analog of ``ModuleContext._propagate``."""
        changed = False
        prog = True
        while prog:
            prog = False
            for lam, ss in list(self.lambda_scopes.items()):
                scope = self.ctx.enclosing_qualname(lam)
                scope = "" if scope == "<module>" else scope
                prog |= self._propagate_body(lam.body, scope, ss)
            for qn in list(self.scopes):
                ss = self.scopes[qn]
                if not ss:
                    continue
                info = self.ctx.functions.get(qn)
                if info is None:
                    continue
                for other_qn in self.ctx.functions:
                    if other_qn.startswith(qn + "."):
                        if self._add(other_qn, ss):
                            prog = True
                prog |= self._propagate_body(info.node, qn, ss)
            changed |= prog
        return changed

    def _propagate_body(self, body: ast.AST, scope: str,
                        ss: Set[Scope]) -> bool:
        changed = False
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            callee = last_name(sub.func)
            if callee is None or callee in TRACE_ENTRYPOINTS:
                continue  # entry sites get the EXTENDED scopes instead
            resolved = self.ctx.resolve_function(
                self._fn_aliases.get(callee, callee), scope)
            if resolved is not None and self._add(resolved, ss):
                changed = True
        return changed

    # ------------------------------------------------------- cross-module
    def _import_target(self, name: str,
                       scope: str) -> Optional[Tuple[str, str]]:
        """(module, attr) a bare name resolves to through this module's
        imports — None when a module-local binding shadows it."""
        if self.ctx.resolve_function(
                self._fn_aliases.get(name, name), scope) is not None:
            return None
        tgt = self.ctx.from_imports.get(name)
        if tgt is None:
            return None
        mod, attr = tgt
        return (mod, attr) if mod else (attr, "")

    def _export_value(self, value: ast.AST, scopes: Set[Scope], scope: str,
                      out: List[Tuple[str, str, FrozenSet[Scope]]]) -> None:
        if isinstance(value, ast.Call) and _is_partial(value) and value.args:
            self._export_value(value.args[0], scopes, scope, out)
            return
        if isinstance(value, ast.Name):
            tgt = self._import_target(value.id, scope)
            if tgt is not None:
                out.append((*tgt, frozenset(scopes)))
        elif isinstance(value, ast.Attribute):
            d = dotted_name(value)
            if d is None:
                return
            head, attr = d.split(".")[:-1], d.split(".")[-1]
            if head and head[0] in self.ctx.import_aliases:
                mod = ".".join(
                    [self.ctx.import_aliases[head[0]]] + head[1:])
                out.append((mod, attr, frozenset(scopes)))

    def exports(self) -> List[Tuple[str, str, FrozenSet[Scope]]]:
        """(module, func, scopes) seeds this module plants into OTHER
        modules: plain calls inside scoped code, and entry-call
        arguments resolving through imports (``jit(other.f)``)."""
        out: List[Tuple[str, str, FrozenSet[Scope]]] = []
        for qn, ss in self.scopes.items():
            info = self.ctx.functions.get(qn)
            if info is None or not ss:
                continue
            self._export_calls(info.node, qn, ss, out)
        for lam, ss in self.lambda_scopes.items():
            scope = self.ctx.enclosing_qualname(lam)
            scope = "" if scope == "<module>" else scope
            self._export_calls(lam, scope, ss, out)
        for call, entry in self._entry_sites:
            ext = self._extend(entry, call, self._base(call))
            scope = self.ctx.enclosing_qualname(call)
            scope = "" if scope == "<module>" else scope
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                self._export_value(arg, ext, scope, out)
        return out

    def _export_calls(self, body: ast.AST, scope: str, ss: Set[Scope],
                      out: List[Tuple[str, str, FrozenSet[Scope]]]) -> None:
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            d = dotted_name(sub.func)
            if d is None or last_name(sub.func) in TRACE_ENTRYPOINTS:
                continue
            parts = d.split(".")
            if len(parts) == 1:
                tgt = self._import_target(parts[0], scope)
                if tgt is not None:
                    out.append((*tgt, frozenset(ss)))
                continue
            head, attr = parts[:-1], parts[-1]
            if head[0] in self.ctx.import_aliases:
                mod = ".".join([self.ctx.import_aliases[head[0]]] + head[1:])
            elif head[0] in self.ctx.from_imports:
                m0, a0 = self.ctx.from_imports[head[0]]
                mod = ".".join([f"{m0}.{a0}" if m0 else a0] + head[1:])
            else:
                mod = ".".join(head)
            out.append((mod, attr, frozenset(ss)))

    def mark_external(self, qualname: str, scopes: Set[Scope]) -> bool:
        """Seed a function's scopes from ANOTHER module and re-run the
        local fixpoint; True if anything new was recorded."""
        if qualname not in self.ctx.functions:
            return False
        if not self._add(qualname, set(scopes)):
            return False
        self._fixpoint()
        return True

    # -------------------------------------------------------------- query
    def scopes_for(self, node: ast.AST) -> Optional[Set[Scope]]:
        """The scope set of the innermost scoped function (or lambda)
        lexically enclosing ``node`` — None when no enclosing function
        has any computed scope (host code, or callers out of reach)."""
        fn = self.ctx.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, ast.Lambda):
                ss = self.lambda_scopes.get(fn)
            else:
                ss = self.scopes.get(self.ctx.enclosing_qualname(fn))
            if ss:
                return ss
            fn = self.ctx.enclosing_function(fn)
        return None


def scope_index(ctx: ModuleContext) -> AxisScopeIndex:
    """The (cached) axis-scope index of one module.  For multi-file
    runs, :func:`link_axis_scopes` must run first so cross-module
    wrappers are linked in; single-file analysis sees local scopes
    only (same contract as the traced index)."""
    idx = getattr(ctx, "_axis_scope_index", None)
    if idx is None:
        idx = AxisScopeIndex(ctx)
        ctx._axis_scope_index = idx
    return idx


def scopes_at(ctx: ModuleContext, node: ast.AST) -> Optional[Set[Scope]]:
    return scope_index(ctx).scopes_for(node)


def link_axis_scopes(ctxs: Dict[str, Optional[ModuleContext]]) -> None:
    """Global scope fixpoint across modules, mirroring
    ``core._link_cross_module``: ambiguous module names (None entries)
    are never linked through; each module's export list is recomputed
    only when its scope count grew."""
    live = [c for c in ctxs.values() if c is not None]
    for c in live:
        scope_index(c)
    memo: Dict[int, Tuple[int, list]] = {}
    changed = True
    while changed:
        changed = False
        for c in live:
            idx = scope_index(c)
            n = idx.size()
            if memo.get(id(c), (-1,))[0] != n:
                memo[id(c)] = (n, idx.exports())
            for mod, attr, ss in memo[id(c)][1]:
                target = ctxs.get(mod)
                if target is None or target is c:
                    continue
                if scope_index(target).mark_external(attr, set(ss)):
                    changed = True


# ------------------------------------------------------- sharding literals
def value_aliases(ctx: ModuleContext) -> Dict[str, ast.AST]:
    """The module's last-wins single-target value-alias map (``mesh =
    Mesh(...)``), shared with the axis-scope index — the one alias
    resolution the sharding rules and the scope pass must agree on."""
    return scope_index(ctx)._value_aliases


def mesh_axes_of(node: Optional[ast.AST],
                 aliases: Dict[str, ast.AST]) -> Optional[FrozenSet[str]]:
    """Public face of :func:`_mesh_axes`: the full axis-name set of a
    mesh expression (a ``Mesh``/``AbstractMesh``/``make_mesh`` call, or
    a Name assigned one), or None when it cannot be read statically."""
    return _mesh_axes(node, aliases)


def resolve_spec(node: Optional[ast.AST],
                 aliases: Dict[str, ast.AST]) -> Optional[ast.Call]:
    """The ``P(...)``/``PartitionSpec(...)`` call a spec expression
    denotes: the call itself, or a Name resolved through one
    last-wins alias hop; None for anything else (a computed spec tree,
    a parameter — trusted, same contract as the dtype lattice)."""
    if isinstance(node, ast.Name):
        node = aliases.get(node.id)
    if isinstance(node, ast.Call) \
            and last_name(node.func) in ("P", "PartitionSpec"):
        return node
    return None


def spec_axis_literals(spec: ast.Call) -> List[Tuple[ast.AST, str]]:
    """(node, axis-name) per string literal in one P(...) call's
    positional entries — handles ``P("dp")``, ``P(None, "tp")`` and
    the tuple entry ``P(("dp_out", "dp_in"))``."""
    out: List[Tuple[ast.AST, str]] = []
    for arg in spec.args:
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                out.append((sub, sub.value))
    return out


def spec_rank(spec: ast.Call) -> int:
    """Number of array dimensions one P(...) call constrains — its
    positional-entry count (each entry maps to one dim, None/str/tuple
    alike)."""
    return len(spec.args)


#: array-creating callables whose shape argument position we know
_CREATION_SHAPE_ARG = {
    "zeros": 0, "ones": 0, "empty": 0, "full": 0,
    "normal": 1, "uniform": 1, "truncated_normal": 1,
}


def creation_rank(node: Optional[ast.AST],
                  aliases: Dict[str, ast.AST]) -> Optional[int]:
    """The rank of an array expression, when it is (or aliases to, one
    hop) a creation call with a LITERAL shape tuple — ``jnp.zeros((8,
    128))``, ``jax.random.normal(key, (4, 4))``.  None otherwise: the
    annotated value's rank is out of static reach and APX207 must stay
    quiet."""
    if isinstance(node, ast.Name):
        node = aliases.get(node.id)
    if not isinstance(node, ast.Call):
        return None
    name = last_name(node.func)
    pos = _CREATION_SHAPE_ARG.get(name)
    if pos is None:
        return None
    shape = node.args[pos] if len(node.args) > pos else _kwarg(node, "shape")
    if isinstance(shape, ast.Constant) and isinstance(shape.value, int):
        # scalar shapes are rank 1 for the zeros/ones family ONLY: the
        # position-1 names collide with numpy's random signatures
        # (np.random.normal(loc, SCALE, size) puts the scale where
        # jax.random.normal puts the shape), and claiming rank 1 there
        # is a confirmed false positive — tuple shapes disambiguate
        return 1 if pos == 0 else None
    dims = literal_dims(shape, aliases)
    if dims is not None:
        return len(dims)
    # a tuple shape whose dims are dynamic still has a static RANK
    if isinstance(shape, (ast.Tuple, ast.List)):
        return len(shape.elts)
    return None


# ------------------------------------------------------------ dtype lattice
#: dtype name -> bytes per element.  The lattice is {UNKNOWN} ∪ these
#: names; anything unresolvable is UNKNOWN (None) and silences rules.
_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "float8_e4m3": 1,
    "float8_e4m3fnuz": 1, "float8_e5m2fnuz": 1,
}


def itemsize(dtype_name: Optional[str]) -> Optional[int]:
    return _ITEMSIZE.get(dtype_name) if dtype_name else None


def dtype_literal(node: Optional[ast.AST],
                  env: Optional[Dict[str, str]] = None) -> Optional[str]:
    """The dtype name an expression denotes, or None (UNKNOWN): a
    string literal, ``jnp.float32``-style attribute, ``jnp.dtype(X)``
    wrapper, or a Name resolved through ``env`` (the local-assignment
    lattice from :func:`dtype_env`)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _ITEMSIZE else None
    if isinstance(node, ast.Name):
        return (env or {}).get(node.id)
    if isinstance(node, ast.Attribute):
        name = last_name(node)
        return name if name in _ITEMSIZE else None
    if isinstance(node, ast.Call) and last_name(node.func) == "dtype" \
            and node.args:
        return dtype_literal(node.args[0], env)
    return None


def _scope_dtype_env(assigns: List[ast.Assign],
                     base: Dict[str, str]) -> Dict[str, str]:
    """One ordered pass over a scope's assignments: chains resolve in
    source order (``a = jnp.bfloat16; b = a``), and a name assigned
    two DIFFERENT resolvable dtypes — or re-assigned something
    unresolvable — is POISONED to UNKNOWN rather than last-wins (the
    two assignments may sit on different branches; claiming either is
    a wrong finding waiting to happen).  No fixpoint: a single pass
    terminates by construction."""
    env = dict(base)
    poisoned: set = set()
    for node in sorted(assigns, key=lambda n: (n.lineno, n.col_offset)):
        name = node.targets[0].id
        if name in poisoned:
            continue
        d = dtype_literal(node.value, env)
        if d is None:
            if name in env:  # a dtype name re-bound to who-knows-what
                del env[name]
                poisoned.add(name)
            continue
        if name in env and env[name] != d:
            del env[name]
            poisoned.add(name)
        else:
            env[name] = d
    return env


def _dtype_assigns(scope: ast.AST) -> List[ast.Assign]:
    return [n for n in ast.walk(scope)
            if isinstance(n, ast.Assign) and len(n.targets) == 1
            and isinstance(n.targets[0], ast.Name)]


def dtype_env(ctx: ModuleContext,
              fn: Optional[ast.AST] = None) -> Dict[str, str]:
    """name -> dtype for simple single-target assignments: module
    TOP-LEVEL constants first (one function's dtype locals must never
    leak into another's resolution), then — overriding — everything
    under ``fn``.  The module layer is cached on the ctx: every rule on
    every pallas_call may ask."""
    mod_env = getattr(ctx, "_dtype_env_module", None)
    if mod_env is None:
        top = [n for n in _dtype_assigns(ctx.tree)
               if ctx.enclosing_function(n) is None]
        mod_env = _scope_dtype_env(top, {})
        ctx._dtype_env_module = mod_env
    if fn is None:
        return dict(mod_env)
    return _scope_dtype_env(_dtype_assigns(fn), mod_env)


def scratch_entries(call: ast.Call) -> List[Tuple[ast.AST, Optional[ast.AST],
                                                  Optional[ast.AST]]]:
    """``(entry_node, shape_node, dtype_node)`` per scratch buffer of a
    ``pallas_call``, in declaration order.  Handles the plain list and
    the repo's ``[pltpu.VMEM(shape, dtype)] * 3`` spelling; entries
    that are not ``VMEM``/``SMEM``/``ANY`` calls (e.g. ``pltpu.SemaphoreType``)
    yield ``(node, None, None)`` — counted (they consume a kernel
    parameter) but unpriceable."""
    arg = _kwarg(call, "scratch_shapes")
    if arg is None:
        return []
    repeat = 1
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mult):
        lst, n = arg.left, arg.right
        if isinstance(lst, (ast.List, ast.Tuple)) \
                and isinstance(n, ast.Constant) and isinstance(n.value, int):
            arg, repeat = lst, n.value
    if not isinstance(arg, (ast.List, ast.Tuple)):
        return []
    out = []
    for el in arg.elts:
        if isinstance(el, ast.Call) \
                and last_name(el.func) in ("VMEM", "SMEM", "ANY"):
            shape = el.args[0] if el.args else _kwarg(el, "shape")
            dtype = el.args[1] if len(el.args) > 1 else _kwarg(el, "dtype")
            out.append((el, shape, dtype))
        else:
            out.append((el, None, None))
    return out * repeat


def literal_dims(shape_node: Optional[ast.AST],
                 aliases: Dict[str, ast.AST]) -> Optional[List[int]]:
    """A shape tuple as concrete ints, resolving Name dims through one
    local-assignment hop (``bn = 256``); None when any dim stays
    dynamic — rules must treat the whole shape as unknowable."""
    if not isinstance(shape_node, (ast.Tuple, ast.List)):
        return None
    out: List[int] = []
    for el in shape_node.elts:
        if isinstance(el, ast.Name):
            el = aliases.get(el.id, el)
        if isinstance(el, ast.Constant) and isinstance(el.value, int) \
                and not isinstance(el.value, bool):
            out.append(el.value)
        else:
            return None
    return out


# ------------------------------------------------------------ taint lattice
#: Host-divergence taint sources (the divergence tier, APX209–211):
#: dotted-suffix call patterns whose RESULT can differ across the
#: processes of one pod — per-process identity, environment, clocks,
#: host RNG, filesystem state.  Matched like
#: ``rules_trace._HAZARD_CALLS`` (``d == suffix`` or
#: ``d.endswith("." + suffix)``), so ``jax.process_index`` and a bare
#: ``process_index`` both hit.  ``process_count`` is on the list
#: deliberately: its VALUE is uniform, but code branching on it
#: ("am I multi-process?") is per-topology dispatch — exactly the
#: registry_engaged class APX211 exists to gate behind the uniformity
#: seam.
_TAINT_CALLS: Dict[str, str] = {
    "process_index": "per-process rank (process_index)",
    "process_count": "process topology (process_count)",
    "gethostname": "hostname (gethostname)",
    "platform.node": "hostname (platform.node)",
    "os.uname": "host identity (os.uname)",
    "getpid": "process id (getpid)",
    "getenv": "environment variable",
    "environ.get": "environment variable",
    "time.time": "wall clock",
    "time.monotonic": "wall clock",
    "time.perf_counter": "wall clock",
    "time.time_ns": "wall clock",
    "datetime.now": "wall clock",
    "datetime.utcnow": "wall clock",
    "os.listdir": "filesystem state (os.listdir)",
    "os.scandir": "filesystem state (os.scandir)",
    "glob.glob": "filesystem state (glob.glob)",
    "os.stat": "filesystem state (os.stat)",
    "path.exists": "filesystem state (os.path.exists)",
    "open": "filesystem read (open)",
    "read_text": "filesystem read (read_text)",
    "read_bytes": "filesystem read (read_bytes)",
}

#: Host-RNG module prefixes — same set APX101 treats as trace-time
#: hazards; here they are divergence sources (each process seeds its
#: own generator).
_TAINT_RANDOM_PREFIXES = ("random.", "np.random.", "numpy.random.")


class TaintIndex:
    """Per-module host-divergence taint: which expressions carry a
    value that can DIFFER across the processes of one pod.

    Built like the dtype lattice, not the scope index: per-scope
    line-ordered assignment *events* replayed at each query line, so

    - a straight-line rebind to a clean value CLEARS taint (the
      shadowed-rebind acquittal: claiming taint after ``rank = 0`` is
      a false positive waiting to happen);
    - an assignment nested under ``if``/``while``/``for``/``try`` only
      JOINS (taint wins, clean does not clear): the other branch may
      have left the tainted value in place;
    - an assignment lexically under an ``if``/``while`` whose test is
      tainted becomes tainted itself (control dependence — the
      "per-rank branches" source).

    ``tainted_returns`` (qualname → reason) is the module-local
    fixpoint over ``return`` statements; :func:`link_taint` runs the
    import-resolved cross-module fixpoint on top, planting
    ``external_calls`` spellings (monotone — a taint CYCLE between two
    modules converges because entries are only ever added).

    The quiet-on-unknown contract holds throughout: a name this pass
    cannot see assigned (parameters, attributes, comprehension
    targets) is clean — threading a value in as an argument is exactly
    the blessed pattern."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        # share the scope index's name→function alias map so
        # ``g = partial(f, ...)`` resolves the same way everywhere
        self._fn_aliases = scope_index(ctx)._fn_aliases
        # and its name→value map, for the aliases the fn map skips:
        # ``who = partial(jax.process_index)`` (Attribute target)
        self._value_aliases = scope_index(ctx)._value_aliases
        #: qualname -> reason: functions whose RETURN value is tainted
        self.tainted_returns: Dict[str, str] = {}
        #: call spelling (bare or dotted) -> reason, planted by
        #: :func:`link_taint` from other modules' tainted returns
        self.external_calls: Dict[str, str] = {}
        # innermost-enclosing-function -> its single-target Name
        # assignments in source order (None = module scope)
        self._scope_assigns: Dict[Optional[ast.AST], List[ast.Assign]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                owner = ctx.enclosing_function(node)
                self._scope_assigns.setdefault(owner, []).append(node)
        for assigns in self._scope_assigns.values():
            assigns.sort(key=lambda n: (n.lineno, n.col_offset))
        # event caches are generation-stamped: any growth of
        # tainted_returns/external_calls invalidates every replayed env
        self._gen = 0
        self._event_gen = -1
        self._events_cache: Dict[Optional[ast.AST], list] = {}
        self._fixpoint()

    def size(self) -> int:
        return len(self.tainted_returns) + len(self.external_calls)

    # ----------------------------------------------------------- fixpoint
    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for qn, info in self.ctx.functions.items():
                if qn in self.tainted_returns:
                    continue
                node = info.node
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and sub.value is not None \
                            and self.ctx.enclosing_function(sub) is node:
                        r = self.taint_of(sub.value)
                        if r is not None:
                            self.tainted_returns[qn] = r
                            self._gen += 1
                            changed = True
                            break

    def mark_external(self, spelling: str, reason: str) -> bool:
        """Record an imported callable as taint-returning (planted by
        :func:`link_taint`) and re-run the local fixpoint; True if new."""
        if spelling in self.external_calls:
            return False
        self.external_calls[spelling] = reason
        self._gen += 1
        self._fixpoint()
        return True

    # ------------------------------------------------------------- events
    def _events(self, owner: Optional[ast.AST]) -> list:
        """``(lineno, name, reason|None, conditional)`` per single-target
        assignment of one scope, in source order.  Built incrementally:
        evaluating event *i*'s value replays exactly the prefix of
        earlier events, so chains resolve without a second fixpoint."""
        if self._event_gen != self._gen:
            self._events_cache.clear()
            self._event_gen = self._gen
        ev = self._events_cache.get(owner)
        if ev is None:
            ev = self._events_cache[owner] = []
            for node in self._scope_assigns.get(owner, []):
                name = node.targets[0].id
                reason = self.taint_of(node.value)
                conds = self._cond_ancestors(node, owner)
                if reason is None:
                    for c in conds:
                        if isinstance(c, (ast.If, ast.While)):
                            t = self.taint_of(c.test)
                            if t is not None:
                                reason = ("assigned under a "
                                          f"rank-divergent branch ({t})")
                                break
                ev.append((node.lineno, name, reason, bool(conds)))
        return ev

    def _cond_ancestors(self, node: ast.AST,
                        owner: Optional[ast.AST]) -> List[ast.AST]:
        out = []
        cur = self.ctx.parent(node)
        while cur is not None and cur is not owner:
            if isinstance(cur, (ast.If, ast.While, ast.For, ast.Try)):
                out.append(cur)
            cur = self.ctx.parent(cur)
        return out

    def _env_at(self, owner: Optional[ast.AST],
                line: int) -> Dict[str, Optional[str]]:
        env: Dict[str, Optional[str]] = {}
        for ln, name, reason, cond in self._events(owner):
            if ln >= line:
                break
            if reason is not None:
                env[name] = reason
            elif cond:
                # conditional clean assignment JOINS: the other branch
                # may have left a tainted value in place
                env.setdefault(name, None)
            else:
                env[name] = None
        return env

    @staticmethod
    def _param_names(fn: ast.AST) -> FrozenSet[str]:
        a = fn.args
        names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        return frozenset(names)

    def _lookup(self, node: ast.Name) -> Optional[str]:
        owner = self.ctx.enclosing_function(node)
        line = node.lineno
        while True:
            if owner is not None and node.id in self._param_names(owner):
                return None  # parameters shadow; threaded-in args are clean
            env = self._env_at(owner, line)
            if node.id in env:
                return env[node.id]
            if owner is None:
                return None
            owner = self.ctx.enclosing_function(owner)

    # -------------------------------------------------------------- query
    def taint_of(self, expr: ast.AST) -> Optional[str]:
        """The host-divergence reason carried by ``expr``, or None.
        Any tainted subterm taints the whole expression; lambda bodies
        are opaque values (defining one evaluates nothing)."""
        stack = [expr]
        while stack:
            node = stack.pop()
            r = self._node_taint(node)
            if r is not None:
                return r
            if not isinstance(node, ast.Lambda):
                stack.extend(ast.iter_child_nodes(node))
        return None

    def _node_taint(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            for suffix, what in _TAINT_CALLS.items():
                if d == suffix or d.endswith("." + suffix):
                    return f"{what}: {d}(...)"
            if any(d.startswith(p) for p in _TAINT_RANDOM_PREFIXES):
                return f"host RNG: {d}(...)"
            return self._call_taint(node, d)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            d = dotted_name(node.value) or ""
            if d in ("os.environ", "environ"):
                return "environment variable: os.environ[...]"
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            return self._lookup(node)
        return None

    def _call_taint(self, node: ast.Call, d: str) -> Optional[str]:
        """Taint of a call's RETURN value: module-local functions via
        ``tainted_returns`` (through the shared ``partial``/name alias
        map), imported ones via the linker-planted ``external_calls``."""
        name = last_name(node.func)
        if name is None and isinstance(node.func, ast.Call) \
                and _is_partial(node.func) and node.func.args:
            # partial(f, ...)(...) called inline
            name = last_name(node.func.args[0])
            d = dotted_name(node.func.args[0]) or (name or "")
        if name is None:
            return None
        base = self._fn_aliases.get(name, name)
        if base == name:
            val = self._value_aliases.get(name)
            if isinstance(val, ast.Call) and _is_partial(val) and val.args:
                base = dotted_name(val.args[0]) or name
        if base != name:
            # the alias resolved to a DIRECT taint source spelled out
            # (who = functools.partial(jax.process_index); who())
            for suffix, what in _TAINT_CALLS.items():
                if base == suffix or base.endswith("." + suffix):
                    return f"{what}: {base}(...)"
            if base.startswith(_TAINT_RANDOM_PREFIXES):
                return f"host RNG: {base}(...)"
        scope = self.ctx.enclosing_qualname(node)
        scope = "" if scope == "<module>" else scope
        resolved = self.ctx.resolve_function(base, scope)
        if resolved is not None:
            r = self.tainted_returns.get(resolved)
            if r is not None:
                return f"return of {resolved} ({r})"
            return None
        for spelling in (d, base):
            r = self.external_calls.get(spelling)
            if r is not None:
                return f"return of {r}"
        return None


# ------------------------------------------------------ thread reachability
#: Constructors whose function-valued ``target=`` starts a new host
#: thread (``threading.Thread``/``Timer``); matched by last dotted
#: component like the trace entrypoints.
_THREAD_CTORS = {"Thread", "Timer"}

#: Entry kinds, in reporting-priority order: ``signal`` (an async
#: signal handler — may run between any two bytecodes of the main
#: thread), ``callback`` (an ``on_*`` seam — the watchdog/preemption
#: hooks, invoked FROM a monitor thread), ``thread`` (an explicit
#: ``Thread(target=...)``), ``executor`` (``pool.submit``).
_ENTRY_KIND_ORDER = ("signal", "callback", "thread", "executor")


class ThreadIndex:
    """Per-module thread-reachability: which functions can execute on a
    host thread OTHER than the main one — the fact the concurrency
    rules (APX114/115/116) are driven by.

    Entry discovery (seeds): ``threading.Thread(target=f)`` /
    ``Timer(t, f)``, executor ``.submit(f, ...)``, ``signal.signal(SIG,
    f)`` handlers, and any ``on_*=`` keyword callback (the watchdog/
    preemption/supervisor hook seams — ``on_fire``/``on_wedge``/
    ``on_preempt`` run on the monitor thread or inside a signal
    handler).  Each reachable function carries its entry KINDS with
    human-readable reasons; reachability propagates through nested
    defs and the module-local call graph exactly like the traced
    index, and :func:`link_threads` runs the same import-resolved
    cross-module fixpoint.

    Quiet-on-unknown holds in the inverse direction here: an
    over-approximated entry (an ``on_*`` callback that happens to run
    on the main thread) only ENABLES the rules, and each rule demands
    independent evidence of shared-state discipline (a lock held at
    some OTHER access site) before it fires."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        # share the axis-scope index's alias maps so Thread(target=g)
        # resolves `g = partial(f, ...)` the same way everywhere
        self._fn_aliases = scope_index(ctx)._fn_aliases
        #: qualname -> {kind: reason}
        self.reachable: Dict[str, Dict[str, str]] = {}
        #: Lambda node -> {kind: reason} (lambda targets, by identity)
        self.lambda_reachable: Dict[ast.Lambda, Dict[str, str]] = {}
        #: (value_node, kind, reason, scope) per entry site — kept for
        #: :meth:`exports` (a target resolving through an import)
        self._entry_values: List[Tuple[ast.AST, str, str, str]] = []
        self._seed()
        self._fixpoint()

    def size(self) -> int:
        return (sum(len(k) for k in self.reachable.values())
                + sum(len(k) for k in self.lambda_reachable.values()))

    # ------------------------------------------------------------ seeding
    def _add(self, qualname: str, kinds: Dict[str, str]) -> bool:
        cur = self.reachable.setdefault(qualname, {})
        before = len(cur)
        for k, r in kinds.items():
            cur.setdefault(k, r)
        return len(cur) != before

    def _add_lambda(self, lam: ast.Lambda, kinds: Dict[str, str]) -> bool:
        cur = self.lambda_reachable.setdefault(lam, {})
        before = len(cur)
        for k, r in kinds.items():
            cur.setdefault(k, r)
        return len(cur) != before

    def _seed_value(self, value: ast.AST, kind: str, reason: str,
                    scope: str) -> None:
        self._entry_values.append((value, kind, reason, scope))
        if isinstance(value, ast.Lambda):
            self._add_lambda(value, {kind: reason})
            return
        if isinstance(value, ast.Call) and _is_partial(value) and value.args:
            self._seed_value(value.args[0], kind, reason, scope)
            return
        name = None
        if isinstance(value, ast.Name):
            name = self._fn_aliases.get(value.id, value.id)
        elif isinstance(value, ast.Attribute):
            name = last_name(value)
        if name is None:
            return
        resolved = self.ctx.resolve_function(name, scope)
        if resolved is not None:
            self._add(resolved, {kind: reason})
        elif isinstance(value, ast.Attribute):
            # a BOUND METHOD reference (acc.spill, self._persist): no
            # lexical match — mark every class method of that name
            # (over-approximate; the rules demand independent locking
            # evidence before firing, so breadth only ENABLES them)
            for qn in self._method_qualnames(name):
                self._add(qn, {kind: reason})

    def _method_qualnames(self, name: str) -> List[str]:
        suffix = "." + name
        return [qn for qn in self.ctx.functions if qn.endswith(suffix)]

    def _seed(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Assign):
                # wd.on_wedge = handler / self.on_fire = hook: the
                # assignment spelling of the callback seam
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr.startswith("on_"):
                        scope = self.ctx.enclosing_qualname(node)
                        scope = "" if scope == "<module>" else scope
                        self._seed_value(
                            node.value, "callback",
                            f"assigned to the `{tgt.attr}` callback "
                            f"seam", scope)
                continue
            if not isinstance(node, ast.Call):
                continue
            scope = self.ctx.enclosing_qualname(node)
            scope = "" if scope == "<module>" else scope
            name = last_name(node.func)
            if name in _THREAD_CTORS:
                target = _kwarg(node, "target")
                if target is None and name == "Timer" and len(node.args) > 1:
                    target = node.args[1]
                if target is not None:
                    self._seed_value(target, "thread",
                                     f"threading.{name}(target=...)", scope)
            elif name == "submit" and isinstance(node.func, ast.Attribute) \
                    and node.args:
                self._seed_value(node.args[0], "executor",
                                 "executor .submit(...)", scope)
            elif name == "signal" and isinstance(node.func, ast.Attribute) \
                    and len(node.args) >= 2:
                self._seed_value(node.args[1], "signal",
                                 "installed as a signal handler "
                                 "(signal.signal)", scope)
            for kw in node.keywords:
                if kw.arg and kw.arg.startswith("on_"):
                    self._seed_value(kw.value, "callback",
                                     f"passed as the `{kw.arg}=` "
                                     f"callback seam", scope)

    # ----------------------------------------------------------- fixpoint
    def _fixpoint(self) -> None:
        changed = True
        while changed:
            changed = False
            for lam, kinds in list(self.lambda_reachable.items()):
                scope = self.ctx.enclosing_qualname(lam)
                scope = "" if scope == "<module>" else scope
                if self._propagate_body(lam.body, scope, kinds):
                    changed = True
            for qn in list(self.reachable):
                kinds = self.reachable[qn]
                info = self.ctx.functions.get(qn)
                if info is None or not kinds:
                    continue
                derived = {k: f"reached from thread entry {qn} ({r})"
                           for k, r in kinds.items()}
                for other_qn in self.ctx.functions:
                    if other_qn.startswith(qn + "."):
                        if self._add(other_qn, derived):
                            changed = True
                if self._propagate_body(info.node, qn, derived):
                    changed = True

    def _propagate_body(self, body: ast.AST, scope: str,
                        kinds: Dict[str, str]) -> bool:
        changed = False
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            callee = last_name(sub.func)
            if callee is None:
                continue
            resolved = self.ctx.resolve_function(
                self._fn_aliases.get(callee, callee), scope)
            if resolved is not None:
                if self._add(resolved, kinds):
                    changed = True
            elif isinstance(sub.func, ast.Attribute):
                # method call with no lexical match (rec.dump(...)):
                # mark same-named class methods (see _seed_value)
                for qn in self._method_qualnames(callee):
                    if self._add(qn, kinds):
                        changed = True
        return changed

    # ------------------------------------------------------- cross-module
    def exports(self) -> List[Tuple[str, str, str, str]]:
        """(module, func, kind, reason) seeds this module plants into
        OTHER modules: entry targets resolving through imports
        (``Thread(target=other.f)``) and cross-module calls inside
        thread-reachable bodies."""
        out: List[Tuple[str, str, str, str]] = []
        scope_idx = scope_index(self.ctx)
        for value, kind, reason, scope in self._entry_values:
            hits: List[Tuple[str, str, FrozenSet]] = []
            scope_idx._export_value(value, set(), scope, hits)
            for mod, attr, _ss in hits:
                out.append((mod, attr, kind, reason))
            if isinstance(value, ast.Attribute) \
                    and isinstance(value.value, ast.Name):
                self._export_bound_method(
                    value.value.id, value.attr, kind, reason, out)
        for qn, kinds in self.reachable.items():
            info = self.ctx.functions.get(qn)
            if info is None or not kinds:
                continue
            self._export_calls(info.node, qn, kinds, out)
        for lam, kinds in self.lambda_reachable.items():
            scope = self.ctx.enclosing_qualname(lam)
            scope = "" if scope == "<module>" else scope
            self._export_calls(lam, scope, kinds, out)
        return out

    def _export_bound_method(self, var: str, meth: str, kind: str,
                             reason: str,
                             out: List[Tuple[str, str, str, str]]) -> None:
        """``Thread(target=acc.spill)`` where ``acc = Acc()`` and
        ``Acc`` is imported: export (module of Acc, ``Acc.spill``).
        The instance-construction assignment is matched anywhere in
        the module (flow-insensitive, like every alias map here)."""
        for node in ast.walk(self.ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == var
                    and isinstance(node.value, ast.Call)):
                continue
            d = dotted_name(node.value.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) == 1:
                scope = self.ctx.enclosing_qualname(node)
                scope = "" if scope == "<module>" else scope
                tgt = scope_index(self.ctx)._import_target(parts[0], scope)
                if tgt is not None:
                    out.append((tgt[0], f"{tgt[1]}.{meth}", kind, reason))
            elif parts[0] in self.ctx.import_aliases:
                mod = ".".join([self.ctx.import_aliases[parts[0]]]
                               + parts[1:-1])
                out.append((mod, f"{parts[-1]}.{meth}", kind, reason))

    def _export_calls(self, body: ast.AST, scope: str,
                      kinds: Dict[str, str],
                      out: List[Tuple[str, str, str, str]]) -> None:
        derived = {k: f"called (cross-module) from thread-reachable "
                      f"{self.ctx.module_name or self.ctx.path}:{scope} "
                      f"({r})" for k, r in kinds.items()}
        for sub in ast.walk(body):
            if not isinstance(sub, ast.Call):
                continue
            d = dotted_name(sub.func)
            if d is None:
                continue
            parts = d.split(".")
            if len(parts) == 1:
                tgt = scope_index(self.ctx)._import_target(parts[0], scope)
                if tgt is not None:
                    for k, r in derived.items():
                        out.append((*tgt, k, r))
                continue
            head, attr = parts[:-1], parts[-1]
            if head[0] in self.ctx.import_aliases:
                mod = ".".join([self.ctx.import_aliases[head[0]]] + head[1:])
            elif head[0] in self.ctx.from_imports:
                m0, a0 = self.ctx.from_imports[head[0]]
                mod = ".".join([f"{m0}.{a0}" if m0 else a0] + head[1:])
            else:
                continue  # x.y() on a non-import: a method call, not a
                # cross-module function — self.foo() must not export
            for k, r in derived.items():
                out.append((mod, attr, k, r))

    def mark_external(self, qualname: str, kinds: Dict[str, str]) -> bool:
        """Seed a function's thread-entry kinds from ANOTHER module and
        re-run the local fixpoint; True if anything new was recorded."""
        if qualname not in self.ctx.functions:
            return False
        if not self._add(qualname, dict(kinds)):
            return False
        self._fixpoint()
        return True

    # -------------------------------------------------------------- query
    def kinds_of(self, qualname: str) -> Dict[str, str]:
        """Entry kinds of one function (``{}`` = main-thread-only as
        far as this pass can see)."""
        return self.reachable.get(qualname, {})

    def kinds_at(self, node: ast.AST) -> Dict[str, str]:
        """Entry kinds of the innermost thread-reachable function (or
        lambda) lexically enclosing ``node``."""
        fn = self.ctx.enclosing_function(node)
        while fn is not None:
            if isinstance(fn, ast.Lambda):
                kinds = self.lambda_reachable.get(fn)
            else:
                kinds = self.reachable.get(self.ctx.enclosing_qualname(fn))
            if kinds:
                return kinds
            fn = self.ctx.enclosing_function(fn)
        return {}

    def thread_reason(self, node: ast.AST) -> Optional[str]:
        """Why the code around ``node`` can run off the main thread, or
        None — highest-priority entry kind first (signal > callback >
        thread > executor)."""
        kinds = self.kinds_at(node)
        for k in _ENTRY_KIND_ORDER:
            if k in kinds:
                return kinds[k]
        return None


def thread_index(ctx: ModuleContext) -> ThreadIndex:
    """The (cached) thread-reachability index of one module.  For
    multi-file runs, :func:`link_threads` must run first so entry
    targets and thread-side callees that live in other modules are
    linked in (same contract as the traced/scope/taint indexes)."""
    idx = getattr(ctx, "_thread_index", None)
    if idx is None:
        idx = ThreadIndex(ctx)
        ctx._thread_index = idx
    return idx


def link_threads(ctxs: Dict[str, Optional[ModuleContext]]) -> None:
    """Cross-module thread-reachability fixpoint, mirroring
    :func:`link_axis_scopes`: a function handed to ``Thread(target=)``
    /``signal.signal``/an ``on_*`` seam in another module — or called
    from thread-reachable code there — is thread-reachable here too.
    Monotone (kinds are only ever added); ambiguous module names (None
    entries) are never linked through; each module's export list is
    recomputed only when its reachable count grew."""
    live = [c for c in ctxs.values() if c is not None]
    for c in live:
        thread_index(c)
    memo: Dict[int, Tuple[int, list]] = {}
    changed = True
    while changed:
        changed = False
        for c in live:
            idx = thread_index(c)
            n = idx.size()
            if memo.get(id(c), (-1,))[0] != n:
                memo[id(c)] = (n, idx.exports())
            for mod, attr, kind, reason in memo[id(c)][1]:
                target = ctxs.get(mod)
                if target is None or target is c:
                    continue
                if thread_index(target).mark_external(attr, {kind: reason}):
                    changed = True


def taint_index(ctx: ModuleContext) -> TaintIndex:
    """The (cached) taint index of one module.  For multi-file runs,
    :func:`link_taint` must run first so imported taint-returning
    helpers are linked in (same contract as the traced and axis-scope
    indexes)."""
    idx = getattr(ctx, "_taint_index", None)
    if idx is None:
        idx = TaintIndex(ctx)
        ctx._taint_index = idx
    return idx


def taint_reason(ctx: ModuleContext, expr: ast.AST) -> Optional[str]:
    """Why ``expr``'s value can differ across the processes of one pod,
    or None — the divergence rules' one query."""
    return taint_index(ctx).taint_of(expr)


def link_taint(ctxs: Dict[str, Optional[ModuleContext]]) -> None:
    """Cross-module taint fixpoint, mirroring :func:`link_axis_scopes`:
    a function imported from a module whose taint index proved its
    return rank-divergent taints every call spelling here.  Monotone —
    spellings are only ever added — so a taint cycle between modules
    converges instead of oscillating.  Ambiguous module names (None
    entries) are never linked through."""
    live = [c for c in ctxs.values() if c is not None]
    for c in live:
        taint_index(c)
    changed = True
    while changed:
        changed = False
        for c in live:
            idx = taint_index(c)
            for local, (mod, attr) in c.from_imports.items():
                if not mod:
                    continue
                src = ctxs.get(mod)
                if src is None or src is c:
                    continue
                r = taint_index(src).tainted_returns.get(attr)
                if r is not None and idx.mark_external(
                        local, f"{mod}.{attr} ({r})"):
                    changed = True
            for alias, mod in c.import_aliases.items():
                src = ctxs.get(mod)
                if src is None or src is c:
                    continue
                for qn, r in list(taint_index(src).tainted_returns.items()):
                    if "." in qn:
                        continue
                    if idx.mark_external(f"{alias}.{qn}",
                                         f"{mod}.{qn} ({r})"):
                        changed = True
