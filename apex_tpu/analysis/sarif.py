"""SARIF 2.1.0 output for editor/CI integrations.

One ``run`` per invocation; every rule that produced a finding gets a
``reportingDescriptor`` (id + help text from its class docstring and
fix hint), every finding a ``result`` with a physical location.  The
shape follows the OASIS SARIF 2.1.0 schema closely enough for GitHub
code scanning and the VS Code SARIF viewer; suppressed (baselined)
findings are emitted with ``suppressions`` so consumers can tell
"clean" from "suppressed" — the same distinction the text format's
stderr summary draws.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Sequence

from apex_tpu.analysis.core import Finding, Rule

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

#: analyzer severity -> SARIF result level
_LEVELS = {"error": "error", "warning": "warning", "info": "note"}


def _rule_descriptor(rule_id: str, rules: Sequence[Rule]) -> dict:
    for rule in rules:
        if rule.rule_id == rule_id:
            doc = (rule.__doc__ or "").strip().splitlines()
            short = doc[0].strip() if doc else rule_id
            return {
                "id": rule_id,
                "shortDescription": {"text": short},
                "help": {"text": rule.fix_hint or short},
                "defaultConfiguration": {
                    "level": _LEVELS.get(rule.severity, "warning")},
            }
    return {"id": rule_id, "shortDescription": {"text": rule_id}}


def _fingerprint(f: Finding) -> str:
    """Line-independent identity of a finding: rule + path + enclosing
    symbol + message.  Code scanning matches results across commits by
    ``partialFingerprints`` — keying on the LINE would re-open every
    alert whenever an unrelated edit above the finding shifts it."""
    path = f.path.replace("\\", "/")
    blob = f"{f.rule}:{path}:{f.symbol}:{f.message}"
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _result(f: Finding, rule_index: Dict[str, int],
            suppressed: bool) -> dict:
    out = {
        "ruleId": f.rule,
        "ruleIndex": rule_index[f.rule],
        "level": _LEVELS.get(f.severity, "warning"),
        "message": {"text": f"{f.message}\nfix: {f.fix_hint}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f.path.replace("\\", "/"),
                    "uriBaseId": "SRCROOT",
                },
                "region": {
                    # SARIF columns are 1-based; ast's are 0-based
                    "startLine": max(f.line, 1),
                    "startColumn": f.col + 1,
                },
            },
            "logicalLocations": [{
                "fullyQualifiedName": f.symbol,
                "kind": "function",
            }],
        }],
        "partialFingerprints": {
            "apexContextHash/v1": _fingerprint(f),
        },
    }
    if suppressed:
        out["suppressions"] = [{
            "kind": "external",
            "justification": "analysis_baseline.json entry",
        }]
    return out


def render(kept: Iterable[Finding], suppressed: Iterable[Finding],
           rules: Sequence[Rule]) -> dict:
    """The SARIF log object (plain dict — callers json.dump it)."""
    kept, suppressed = list(kept), list(suppressed)
    rule_ids = sorted({f.rule for f in kept + suppressed})
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results: List[dict] = [
        _result(f, rule_index, suppressed=False) for f in kept
    ] + [
        _result(f, rule_index, suppressed=True) for f in suppressed
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "apex_tpu.analysis",
                "informationUri": "docs/static_analysis.md",
                "rules": [_rule_descriptor(rid, rules)
                          for rid in rule_ids],
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }
