"""ResNet-50 — the canonical amp-O2 workload.

Reference: ``examples/imagenet/main_amp.py`` trains torchvision
ResNet-50 under ``amp.initialize(O2)`` + apex DDP; the reference's SyncBN
and DDP tests all use this model family.

TPU-first: NHWC layout (TPU conv layout), bf16 compute with fp32
BatchNorm (the O2 ``keep_batchnorm_fp32`` rule), flax modules, and
:class:`apex_tpu.parallel.SyncBatchNorm` when stats must sync across the
``dp`` axis under shard_map.
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    projection: bool = False
    norm: Callable = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1))(y)
        y = self.norm()(y, use_running_average=not train)
        if self.projection:
            residual = conv(self.features * 4, (1, 1), strides=(self.strides, self.strides))(x)
            residual = self.norm()(residual, use_running_average=not train)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    sync_bn_axis: Optional[str] = None  # "dp" to sync under shard_map

    @nn.compact
    def __call__(self, x, train: bool = True):
        def norm_factory(features=None):
            # SyncBatchNorm with axis=None degrades to local BN; stats fp32
            # (O2 keep_batchnorm_fp32 semantics)
            class _N(nn.Module):
                feats: int

                @nn.compact
                def __call__(self_inner, h, use_running_average=False):
                    return SyncBatchNorm(
                        num_features=h.shape[-1],
                        axis_name=self.sync_bn_axis,
                        channel_last=True,
                        momentum=0.1,
                    )(h, use_running_average=use_running_average)

            return _N(feats=features or 0)

        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = norm_factory()(x, use_running_average=not train)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for b in range(block_count):
                strides = 2 if i > 0 and b == 0 else 1
                x = Bottleneck(
                    features=self.width * 2 ** i,
                    strides=strides,
                    projection=(b == 0),
                    norm=norm_factory,
                    dtype=self.dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32)(x)
        return x


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], **kw)


def ResNet18ish(**kw) -> ResNet:
    """Small variant for tests."""
    return ResNet(stage_sizes=[1, 1], width=16, **kw)
