"""BERT — bidirectional encoder LM (the FusedLAMB pretraining workload).

Reference: ``apex/transformer/testing/standalone_bert.py`` (Megatron
BertModel used by test_bert_minimal.py) and BASELINE config 5
(BERT-large + FusedLAMB + O2).

Same TPU-first skeleton as :mod:`apex_tpu.models.gpt` — (seq, batch,
hidden) activations, scan over stacked layers, one code path for dense
and tensor-parallel — with bidirectional attention under a padding mask
and the MLM head (binary NSP head omitted; modern recipes drop it and
the reference's test path exercises MLM loss).
"""

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.models._remat import remat_layer, validate_policy
import numpy as np

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.ops.attention import flash_attention
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 512
    type_vocab_size: int = 2
    ffn_hidden_size: Optional[int] = None
    layernorm_eps: float = 1e-12
    compute_dtype: Any = jnp.bfloat16
    checkpoint_layers: bool = True
    # "full" | "dots" — see apex_tpu.models._remat
    remat_policy: str = "full"
    # chunked fused MLM-head+CE (ops/fused_ce.py; see GPTConfig.fused_ce)
    fused_ce: bool = False
    fused_ce_chunk: int = 128
    fused_ce_impl: Optional[str] = None  # see GPTConfig.fused_ce_impl

    def __post_init__(self):
        validate_policy(self.remat_policy)

    @property
    def ffn(self):
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def init_params(config: BertConfig, key) -> Dict[str, Any]:
    H, F, L, V = config.hidden_size, config.ffn, config.num_layers, config.vocab_size
    k = jax.random.split(key, 10)
    std = 0.02
    init = lambda kk, *s: jax.random.normal(kk, s, jnp.float32) * std
    return {
        "embed": init(k[0], V, H),
        "pos_embed": init(k[1], config.max_seq_len, H),
        "type_embed": init(k[2], config.type_vocab_size, H),
        "embed_ln_scale": jnp.ones((H,)),
        "embed_ln_bias": jnp.zeros((H,)),
        "layers": {
            "wq": init(k[3], L, H, H),
            "wk": init(k[4], L, H, H),
            "wv": init(k[5], L, H, H),
            "bq": jnp.zeros((L, H)),
            "bk": jnp.zeros((L, H)),
            "bv": jnp.zeros((L, H)),
            "wo": init(k[6], L, H, H) / np.sqrt(2 * L),
            "bo": jnp.zeros((L, H)),
            "ln1_scale": jnp.ones((L, H)),
            "ln1_bias": jnp.zeros((L, H)),
            "fc1": init(k[7], L, F, H),
            "fc1_b": jnp.zeros((L, F)),
            "fc2": init(k[8], L, H, F) / np.sqrt(2 * L),
            "fc2_b": jnp.zeros((L, H)),
            "ln2_scale": jnp.ones((L, H)),
            "ln2_bias": jnp.zeros((L, H)),
        },
        "mlm_dense": init(k[9], H, H),
        "mlm_dense_b": jnp.zeros((H,)),
        "mlm_ln_scale": jnp.ones((H,)),
        "mlm_ln_bias": jnp.zeros((H,)),
    }


def param_specs(config: BertConfig):
    from jax.sharding import PartitionSpec as P

    col, colb, row, rep2 = P(None, "tp", None), P(None, "tp"), P(None, None, "tp"), P(None, None)
    return {
        "embed": P("tp", None),
        "pos_embed": P(None, None),
        "type_embed": P(None, None),
        "embed_ln_scale": P(None),
        "embed_ln_bias": P(None),
        "layers": {
            "wq": col, "wk": col, "wv": col,
            "bq": colb, "bk": colb, "bv": colb,
            "wo": row, "bo": rep2,
            "ln1_scale": rep2, "ln1_bias": rep2,
            "fc1": col, "fc1_b": colb,
            "fc2": row, "fc2_b": rep2,
            "ln2_scale": rep2, "ln2_bias": rep2,
        },
        "mlm_dense": P(None, None),
        "mlm_dense_b": P(None),
        "mlm_ln_scale": P(None),
        "mlm_ln_bias": P(None),
    }


def _attention(x, p, pad_mask, config, axis_name, n_local_heads):
    S, B = x.shape[0], x.shape[1]
    hd = config.head_dim

    def col(x_, w, b):
        if axis_name is None:
            return jnp.matmul(x_, w.T.astype(x_.dtype)) + b.astype(x_.dtype)
        return column_parallel_linear(x_, w, b, gather_output=False, axis_name=axis_name)

    q, k, v = (col(x, p[f"w{n}"], p[f"b{n}"]) for n in "qkv")

    def heads(t):
        return t.reshape(S, B, n_local_heads, hd).transpose(1, 2, 0, 3)

    q, k, v = heads(q), heads(k), heads(v)
    # bidirectional flash attention; pad_mask (B, S) True=valid rides the
    # kernel's key-validity mask — no dense S×S score matrix.
    ctx = flash_attention(q, k, v, causal=False, kv_mask=pad_mask)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, n_local_heads * hd)
    if axis_name is None:
        return jnp.matmul(ctx, p["wo"].T.astype(ctx.dtype)) + p["bo"].astype(ctx.dtype)
    return row_parallel_linear(ctx, p["wo"], p["bo"], input_is_parallel=True, axis_name=axis_name)


def _mlp(x, p, axis_name):
    if axis_name is None:
        h = jnp.matmul(x, p["fc1"].T.astype(x.dtype)) + p["fc1_b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=True)
        return jnp.matmul(h, p["fc2"].T.astype(h.dtype)) + p["fc2_b"].astype(h.dtype)
    h = column_parallel_linear(x, p["fc1"], p["fc1_b"], gather_output=False, axis_name=axis_name)
    h = jax.nn.gelu(h, approximate=True)
    return row_parallel_linear(h, p["fc2"], p["fc2_b"], input_is_parallel=True, axis_name=axis_name)


def _layer(x, p, pad_mask, config, axis_name, n_local_heads):
    # BERT post-LN block
    H = config.hidden_size
    a = _attention(x, p, pad_mask, config, axis_name, n_local_heads)
    x = fused_layer_norm_affine(x + a, p["ln1_scale"], p["ln1_bias"], (H,), config.layernorm_eps)
    m = _mlp(x.astype(config.compute_dtype), p, axis_name)
    x = fused_layer_norm_affine(x + m, p["ln2_scale"], p["ln2_bias"], (H,), config.layernorm_eps)
    return x.astype(config.compute_dtype)


def bert_forward(params, tokens, token_types=None, pad_mask=None,
                 config: BertConfig = None, axis_name=None,
                 return_hidden=False):
    """tokens (B, S) → MLM logits (S, B, V or V/tp); ``return_hidden``:
    the pre-decoder (S, B, H) MLM-head activations instead."""
    B, S = tokens.shape
    tp = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    n_local_heads = config.num_attention_heads // tp

    if axis_name is None:
        emb = jnp.take(params["embed"], tokens, axis=0)
    else:
        emb = vocab_parallel_embedding(tokens, params["embed"], axis_name=axis_name)
    x = emb.transpose(1, 0, 2) + params["pos_embed"][:S][:, None, :]
    if token_types is not None:
        x = x + jnp.take(params["type_embed"], token_types, axis=0).transpose(1, 0, 2)
    x = fused_layer_norm_affine(
        x, params["embed_ln_scale"], params["embed_ln_bias"], (config.hidden_size,), config.layernorm_eps
    )
    x = x.astype(config.compute_dtype)

    layer = partial(
        _layer, pad_mask=pad_mask, config=config, axis_name=axis_name, n_local_heads=n_local_heads
    )
    if config.checkpoint_layers:
        layer = remat_layer(layer, config.remat_policy)
    x, _ = jax.lax.scan(lambda c, lp: (layer(c, lp), None), x, params["layers"])

    # MLM head: dense + gelu + LN + tied decoder
    h = jnp.matmul(x.astype(jnp.float32), params["mlm_dense"].T) + params["mlm_dense_b"]
    h = jax.nn.gelu(h, approximate=True)
    h = fused_layer_norm_affine(
        h, params["mlm_ln_scale"], params["mlm_ln_bias"], (config.hidden_size,), config.layernorm_eps
    )
    if axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
        )

        h = copy_to_tensor_model_parallel_region(h, axis_name)
    if return_hidden:
        return h
    return jnp.matmul(h, params["embed"].T.astype(jnp.float32))


def bert_mlm_loss(params, tokens, targets, loss_mask, config: BertConfig, axis_name=None, pad_mask=None):
    """Mean MLM CE over masked positions (loss_mask (B, S) 1=predict).

    Routes through the ONE head dispatch (models/gpt.lm_head_loss):
    chunked fused CE when ``config.fused_ce`` (the MLM decoder is a
    tied (S,B,H)x(H,V) head exactly like GPT's), dense logits + CE
    otherwise."""
    from apex_tpu.models.gpt import lm_head_loss

    h = bert_forward(params, tokens, pad_mask=pad_mask, config=config,
                     axis_name=axis_name, return_hidden=True)
    t = targets.transpose(1, 0)
    lm = loss_mask.transpose(1, 0).astype(jnp.float32)
    loss = lm_head_loss(h, params["embed"], t, config, axis_name)
    return jnp.sum(loss * lm) / jnp.maximum(jnp.sum(lm), 1.0)
