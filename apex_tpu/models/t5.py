"""T5-style encoder-decoder transformer — the model family behind the
``ModelType.encoder_and_decoder`` path of the reference's pipeline
schedules (``apex/transformer/pipeline_parallel/schedules/common.py:
85-100``, ``fwd_bwd_pipelining_without_interleaving.py:50-84``; the
reference ships the *machinery* for such models, the model itself lives
in Megatron — this module supplies both the machinery driver and a
concrete model so the path is testable end-to-end).

Architecture (kept close to T5 where it doesn't fight the pipeline):

- pre-norm residual blocks with scale-only RMSNorm (T5's norm),
  bias-free projections (T5 has no biases), tied source/target
  embedding reused as the LM head;
- learned absolute position tables per side instead of T5's relative
  position bias: a per-layer bias table would have to ride every
  pipeline hop as a second stream for no scheduling insight;
- each decoder layer RMS-norms the encoder memory with its OWN scale
  (``lnm_scale``) instead of one shared final encoder norm: the
  pipeline forwards the encoder's RAW final hidden state stage to
  stage, so a shared scale would belong to no stage's chunk — a
  per-layer scale is strictly more expressive and keeps every
  parameter either per-chunk or in ``shared_params``.

Tensor parallelism follows the reference recipe (column-parallel
q/k/v + fc1, row-parallel o + fc2, vocab-parallel embedding and cross
entropy — reference ``tensor_parallel/layers.py:174,460,645``);
pipeline parallelism drives the dual-stream tick schedule
(:mod:`...schedules.tick_schedule_encdec`) with the split rank from
``parallel_state`` (reference ``parallel_state.py:538-575``).
"""

import dataclasses
from functools import partial

from apex_tpu.models._remat import remat_layer, validate_policy
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.normalization.fused_layer_norm import fused_rms_norm_affine
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)

__all__ = [
    "T5Config", "init_params", "param_specs", "t5_forward", "t5_loss",
    "make_train_step", "make_pp_train_step", "params_to_pp_layout",
]


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    hidden_size: int = 512
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_attention_heads: int = 8
    max_src_len: int = 512
    max_tgt_len: int = 512
    ffn_hidden_size: Optional[int] = None  # default 4H
    layernorm_eps: float = 1e-6
    compute_dtype: jnp.dtype = jnp.bfloat16
    checkpoint_layers: bool = True
    # "full" | "dots" — see apex_tpu.models._remat
    remat_policy: str = "full"
    # chunked fused LM-head+CE (ops/fused_ce.py; see GPTConfig.fused_ce)
    fused_ce: bool = False
    fused_ce_chunk: int = 128
    fused_ce_impl: Optional[str] = None  # see GPTConfig.fused_ce_impl

    def __post_init__(self):
        validate_policy(self.remat_policy)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size


def init_params(config: T5Config, key):
    """Global (unsharded) fp32 params.  Encoder/decoder layers are
    stacked on a leading layer axis (scan/pipeline layout)."""
    H, F, V = config.hidden_size, config.ffn, config.vocab_size
    ks = jax.random.split(key, 8)

    def w(k, shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[-1])
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    def enc_layer(k, L):
        kk = jax.random.split(k, 6)
        return {
            "ln1_scale": jnp.ones((L, H)),
            "wq": w(kk[0], (L, H, H)),
            "wk": w(kk[1], (L, H, H)),
            "wv": w(kk[2], (L, H, H)),
            "wo": w(kk[3], (L, H, H)),
            "ln2_scale": jnp.ones((L, H)),
            "fc1": w(kk[4], (L, F, H)),
            "fc2": w(kk[5], (L, H, F)),
        }

    def dec_layer(k, L):
        kk = jax.random.split(k, 10)
        return {
            "ln1_scale": jnp.ones((L, H)),
            "wq": w(kk[0], (L, H, H)),
            "wk": w(kk[1], (L, H, H)),
            "wv": w(kk[2], (L, H, H)),
            "wo": w(kk[3], (L, H, H)),
            "lnx_scale": jnp.ones((L, H)),   # cross-attn input norm
            "lnm_scale": jnp.ones((L, H)),   # encoder-memory norm
            "cq": w(kk[4], (L, H, H)),
            "ck": w(kk[5], (L, H, H)),
            "cv": w(kk[6], (L, H, H)),
            "co": w(kk[7], (L, H, H)),
            "ln3_scale": jnp.ones((L, H)),
            "fc1": w(kk[8], (L, F, H)),
            "fc2": w(kk[9], (L, H, F)),
        }

    return {
        "embed": w(ks[0], (V, H), scale=0.02),
        "pos_enc": w(ks[1], (config.max_src_len, H), scale=0.02),
        "pos_dec": w(ks[2], (config.max_tgt_len, H), scale=0.02),
        "enc_layers": enc_layer(ks[3], config.num_encoder_layers),
        "dec_layers": dec_layer(ks[4], config.num_decoder_layers),
        "lnf_scale": jnp.ones((H,)),  # final decoder norm (shared: head)
    }


def param_specs(config: T5Config):
    """PartitionSpecs (tp axis 'tp'): column-parallel shard the output
    dim, row-parallel the input dim, embedding the vocab."""
    from jax.sharding import PartitionSpec as P

    col = P(None, "tp", None)
    row = P(None, None, "tp")
    rep = P(None, None)
    enc = {
        "ln1_scale": rep, "wq": col, "wk": col, "wv": col, "wo": row,
        "ln2_scale": rep, "fc1": col, "fc2": row,
    }
    dec = {
        "ln1_scale": rep, "wq": col, "wk": col, "wv": col, "wo": row,
        "lnx_scale": rep, "lnm_scale": rep,
        "cq": col, "ck": col, "cv": col, "co": row,
        "ln3_scale": rep, "fc1": col, "fc2": row,
    }
    return {
        "embed": P("tp", None),
        "pos_enc": P(), "pos_dec": P(),
        "enc_layers": enc,
        "dec_layers": dec,
        "lnf_scale": P(),
    }


# ---------------------------------------------------------------- layers
def _rms(x, scale, config):
    return fused_rms_norm_affine(
        x, scale, (config.hidden_size,), config.layernorm_eps)


def _heads(t, B, S, nh, hd):
    return t.reshape(S, B, nh, hd).transpose(1, 2, 0, 3)  # (B,nh,S,hd)


def _attn_core(q, k, v, causal, hd):
    scores = jnp.einsum("bnsh,bnth->bnst", q, k) / np.sqrt(hd)
    if causal:
        # the repo's fused causal-softmax path (square S==T self-attn)
        from apex_tpu.transformer.functional.fused_softmax import (
            scaled_upper_triang_masked_softmax,
        )

        probs = scaled_upper_triang_masked_softmax(scores, 1.0)
    else:
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bnst,bnth->bnsh", probs.astype(v.dtype), v)


def _mha(x_q, x_kv, p, prefix, config, axis_name, causal):
    """Multi-head attention (x: (S, B, H)); q from ``x_q``, k/v from
    ``x_kv`` (``None`` = self-attention).  Column-parallel projections,
    row-parallel output."""
    if x_kv is None:
        x_kv = x_q
    Sq, B, _ = x_q.shape
    Skv = x_kv.shape[0]
    hd = config.head_dim
    tp = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    nl = config.num_attention_heads // tp
    names = {"q": prefix[0], "k": prefix[1], "v": prefix[2], "o": prefix[3]}

    def col(x_, w):
        if axis_name is None:
            return jnp.matmul(x_, w.T.astype(x_.dtype))
        return column_parallel_linear(x_, w, None, gather_output=False,
                                      axis_name=axis_name)

    q = _heads(col(x_q, p[names["q"]]), B, Sq, nl, hd)
    k = _heads(col(x_kv, p[names["k"]]), B, Skv, nl, hd)
    v = _heads(col(x_kv, p[names["v"]]), B, Skv, nl, hd)
    ctx = _attn_core(q, k, v, causal, hd)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(Sq, B, nl * hd)
    if axis_name is None:
        return jnp.matmul(ctx, p[names["o"]].T.astype(ctx.dtype))
    return row_parallel_linear(ctx, p[names["o"]], None,
                               input_is_parallel=True, axis_name=axis_name)


def _ffn(x, p, config, axis_name):
    if axis_name is None:
        h = jax.nn.relu(jnp.matmul(x, p["fc1"].T.astype(x.dtype)))
        return jnp.matmul(h, p["fc2"].T.astype(h.dtype))
    h = column_parallel_linear(x, p["fc1"], None, gather_output=False,
                               axis_name=axis_name)
    h = jax.nn.relu(h)
    return row_parallel_linear(h, p["fc2"], None, input_is_parallel=True,
                               axis_name=axis_name)


def encoder_layer(x, p, config: T5Config, axis_name=None):
    cd = config.compute_dtype
    x = x + _mha(_rms(x, p["ln1_scale"], config).astype(cd), None, p,
                 ("wq", "wk", "wv", "wo"), config, axis_name,
                 causal=False)
    x = x + _ffn(_rms(x, p["ln2_scale"], config).astype(cd), p, config,
                 axis_name)
    return x


def decoder_layer(x, enc_out, p, config: T5Config, axis_name=None):
    cd = config.compute_dtype
    x = x + _mha(_rms(x, p["ln1_scale"], config).astype(cd), None, p,
                 ("wq", "wk", "wv", "wo"), config, axis_name, causal=True)
    mem = _rms(enc_out, p["lnm_scale"], config).astype(cd)
    xq = _rms(x, p["lnx_scale"], config).astype(cd)
    x = x + _mha(xq, mem, p, ("cq", "ck", "cv", "co"), config, axis_name,
                 causal=False)
    x = x + _ffn(_rms(x, p["ln3_scale"], config).astype(cd), p, config,
                 axis_name)
    return x


def _embed(tokens, params, pos_key, config, axis_name):
    """(B, S) ids -> (S, B, H) compute-dtype embeddings + positions."""
    if axis_name is None:
        emb = params["embed"][tokens]
    else:
        emb = vocab_parallel_embedding(tokens, params["embed"],
                                       axis_name=axis_name)
    S = tokens.shape[1]
    x = emb.transpose(1, 0, 2) + params[pos_key][:S][:, None, :]
    return x.astype(config.compute_dtype)


def _pre_head(x, params, config, axis_name):
    """Final RMS norm + tp copy-region: the activations the tied head
    consumes, shared by the logits oracle and the fused-CE path."""
    x = fused_rms_norm_affine(x, params["lnf_scale"],
                              (config.hidden_size,), config.layernorm_eps)
    if axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
        )

        x = copy_to_tensor_model_parallel_region(x, axis_name)
    return x


def _lm_head(x, params, config, axis_name):
    """Tied head: (S_tgt, B, H) -> vocab(-parallel) logits fp32."""
    x = _pre_head(x, params, config, axis_name)
    return jnp.matmul(x.astype(jnp.float32),
                      params["embed"].T.astype(jnp.float32))


def _ce(logits, targets, axis_name):
    """targets (B, S) -> mean loss; vocab-parallel CE on a mesh."""
    t = targets.transpose(1, 0)
    if axis_name is None:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        # clamp: same out-of-range semantic as gpt.lm_head_loss (bare
        # take_along_axis wraps negatives / NaN-fills past-V under jit)
        t_cl = jnp.clip(t, 0, logits.shape[-1] - 1)
        tgt = jnp.take_along_axis(logits, t_cl[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - tgt)
    return jnp.mean(vocab_parallel_cross_entropy(logits, t, 0.0, axis_name))


def _head_loss(y, params, targets, config, axis_name):
    """Decoder output -> mean CE through the ONE head dispatch
    (models/gpt.lm_head_loss): fused chunked CE when configured, the
    dense logits oracle otherwise."""
    from apex_tpu.models.gpt import lm_head_loss

    x = _pre_head(y, params, config, axis_name)
    t = targets.transpose(1, 0)
    return jnp.mean(lm_head_loss(x, params["embed"], t, config, axis_name))


# ---------------------------------------------------------------- oracle
def t5_forward(params, src_tokens, dec_tokens, config: T5Config,
               axis_name: Optional[str] = None, return_hidden: bool = False):
    """Full forward: (B, S_src), (B, S_tgt) token ids -> (S_tgt, B, V)
    fp32 logits (``return_hidden``: the pre-head (S_tgt, B, H) decoder
    stream instead).  The single-device (or tp-only) oracle the pipeline
    schedules are parity-tested against."""
    x = _embed(src_tokens, params, "pos_enc", config, axis_name)
    enc = partial(encoder_layer, config=config, axis_name=axis_name)
    if config.checkpoint_layers:
        enc = remat_layer(enc, config.remat_policy)
    x = jax.lax.scan(lambda c, lp: (enc(c, lp), None),
                     x, params["enc_layers"])[0]
    y = _embed(dec_tokens, params, "pos_dec", config, axis_name)
    dec = partial(decoder_layer, config=config, axis_name=axis_name)
    if config.checkpoint_layers:
        dec = remat_layer(dec, config.remat_policy)
    y = jax.lax.scan(lambda c, lp: (dec(c, x, lp), None),
                     y, params["dec_layers"])[0]
    if return_hidden:
        return y  # pre-head decoder stream (S_tgt, B, H)
    return _lm_head(y, params, config, axis_name)


def t5_loss(params, src_tokens, dec_tokens, targets, config: T5Config,
            axis_name: Optional[str] = None):
    y = t5_forward(params, src_tokens, dec_tokens, config, axis_name,
                   return_hidden=True)
    return _head_loss(y, params, targets, config, axis_name)


def make_train_step(config: T5Config, optimizer, mesh=None,
                    tp_axis: str = "tp", dp_axis: Optional[str] = None,
                    donate_state: bool = False):
    """(tp × dp) train step without pipeline parallelism.

    ``donate_state``: donate params/opt-state buffers (see
    models/gpt.make_train_step — callers must rebind every call)."""
    from jax.sharding import PartitionSpec as P

    donate = (0, 1) if donate_state else ()
    if mesh is None:
        def step(params, opt_state, src, dec_in, targets):
            loss, grads = jax.value_and_grad(t5_loss)(
                params, src, dec_in, targets, config)
            params, opt_state = optimizer.update(grads, opt_state, params)
            return params, opt_state, loss

        return jax.jit(step, donate_argnums=donate)

    specs = param_specs(config)

    def local_step(params, opt_state, src, dec_in, targets):
        loss, grads = jax.value_and_grad(t5_loss)(
            params, src, dec_in, targets, config, tp_axis)
        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, dp_axis), grads)
        params, opt_state = optimizer.update(grads, opt_state, params)
        return params, opt_state, loss

    from apex_tpu.optimizers.fused_adam import AdamState

    sspec = AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs, master=None)
    data = P(dp_axis) if dp_axis else P()
    return jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, sspec, data, data, data),
        out_specs=(specs, sspec, P()),
        check_vma=False,
    ), donate_argnums=donate)


# -------------------------------------------------------------- pipeline
def params_to_pp_layout(params, pp: int, split: int):
    """Re-stack enc/dec layers into the padded per-stage SPMD layout
    (:func:`...tick_schedule_encdec.pad_stage_layout_encdec`): encoder
    chunks real on stages < split, decoder chunks real on stages >=
    split, zeros elsewhere.  Shard the results over pp on dim 0."""
    from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule_encdec import (
        pad_stage_layout_encdec,
    )

    enc_p, dec_p = pad_stage_layout_encdec(
        params["enc_layers"], params["dec_layers"], pp, split)
    return {**params, "enc_layers": enc_p, "dec_layers": dec_p}


def make_pp_train_step(
    config: T5Config,
    optimizer,
    mesh,
    num_microbatches: int,
    split: Optional[int] = None,
    tp_axis: str = "tp",
    pp_axis: str = "pp",
    dp_axis: Optional[str] = None,
    loss_scaler=None,
    donate_state: bool = False,
):
    """Encoder-decoder pipeline train step (tp × pp × dp) over the
    dual-stream 1F1B schedule.  ``split`` defaults to
    ``parallel_state.get_pipeline_model_parallel_split_rank()``
    (reference parallel_state.py:538: the rank where encoder hands to
    decoder).  Params (and optimizer state) must be in the
    :func:`params_to_pp_layout` layout.

    ``loss_scaler``: fp16 dynamic loss scaling through the dual-stream
    pipeline (reference ``apex/transformer/amp/grad_scaler.py``): the
    loss head seeds the SCALED backward, found_inf is agreed over tp
    AND pp, and the step signature grows a scaler state —
    ``step(params, opt_state, scaler_state, src, dec_in, targets)``.

    Returns (jitted) ``step(params, opt_state, src, dec_in, targets)
    -> (params, opt_state, loss)`` without a scaler, or
    ``step(params, opt_state, scaler_state, src, dec_in, targets) ->
    (params, opt_state, scaler_state, loss)`` with one; token arrays
    are (B, S) and split into ``num_microbatches`` along B.
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel.schedules.tick_schedule_encdec import (
        forward_backward_pipelining_encdec,
    )

    pp = mesh.shape[pp_axis]
    if split is None:
        try:
            split = parallel_state.get_pipeline_model_parallel_split_rank()
        except RuntimeError:
            split = None  # parallel_state not initialized: require split=
    if split is None:
        raise ValueError(
            "pass split= or initialize_model_parallel(..., "
            "pipeline_model_parallel_split_rank_=...) — an encoder-decoder "
            "pipeline needs the split rank (reference common.py:90)")
    if not (0 < split < pp):
        raise ValueError(f"split must be in (0, {pp}); got {split}")

    base = param_specs(config)

    def pp_spec(spec):
        return P(pp_axis, *spec[1:])

    specs = dict(base)
    for side in ("enc_layers", "dec_layers"):
        specs[side] = jax.tree.map(
            pp_spec, base[side], is_leaf=lambda s: isinstance(s, P))

    def pre_enc_fn(shared, mb):
        return _embed(mb["src"], shared, "pos_enc", config, tp_axis)

    def pre_dec_fn(shared, mb):
        return _embed(mb["dec_in"], shared, "pos_dec", config, tp_axis)

    def enc_stage_fn(chunk, x):
        layer = partial(encoder_layer, config=config, axis_name=tp_axis)
        if config.checkpoint_layers:
            layer = remat_layer(layer, config.remat_policy)
        return jax.lax.scan(lambda c, lp: (layer(c, lp), None), x, chunk)[0]

    def dec_stage_fn(chunk, x, enc_out):
        layer = partial(decoder_layer, config=config, axis_name=tp_axis)
        if config.checkpoint_layers:
            layer = remat_layer(layer, config.remat_policy)
        return jax.lax.scan(
            lambda c, lp: (layer(c, enc_out, lp), None), x, chunk)[0]

    def post_fn(shared, y, mb):
        return _head_loss(y, shared, mb["targets"], config, tp_axis)

    def run_schedule(params, src, dec_in, targets, post_fn_):
        shared = {k: v for k, v in params.items()
                  if k not in ("enc_layers", "dec_layers")}
        B = src.shape[0]
        mb = {
            "src": src.reshape(num_microbatches, B // num_microbatches, -1),
            "dec_in": dec_in.reshape(num_microbatches,
                                     B // num_microbatches, -1),
            "targets": targets.reshape(num_microbatches,
                                       B // num_microbatches, -1),
        }
        loss, (g_sh, g_enc, g_dec) = forward_backward_pipelining_encdec(
            pre_enc_fn, pre_dec_fn, enc_stage_fn, dec_stage_fn, post_fn_,
            shared, params["enc_layers"], params["dec_layers"], mb,
            split=split, axis_name=pp_axis,
        )
        return loss, {**g_sh, "enc_layers": g_enc, "dec_layers": g_dec}

    def dp_sync(loss, grads):
        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, dp_axis), grads)
        return loss, grads

    def local_step(params, opt_state, src, dec_in, targets):
        loss, grads = run_schedule(params, src, dec_in, targets, post_fn)
        loss, grads = dp_sync(loss, grads)
        params, opt_state = optimizer.update(grads, opt_state, params)
        return params, opt_state, loss

    def scaled_local_step(params, opt_state, scaler_state, src, dec_in,
                          targets):
        from apex_tpu.models.gpt import _apply_scaled_update

        scale = scaler_state.loss_scale

        def post_scaled(shared, y, mb_):
            # the schedule seeds backward from post_fn's output:
            # scaling here scales every cotangent in BOTH streams
            return post_fn(shared, y, mb_) * scale

        scaled_loss, grads = run_schedule(params, src, dec_in, targets,
                                          post_scaled)
        loss, grads = dp_sync(scaled_loss / scale, grads)
        # stage- (pp) and tp-sharded grads can overflow on one rank
        # only; every model axis must agree on the skip decision
        params, opt_state, scaler_state = _apply_scaled_update(
            loss_scaler, scaler_state, grads, optimizer, opt_state,
            params, [tp_axis, pp_axis])
        return params, opt_state, scaler_state, loss

    from apex_tpu.optimizers.fused_adam import AdamState

    sspec = AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs, master=None)
    data = P(dp_axis) if dp_axis else P()
    donate = (0, 1) if donate_state else ()
    if loss_scaler is not None:
        return jax.jit(jax.shard_map(
            scaled_local_step, mesh=mesh,
            in_specs=(specs, sspec, P(), data, data, data),
            out_specs=(specs, sspec, P(), P()),
            check_vma=False,
        ), donate_argnums=donate)
    return jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, sspec, data, data, data),
        out_specs=(specs, sspec, P()),
        check_vma=False,
    ), donate_argnums=donate)
