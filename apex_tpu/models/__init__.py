"""Flagship models (reference: ``apex/transformer/testing/standalone_*.py``
and ``examples/imagenet``)."""

from apex_tpu.models import gpt

__all__ = ["gpt", "t5"]


def __getattr__(name):
    if name in ("resnet", "bert", "t5"):
        import importlib

        mod = importlib.import_module(f"apex_tpu.models.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.models' has no attribute {name!r}")
