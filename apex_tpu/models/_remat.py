"""Shared layer-remat policy for the model families.

``"full"`` saves only the layer inputs across the remat boundary (the
reference's activation-checkpoint semantics,
``apex/transformer/tensor_parallel/random.py:236``) — maximum HBM
savings, re-runs the whole layer forward inside the backward.
``"dots"`` (``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``)
keeps MXU (matmul) outputs and recomputes only the cheap elementwise
work — trades a little HBM for skipping the expensive recompute, often
the best step time on TPU where the backward is MXU-bound.
``benchmarks/profile_gpt.py`` measures all three strategies (none /
full / dots) on the chip.
"""

import jax

POLICIES = ("full", "dots")


def validate_policy(policy: str) -> None:
    """Raise at config construction — a typo'd policy must not silently
    fall back to some default remat behavior."""
    if policy not in POLICIES:
        raise ValueError(
            f"remat_policy must be one of {POLICIES} (got {policy!r})")


def remat_layer(layer, policy: str):
    """Wrap a layer fn in ``jax.checkpoint`` under ``policy``."""
    if policy == "dots":
        return jax.checkpoint(
            layer,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    return jax.checkpoint(layer)
