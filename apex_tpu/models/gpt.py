"""GPT — the flagship transformer LM, Megatron-parallel on TPU.

Reference: ``apex/transformer/testing/standalone_gpt.py`` +
``standalone_transformer_lm.py`` (the Megatron LM used by the reference's
transformer tests): vocab-parallel embedding, pre-LN blocks with
column/row-parallel attention and MLP, causal fused softmax,
vocab-parallel cross entropy, sequence parallelism.

TPU-first structure:
- activations are ``(seq, batch, hidden)`` — the Megatron cross-stage
  contract (SURVEY §3.4) and the natural SP layout (seq is dim 0);
- layers are stacked with ``lax.scan`` over a leading layer axis so the
  program compiles once regardless of depth;
- per-layer activation checkpointing via ``jax.checkpoint`` (reference:
  tensor_parallel/random.py:237 CheckpointFunction);
- one code path: ``axis_name=None`` runs dense single-device; with an
  axis name the same functions run inside ``shard_map`` with
  q/k/v/fc1 column-sharded and proj/fc2 row-sharded.
"""

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.models._remat import remat_layer, validate_policy
from apex_tpu.observability.stepstats import offer as _stat_offer
from apex_tpu.transformer.functional import scaled_upper_triang_masked_softmax
from apex_tpu.transformer.tensor_parallel.cross_entropy import vocab_parallel_cross_entropy
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    # grouped-query attention (Megatron's knob name): number of kv-head
    # groups; None = one kv head per q head (standard MHA), 1 = MQA.
    num_query_groups: Optional[int] = None
    # "learned" (absolute table, the reference's standalone GPT) or
    # "rope" (rotary: unbounded length, composes with ring attention)
    position_embedding_type: str = "learned"
    rope_theta: float = 10000.0
    layernorm_eps: float = 1e-5
    compute_dtype: Any = jnp.bfloat16
    checkpoint_layers: bool = True
    # What layer remat may keep: "full" saves only the layer inputs (the
    # reference's tensor_parallel.random.checkpoint semantics — maximum
    # HBM savings, re-runs the whole layer forward in the backward);
    # "dots" saves MXU (matmul) outputs and recomputes only the cheap
    # elementwise/VPU work (LN, gelu, softmax) — trades a little HBM for
    # skipping the expensive recompute, often the best step time on TPU
    # where the backward is MXU-bound.  Ignored when checkpoint_layers
    # is False.
    remat_policy: str = "full"
    sequence_parallel: bool = False
    # memory-efficient attention core (ops.attention.flash_attention);
    # automatic when context parallelism is active
    use_flash_attention: bool = False
    # mixture-of-experts FFN (beyond the reference — SURVEY §2.4 "EP: No").
    # 0 = dense MLP.  Experts shard over the dp axis (EP rides DP) with
    # all_to_all token exchange; see transformer/expert_parallel.py.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # Chunked fused LM-head + CE (ops/fused_ce.py): never materializes
    # the fp32 (S, B, V) logits — ~3.3 GB less HBM traffic per step at
    # 124M/S1024/B8 for one extra head-matmul of recompute in backward.
    # Falls back to the dense head when S % fused_ce_chunk != 0.
    fused_ce: bool = False
    fused_ce_chunk: int = 128
    # Pin the fused-CE implementation ("on" = Pallas kernels, "off" =
    # chunked scan, "interpret" = kernels via the Pallas interpreter);
    # None defers to the platform/env default.  Threaded (not an env
    # var) so an A/B never mutates process-global state under an
    # already-traced step function.
    fused_ce_impl: Optional[str] = None
    # Context-parallel ring attention only: issue each next hop's
    # ppermute BEFORE the current chunk's flash compute so the ICI hop
    # hides behind the per-chunk kernels (ring_attention's ``overlap``
    # knob — fp32-bitwise either way, so this is a pure schedule A/B).
    # Ignored when no cp axis is active.
    cp_overlap: bool = False

    def __post_init__(self):
        # validate at construction so every path (incl. checkpoint-
        # restored params that never call init_params) fails loudly on
        # a typo'd type — an unrecognized value would otherwise
        # silently train with NO positional information
        if self.position_embedding_type not in ("learned", "rope"):
            raise ValueError(
                f"position_embedding_type must be 'learned' or 'rope' "
                f"(got {self.position_embedding_type!r})"
            )
        validate_policy(self.remat_policy)

    @property
    def ffn(self):
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def moe(self):
        return self.moe_num_experts > 0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self):
        if self.num_query_groups is None:
            return self.num_attention_heads
        if self.num_query_groups < 1:
            raise ValueError(
                f"num_query_groups must be >= 1 (got {self.num_query_groups}); "
                "use None for standard multi-head attention"
            )
        return self.num_query_groups


def init_params(config: GPTConfig, key) -> Dict[str, Any]:
    """Global (unsharded) fp32 params; shard via PartitionSpecs from
    :func:`param_specs`."""
    H, F, L, V = config.hidden_size, config.ffn, config.num_layers, config.vocab_size
    k = jax.random.split(key, 12)
    std = 0.02
    init = lambda k, *s: jax.random.normal(k, s, jnp.float32) * std

    if config.num_attention_heads % config.kv_heads != 0:
        raise ValueError(
            f"num_attention_heads ({config.num_attention_heads}) must be "
            f"divisible by num_query_groups ({config.kv_heads})"
        )
    KV = config.kv_heads * config.head_dim  # kv projection width (GQA)
    params = {
        "embed": init(k[0], V, H),
        "layers": {
            "ln1_scale": jnp.ones((L, H)),
            "ln1_bias": jnp.zeros((L, H)),
            "wq": init(k[2], L, H, H),
            "wk": init(k[3], L, KV, H),
            "wv": init(k[4], L, KV, H),
            "bq": jnp.zeros((L, H)),
            "bk": jnp.zeros((L, KV)),
            "bv": jnp.zeros((L, KV)),
            "wo": init(k[5], L, H, H) / np.sqrt(2 * L),
            "bo": jnp.zeros((L, H)),
            "ln2_scale": jnp.ones((L, H)),
            "ln2_bias": jnp.zeros((L, H)),
        },
        "final_ln_scale": jnp.ones((H,)),
        "final_ln_bias": jnp.zeros((H,)),
    }
    if config.position_embedding_type == "learned":
        params["pos_embed"] = init(k[1], config.max_seq_len, H)
    if config.moe:
        from apex_tpu.transformer.expert_parallel import moe_init

        params["layers"]["moe"] = moe_init(
            k[8], H, F, config.moe_num_experts, layers=L
        )
    else:
        params["layers"].update(
            {
                "fc1": init(k[6], L, F, H),
                "fc1_b": jnp.zeros((L, F)),
                "fc2": init(k[7], L, H, F) / np.sqrt(2 * L),
                "fc2_b": jnp.zeros((L, H)),
            }
        )
    return params


def param_specs(config: GPTConfig, ep_axis: Optional[str] = None):
    """PartitionSpecs for shard_map in_specs (tp axis named 'tp').

    Column-parallel weights shard the output dim, row-parallel the input
    dim; embedding shards the vocab (reference layers.py:174,460,645).
    With MoE, expert weights shard over ``ep_axis`` (usually 'dp').
    """
    from jax.sharding import PartitionSpec as P

    col = P(None, "tp", None)
    colb = P(None, "tp")
    row = P(None, None, "tp")
    rep2 = P(None, None)
    layers = {
        "ln1_scale": rep2,
        "ln1_bias": rep2,
        "wq": col,
        "wk": col,
        "wv": col,
        "bq": colb,
        "bk": colb,
        "bv": colb,
        "wo": row,
        "bo": rep2,
        "ln2_scale": rep2,
        "ln2_bias": rep2,
    }
    if config.moe:
        from apex_tpu.transformer.expert_parallel import moe_param_specs

        # ep_axis None = replicated (single-device / no EP)
        layers["moe"] = moe_param_specs(ep_axis, layers=True)
    else:
        layers.update({"fc1": col, "fc1_b": colb, "fc2": row, "fc2_b": rep2})
    specs = {
        "embed": P("tp", None),
        "layers": layers,
        "final_ln_scale": P(None),
        "final_ln_bias": P(None),
    }
    if config.position_embedding_type == "learned":
        specs["pos_embed"] = P(None, None)
    return specs


def _add_pos_embed(x, pos_table, config: GPTConfig, cp_axis):
    """Add the learned position table to (S, B, H) activations — the
    LOCAL sequence chunk's rows when the sequence is cp-sharded.  No-op
    under rope (positions enter as q/k rotations in attention)."""
    if config.position_embedding_type != "learned":
        return x
    S = x.shape[0]
    if cp_axis is not None:
        start = jax.lax.axis_index(cp_axis) * S
        pos = jax.lax.dynamic_slice_in_dim(pos_table, start, S, axis=0)
    else:
        pos = pos_table[:S]
    return x + pos[:, None, :]


def _col_proj(x, w, b, axis_name, sp=False):
    """Column-parallel projection, dense when ``axis_name`` is None —
    the ONE dispatch both the training attention block and the decode
    twin (:func:`forward_decode`) use, so the dense/tp seam cannot
    drift between them."""
    if axis_name is None:
        return jnp.matmul(x, w.T.astype(x.dtype)) + b.astype(x.dtype)
    return column_parallel_linear(
        x, w, b, gather_output=False, sequence_parallel_enabled=sp,
        axis_name=axis_name)


def _attention(x, p, config: GPTConfig, axis_name, n_local_heads, cp_axis=None,
               collect_kv=False):
    """Self attention with column-parallel QKV and row-parallel output
    proj (reference standalone_transformer_lm.py ParallelAttention).
    The core is selectable: fused-softmax einsum (default), flash
    attention, or ring attention when the sequence is sharded over
    ``cp_axis``.  With grouped-query attention
    (``config.num_query_groups``) k/v carry fewer heads; the flash
    kernel reads group-shared kv blocks directly, the einsum/ring paths
    repeat heads."""
    S = x.shape[0] * (1 if not (axis_name and config.sequence_parallel) else jax.lax.axis_size(axis_name))
    B = x.shape[1]
    hd = config.head_dim
    tp = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    if config.kv_heads % tp != 0:
        raise ValueError(
            f"num_query_groups ({config.kv_heads}) must be divisible by the "
            f"tensor-parallel size ({tp}): kv heads shard over tp"
        )
    n_local_kv = config.kv_heads // tp
    sp = config.sequence_parallel and axis_name is not None

    def col(x_, w, b):
        return _col_proj(x_, w, b, axis_name, sp=sp)

    q = col(x, p["wq"], p["bq"])
    k = col(x, p["wk"], p["bk"])
    v = col(x, p["wv"], p["bv"])

    # (S, B, local_heads*hd) → (B, nh, S, hd)
    def heads(t, nh):
        return t.reshape(S, B, nh, hd).transpose(1, 2, 0, 3)

    q, k, v = heads(q, n_local_heads), heads(k, n_local_kv), heads(v, n_local_kv)
    if config.position_embedding_type == "rope":
        from apex_tpu.ops.rope import apply_rope

        # global positions of the LOCAL chunk: with context parallelism
        # each rank rotates its own chunk before k/v ride the ring
        start = 0 if cp_axis is None else jax.lax.axis_index(cp_axis) * S
        positions = start + jnp.arange(S)
        q = apply_rope(q, positions, config.rope_theta)
        k = apply_rope(k, positions, config.rope_theta)
    # the prefill path captures each layer's post-RoPE k/v (B, kv, S, hd)
    # BEFORE any head repeat, so the paged cache stores the group-shared
    # GQA heads exactly as the decode kernels expect them
    kv_out = (k, v) if collect_kv else None
    if cp_axis is not None:
        from apex_tpu.ops.attention import repeat_kv_heads
        from apex_tpu.transformer.context_parallel import ring_attention

        # the ring walks matched head counts; GQA repeats before it
        k, v = repeat_kv_heads(q, k, v)
        ctx = ring_attention(q, k, v, cp_axis, causal=True,
                             overlap=config.cp_overlap).astype(v.dtype)
    elif config.use_flash_attention:
        from apex_tpu.ops.attention import flash_attention

        ctx = flash_attention(q, k, v, causal=True)
    else:
        from apex_tpu.ops.attention import repeat_kv_heads

        k, v = repeat_kv_heads(q, k, v)
        scores = jnp.einsum("bnsh,bnth->bnst", q, k) / np.sqrt(hd)
        probs = scaled_upper_triang_masked_softmax(scores, 1.0)
        ctx = jnp.einsum("bnst,bnth->bnsh", probs.astype(v.dtype), v)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(S, B, n_local_heads * hd)

    if axis_name is None:
        out = jnp.matmul(ctx, p["wo"].T.astype(ctx.dtype)) + p["bo"].astype(ctx.dtype)
    else:
        out = row_parallel_linear(
            ctx, p["wo"], p["bo"], input_is_parallel=True,
            sequence_parallel_enabled=sp, axis_name=axis_name,
        )
    return (out, kv_out) if collect_kv else out


def _mlp(x, p, config: GPTConfig, axis_name):
    sp = config.sequence_parallel and axis_name is not None
    if axis_name is None:
        h = jnp.matmul(x, p["fc1"].T.astype(x.dtype)) + p["fc1_b"].astype(x.dtype)
        h = jax.nn.gelu(h, approximate=True)
        return jnp.matmul(h, p["fc2"].T.astype(h.dtype)) + p["fc2_b"].astype(h.dtype)
    h = column_parallel_linear(
        x, p["fc1"], p["fc1_b"], gather_output=False, sequence_parallel_enabled=sp, axis_name=axis_name
    )
    h = jax.nn.gelu(h, approximate=True)
    return row_parallel_linear(
        h, p["fc2"], p["fc2_b"], input_is_parallel=True, sequence_parallel_enabled=sp, axis_name=axis_name
    )


def _moe_mlp(x, p, config: GPTConfig, ep_axis):
    """Expert-parallel FFN (beyond the reference); x: (S, B, H).
    Experts shard over ``ep_axis``; tp ranks compute replicated."""
    from apex_tpu.transformer.expert_parallel import moe_ffn

    out, aux = moe_ffn(
        x,
        p["moe"],
        top_k=config.moe_top_k,
        capacity_factor=config.moe_capacity_factor,
        ep_axis=ep_axis,
    )
    return out, aux


def _layer(x, p, config: GPTConfig, axis_name, n_local_heads, cp_axis=None,
           ep_axis=None, collect_kv=False):
    """Returns (x, aux) — aux is the MoE load-balancing loss (0 when
    dense).  With ``collect_kv`` the aux slot becomes ``(aux, k, v)``
    with the layer's post-RoPE keys/values (the prefill capture)."""
    H = config.hidden_size
    ln1 = fused_layer_norm_affine(x, p["ln1_scale"], p["ln1_bias"], (H,), config.layernorm_eps)
    attn = _attention(ln1.astype(config.compute_dtype), p, config, axis_name,
                      n_local_heads, cp_axis, collect_kv=collect_kv)
    kv = None
    if collect_kv:
        attn, kv = attn
    x = x + attn
    ln2 = fused_layer_norm_affine(x, p["ln2_scale"], p["ln2_bias"], (H,), config.layernorm_eps)
    if config.moe:
        h, aux = _moe_mlp(ln2.astype(config.compute_dtype), p, config, ep_axis)
    else:
        h = _mlp(ln2.astype(config.compute_dtype), p, config, axis_name)
        aux = jnp.float32(0.0)
    x = x + h
    if collect_kv:
        return x, (aux, kv[0], kv[1])
    return x, aux


def _embed_segment(embed_w, pos_w, tokens, config: GPTConfig, axis_name,
                   cp_axis):
    """Forward segment 1: token lookup + learned positions, cast to the
    compute dtype, SP scatter.  ``(B, S)`` tokens → ``(S, B, H)``.

    The three ``_*_segment`` functions are the seam the backward-
    overlapped gradient sync (``make_train_step(overlap_grad_sync=
    True)``) cuts the model at: each segment gets its own ``jax.vjp`` so
    bucket collectives can issue between segment backwards.  They are
    the SAME functions ``gpt_forward`` composes, so the overlapped
    build's per-op arithmetic is definitionally identical to the
    monolithic one — only collective placement moves."""
    if axis_name is None:
        emb = jnp.take(embed_w, tokens, axis=0)  # (B, S, H)
    else:
        emb = vocab_parallel_embedding(tokens, embed_w, axis_name=axis_name)
    x = _add_pos_embed(emb.transpose(1, 0, 2), pos_w, config, cp_axis)
    x = x.astype(config.compute_dtype)
    if config.sequence_parallel and axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            scatter_to_sequence_parallel_region,
        )

        x = scatter_to_sequence_parallel_region(x, axis_name)
    return x


def _layers_segment(layers_p, x, config: GPTConfig, axis_name, cp_axis,
                    ep_axis, return_kv=False):
    """Forward segment 2: the stacked-layer ``lax.scan`` — returns
    ``(x, ys)`` exactly as the scan does.  Because layers are scanned
    over a stacked leading axis, every ``layers.*`` leaf's gradient
    materializes only when the WHOLE scan backward finishes: the scan
    is one readiness stage, not L of them."""
    tp = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    n_local_heads = config.num_attention_heads // tp
    layer = partial(
        _layer, config=config, axis_name=axis_name,
        n_local_heads=n_local_heads, cp_axis=cp_axis, ep_axis=ep_axis,
        collect_kv=return_kv,
    )
    if config.checkpoint_layers:
        layer = remat_layer(layer, config.remat_policy)

    # _layer's (carry, lp) -> (x, aux) is exactly the scan contract
    return jax.lax.scan(layer, x, layers_p)


def _head_segment(x, ln_scale, ln_bias, config: GPTConfig, axis_name):
    """Forward segment 3: SP gather, final LayerNorm, copy-to-region.
    Returns pre-head hidden states ``(S, B, H)``."""
    if config.sequence_parallel and axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            gather_from_sequence_parallel_region,
        )

        # tensor_parallel_output_grad=False: the head's dx is psum'd by the
        # copy-to-region below, so the backward here must split, not
        # reduce-scatter (reference mappings.py:236-250)
        x = gather_from_sequence_parallel_region(x, axis_name, False)

    x = fused_layer_norm_affine(
        x, ln_scale, ln_bias, (config.hidden_size,), config.layernorm_eps
    )
    # tied LM head over the (local) vocab shard.  The copy-to-region is
    # load-bearing: its backward all-reduces dx across vocab shards
    # (Megatron parallel_lm_logits; reference layers.py:141-156 pairing).
    if axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
        )

        x = copy_to_tensor_model_parallel_region(x, axis_name)
    return x


# Gradient-readiness stage of each top-level param group under the
# segmented (overlapped) backward: the head backward (stage 0) yields
# the final-LN cotangents, the scan backward (stage 1) every stacked
# ``layers.*`` leaf at once, and the embed backward (stage 2) the
# positions plus the tied embedding's lookup half (its head half
# arrives at stage 0 but the leaf is only COMPLETE — summable — after
# stage 2, so the tied embed is last-ready by construction).
_OVERLAP_STAGES = {"final_ln_scale": 0, "final_ln_bias": 0, "layers": 1,
                   "embed": 2, "pos_embed": 2}


def gpt_forward(
    params, tokens, config: GPTConfig, axis_name: Optional[str] = None,
    cp_axis: Optional[str] = None, ep_axis: Optional[str] = None,
    return_aux: bool = False, return_hidden: bool = False,
    return_kv: bool = False,
):
    """tokens (B, S) → logits.

    With ``axis_name``: runs inside shard_map; returns vocab-LOCAL logits
    ``(S, B, V/tp)``.  Without: dense logits ``(S, B, V)``.
    With ``cp_axis`` (context parallelism — a capability beyond the
    reference): tokens are the LOCAL sequence chunk, attention is ring
    attention over the axis, positions are globally offset.
    With MoE (``config.moe_num_experts > 0``), ``ep_axis`` shards the
    experts (EP rides DP); ``return_aux=True`` additionally returns the
    summed load-balancing loss.
    With ``return_kv=True`` a trailing ``(k, v)`` pair is appended —
    each ``(L, B, kv_heads_local, S, head_dim)``, every layer's
    post-RoPE keys/values — the prefill capture the paged-KV serving
    path (:mod:`apex_tpu.inference`) writes into its page pool.
    """
    if cp_axis is not None and config.sequence_parallel:
        raise ValueError("sequence_parallel (tp) and context parallelism both shard "
                         "the sequence; enable one")
    if config.moe and config.sequence_parallel:
        raise ValueError("MoE with Megatron sequence parallelism is not supported: "
                         "expert grads would need an extra tp-psum; use cp instead")
    x = _embed_segment(params["embed"], params.get("pos_embed"), tokens,
                       config, axis_name, cp_axis)
    x, ys = _layers_segment(params["layers"], x, config, axis_name, cp_axis,
                            ep_axis, return_kv=return_kv)
    if return_kv:
        aux_per_layer, kv_k, kv_v = ys
        kv = (kv_k, kv_v)
    else:
        aux_per_layer, kv = ys, None
    aux = jnp.sum(aux_per_layer)

    def _out(*vals):
        return vals + (kv,) if return_kv else (
            vals if len(vals) > 1 else vals[0])

    x = _head_segment(x, params["final_ln_scale"], params["final_ln_bias"],
                      config, axis_name)
    if return_hidden:
        # pre-head activations for the chunked fused CE (fused_ce.py);
        # the copy-to-region above already carries the head's dx
        # all-reduce, so the fused op's local dx composes unchanged
        return _out(x, aux) if return_aux else _out(x)  # (S, B, H)
    logits = jnp.matmul(x.astype(jnp.float32), params["embed"].T.astype(jnp.float32))
    if return_aux:
        return _out(logits, aux)  # (S, B, V_local), scalar
    return _out(logits)  # (S, B, V_local)


def lm_head_loss(x, embed, targets, config: GPTConfig,
                 axis_name: Optional[str] = None):
    """Per-token CE ``(S, B)`` of the tied LM head on pre-head
    activations ``x`` (post final-LN, post copy-to-region in tp mode).

    The ONE dispatch between the dense head (fp32 logits matmul + CE)
    and the chunked fused head (ops/fused_ce.py) — both ``gpt_loss``
    and the pipeline post-stage consume it, so the fallback condition
    and head semantics cannot drift between the two training paths."""
    if config.fused_ce and targets.shape[0] % config.fused_ce_chunk == 0:
        from apex_tpu.ops.fused_ce import fused_lm_head_ce

        return fused_lm_head_ce(x, embed, targets,
                                config.fused_ce_chunk, axis_name,
                                config.fused_ce_impl)
    logits = jnp.matmul(x.astype(jnp.float32), embed.T.astype(jnp.float32))
    if axis_name is None:
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        # clamp: bare take_along_axis WRAPS negative ids and NaN-fills
        # past-V ones under jit — the fused scan and Pallas heads both
        # clamp, and all three paths must share one out-of-range semantic
        t_cl = jnp.clip(targets, 0, logits.shape[-1] - 1)
        tgt = jnp.take_along_axis(logits, t_cl[..., None], axis=-1)[..., 0]
        return lse - tgt
    return vocab_parallel_cross_entropy(logits, targets, 0.0, axis_name)


def _spmd_ce_fwd_impl(logits, target):
    """Dense spelling of the Megatron vocab-parallel CE (see
    ``transformer/tensor_parallel/cross_entropy._fwd_impl``) with every
    collective dropped — max/sum/gather run over the FULL vocab axis.
    Under ``jit`` with the vocab dim sharded, XLA's SPMD partitioner
    re-derives exactly the collectives the shard_map version spells by
    hand (local max + all-reduce-max, masked local gather + all-reduce,
    local sum-exp + all-reduce), which is what makes the
    ``spmd="auto"`` step's loss bitwise-comparable to the shard_map
    oracle — the ``logsumexp`` head in :func:`lm_head_loss` is a
    DIFFERENT formula with a different autodiff backward and can never
    match it."""
    lmax = jnp.max(logits, axis=-1)
    logits = logits - lmax[..., None]
    vocab = logits.shape[-1]
    mask = (target < 0) | (target >= vocab)
    clipped = jnp.clip(target, 0, vocab - 1)
    predicted = jnp.take_along_axis(logits, clipped[..., None], axis=-1)[..., 0]
    predicted = jnp.where(mask, 0.0, predicted)
    exp_logits = jnp.exp(logits)
    sum_exp = jnp.sum(exp_logits, axis=-1)
    loss = jnp.log(sum_exp) - predicted
    softmax = exp_logits / sum_exp[..., None]
    return loss, (softmax, mask, clipped)


@jax.custom_vjp
def _spmd_vocab_ce(logits, target):
    """Per-token CE ``(S, B)`` on fp32 logits ``(S, B, V)`` — the
    GSPMD-native head of :func:`make_train_step` ``spmd="auto"``.  The
    backward is the Megatron ``softmax - onehot`` custom vjp, matching
    ``vocab_parallel_cross_entropy`` term for term so the partitioned
    program and the shard_map oracle run the same arithmetic."""
    return _spmd_ce_fwd_impl(logits, target)[0]


def _spmd_ce_fwd(logits, target):
    return _spmd_ce_fwd_impl(logits, target)


def _spmd_ce_bwd(res, g):
    softmax, mask, clipped = res
    vocab = softmax.shape[-1]
    update = (~mask).astype(softmax.dtype)
    onehot = jax.nn.one_hot(clipped, vocab, dtype=softmax.dtype) * update[..., None]
    grad = (softmax - onehot) * g[..., None]
    return grad.astype(softmax.dtype), None


_spmd_vocab_ce.defvjp(_spmd_ce_fwd, _spmd_ce_bwd)


def gpt_loss_spmd(params, tokens, targets, config: GPTConfig):
    """Mean causal-LM loss of the GSPMD-native step: the DENSE forward
    (no axis names, no collectives — XLA places them from the sharding
    annotations) with the Megatron-formulation CE head
    (:func:`_spmd_vocab_ce`)."""
    hidden = gpt_forward(params, tokens, config, None, None, None,
                         return_hidden=True)
    logits = jnp.matmul(hidden.astype(jnp.float32),
                        params["embed"].T.astype(jnp.float32))
    return jnp.mean(_spmd_vocab_ce(logits, targets.transpose(1, 0)))


def forward_decode(params, tokens, positions, active, kv_pools, page_tables,
                   config: GPTConfig, axis_name: Optional[str] = None,
                   attn_impl: str = "auto", verify_width: int = 1,
                   write_mask=None):
    """Single-token decode forward over the paged KV cache.

    The serving-side twin of :func:`gpt_forward`: same weights, same
    block expression (the LN/projection/MLP helpers are shared, run at
    sequence length 1), but attention is single-query over the page
    pool (:func:`apex_tpu.ops.decode_attention_pallas.decode_attention`)
    and each layer first scatters the current token's post-RoPE k/v
    into its pages.  Every shape is static — batch is the slot count,
    the page-table block is (B, pages_per_seq) — so the jitted step
    compiles ONCE and is reused across all cache lengths and batch
    occupancies (inactive slots are masked, their writes land on the
    reserved garbage page).

    ``tokens``/``positions``/``active``: (B,) current token ids, their
    0-based positions, and the slot-live mask.  ``kv_pools``: the
    ``{"k", "v"}`` pools from :func:`apex_tpu.inference.kv_cache
    .alloc_pools` (kv heads LOCAL under tp).  ``page_tables``:
    (B // verify_width, P) int32.  With ``axis_name`` the projections
    run column/row-parallel inside shard_map exactly as in training
    (kv heads shard over tp, so each rank's pool carries its local
    heads).

    ``verify_width`` W > 1 is the multi-position layout (speculative
    verification, a prefill chunk): rows come in groups of W
    CONSECUTIVE positions of one sequence sharing a page-table row.
    Each layer first scatters ALL W rows' post-RoPE k/v into the pages,
    then every row attends under its OWN causal length (``positions[i]
    + 1``) — row j of a group reads the k/v rows 0..j wrote this very
    step, so the group is exactly a causal block over the paged cache.
    W is static: one compile per width, reused across every
    occupancy / draft-hit / chunk-phase mix.  ``write_mask`` (defaults
    to ``active``) narrows WHICH rows scatter their k/v — attention
    liveness stays ``active`` — so a chunk can recompute a
    shared-prefix position's hidden state without rewriting the shared
    page (the COW discipline).

    Returns ``(hidden, new_pools)`` — hidden (B, H) is the pre-head
    activation (post final-LN, post copy-to-region under tp), the same
    contract as ``gpt_forward(return_hidden=True)``; the caller owns
    the head (fused sampling for serving, the fp32 logits matmul for
    the parity band).
    """
    from apex_tpu.inference.kv_cache import write_decode_kv
    from apex_tpu.ops.decode_attention_pallas import decode_attention

    if config.moe:
        raise NotImplementedError(
            "MoE decode is not wired (expert routing at batch 1 needs "
            "its own capacity plan); see ROADMAP follow-ons")
    if config.sequence_parallel:
        raise ValueError(
            "sequence_parallel shards the sequence axis; a decode step "
            "is one token — build the decode config without it")
    B = tokens.shape[0]
    H = config.hidden_size
    hd = config.head_dim
    tp = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    if config.kv_heads % tp != 0:
        raise ValueError(
            f"num_query_groups ({config.kv_heads}) must be divisible by "
            f"the tensor-parallel size ({tp}): kv heads (and the KV page "
            "pools) shard over tp")
    n_local_heads = config.num_attention_heads // tp
    n_local_kv = config.kv_heads // tp
    positions = positions.astype(jnp.int32)
    lengths = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    if write_mask is None:
        write_mask = active
    if verify_width > 1:
        if B % verify_width != 0:
            raise ValueError(
                f"batch ({B}) must be a multiple of verify_width "
                f"({verify_width})")
        # one table ROW per sequence rides the attention seam as-is
        # (the kernel folds b // width); the scatter wants a row per
        # flattened position
        write_tables = jnp.repeat(page_tables, verify_width, axis=0)
    else:
        write_tables = page_tables

    if axis_name is None:
        emb = jnp.take(params["embed"], tokens, axis=0)  # (B, H)
    else:
        emb = vocab_parallel_embedding(
            tokens[:, None], params["embed"], axis_name=axis_name)[:, 0]
    x = emb[None]  # (1, B, H) — the (S, B, H) layout at S = 1
    if config.position_embedding_type == "learned":
        pos = jnp.take(params["pos_embed"],
                       jnp.clip(positions, 0, config.max_seq_len - 1), axis=0)
        x = x + pos[None]
    x = x.astype(config.compute_dtype)

    def layer(x, inp):
        p, k_pool, v_pool = inp
        ln1 = fused_layer_norm_affine(
            x, p["ln1_scale"], p["ln1_bias"], (H,), config.layernorm_eps)
        h = ln1.astype(config.compute_dtype)
        col = lambda w, b: _col_proj(h, w, b, axis_name)  # noqa: E731
        q = col(p["wq"], p["bq"])[0].reshape(B, n_local_heads, hd)
        k = col(p["wk"], p["bk"])[0].reshape(B, n_local_kv, hd)
        v = col(p["wv"], p["bv"])[0].reshape(B, n_local_kv, hd)
        if config.position_embedding_type == "rope":
            from apex_tpu.ops.rope import apply_rope_at

            q = apply_rope_at(q, positions, config.rope_theta)
            k = apply_rope_at(k, positions, config.rope_theta)
        k_pool, v_pool = write_decode_kv(
            k_pool, v_pool, k, v, write_tables, positions, write_mask)
        ctx = decode_attention(q, k_pool, v_pool, page_tables, lengths,
                               impl=attn_impl, width=verify_width)
        ctx = ctx.astype(config.compute_dtype).reshape(
            1, B, n_local_heads * hd)
        if axis_name is None:
            attn = jnp.matmul(ctx, p["wo"].T.astype(ctx.dtype)) \
                + p["bo"].astype(ctx.dtype)
        else:
            attn = row_parallel_linear(
                ctx, p["wo"], p["bo"], input_is_parallel=True,
                sequence_parallel_enabled=False, axis_name=axis_name)
        x = x + attn
        ln2 = fused_layer_norm_affine(
            x, p["ln2_scale"], p["ln2_bias"], (H,), config.layernorm_eps)
        x = x + _mlp(ln2.astype(config.compute_dtype), p, config, axis_name)
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        layer, x, (params["layers"], kv_pools["k"], kv_pools["v"]))
    x = fused_layer_norm_affine(
        x, params["final_ln_scale"], params["final_ln_bias"], (H,),
        config.layernorm_eps)
    if axis_name is not None:
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
        )

        x = copy_to_tensor_model_parallel_region(x, axis_name)
    return x[0], {"k": new_k, "v": new_v}


def sp_grad_sync(grads, axis_name: str):
    """Sequence-parallel gradient sync: params consumed in the
    seq-sharded region (LN scales/biases and row-parallel biases) see only
    this rank's tokens in backward, so their grads must be summed over tp
    (reference: apex/transformer/layers/layer_norm.py:26 marking +
    Megatron's allreduce_sequence_parallel_gradients)."""
    sp_keys = {"ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias", "bo", "fc2_b"}
    layers = dict(grads["layers"])
    for k in sp_keys:
        layers[k] = jax.lax.psum(layers[k], axis_name)
    return {**grads, "layers": layers}


def clip_sumsq_reduce(specs):
    """The cross-rank Σx² agreement for a global-l2 grad clip inside a
    shard_map step.

    A leaf whose PartitionSpec names mesh axes holds only its LOCAL
    shard of the grads, so the true global norm needs its Σx² psummed
    over exactly those axes — while replicated leaves (every rank holds
    the full grad) must NOT be psummed, or each mesh axis would
    multiply their contribution by its size.  Group the leaves by the
    axis set their spec names, sum each group locally, psum the
    sharded groups over their axes, add.  (Megatron's
    ``clip_grad_norm_`` does the same split via the
    ``tensor_model_parallel`` param attribute; here the PartitionSpecs
    already carry the fact.)  Returns ``reduce(per_leaf_sumsq) ->
    total_sumsq`` for the optimizer's ``sumsq_reduce=`` hook."""
    from jax.sharding import PartitionSpec

    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, PartitionSpec))

    def axes_of(p):
        axes = []
        for e in tuple(p):
            if isinstance(e, (tuple, list)):
                axes.extend(a for a in e if a)
            elif e is not None:
                axes.append(e)
        return frozenset(axes)

    groups: Dict[frozenset, list] = {}
    for i, sp in enumerate(spec_leaves):
        groups.setdefault(axes_of(sp), []).append(i)

    def reduce(sq):
        if len(sq) != len(spec_leaves):
            raise ValueError(
                f"clip_sumsq_reduce built for {len(spec_leaves)} param "
                f"leaves got {len(sq)} sumsq values — param tree and "
                f"spec tree diverged")
        total = jnp.float32(0.0)
        for axes in sorted(groups, key=lambda a: sorted(a)):
            part = jnp.stack([sq[i] for i in groups[axes]]).sum()
            if axes:
                part = jax.lax.psum(part, tuple(sorted(axes)))
            total = total + part
        return total

    return reduce


def _check_zero_axis(zero_opt, optimizer, dp_axis):
    """A ZeRO optimizer's collectives run over ITS ``axis_name`` (or
    hierarchical ``dp_axes``); the step builder's grad calculus (skip
    the dp pmean, add dp to the finite-vote axes) is keyed on
    ``dp_axis``.  A mismatch would silently double- or un-sync the
    grads, so fail at build time.  A hierarchical step
    (``dp_axis=(outer, inner)``) needs an optimizer constructed with
    the SAME ``dp_axes`` split — its two-hop reduce-scatter owns both
    hops — and a hierarchical optimizer refuses a flat step."""
    if not zero_opt:
        return
    opt_axes = getattr(optimizer, "dp_axes", None)
    if isinstance(dp_axis, (tuple, list)):
        dp_axis = tuple(dp_axis)
        if opt_axes is None or tuple(opt_axes) != dp_axis:
            have = (tuple(opt_axes) if opt_axes is not None
                    else getattr(optimizer, "axis_name", None))
            raise ValueError(
                f"the train step's dp axis is the hierarchical split "
                f"{dp_axis!r} but the ZeRO optimizer syncs over "
                f"{have!r}; construct it with dp_axes={dp_axis!r} (the "
                "optimizer owns both hops of the grad sync)")
        return
    if opt_axes is not None:
        raise ValueError(
            f"ZeRO optimizer was built for the hierarchical dp split "
            f"{tuple(opt_axes)!r} but the train step's dp axis is the "
            f"flat {dp_axis!r}; pass dp_axis={tuple(opt_axes)!r} to "
            "make_train_step (or drop the optimizer's dp_axes)")
    opt_axis = getattr(optimizer, "axis_name", None)
    if dp_axis is None or opt_axis != dp_axis:
        raise ValueError(
            f"ZeRO optimizer shards over axis {opt_axis!r} but the train "
            f"step's dp axis is {dp_axis!r}; pass axis_name={dp_axis!r} "
            "to the optimizer (or dp_axis= to the step builder)")


def _clip_reduce_for(optimizer, clip_grad_norm, specs):
    """Shared clip wiring for both step builders: validate the
    optimizer can fold the clip into its fused grad pass, and build
    the spec-driven cross-rank sumsq agreement.  Returns None when no
    clipping is requested."""
    if clip_grad_norm is None:
        return None
    if not getattr(optimizer, "supports_update_scaled", False):
        raise ValueError(
            "clip_grad_norm needs an engine optimizer (OptimizerBase "
            "subclass) — the clip folds into its fused grad pass")
    return clip_sumsq_reduce(specs)


def _apply_scaled_update(loss_scaler, scaler_state, grads, optimizer,
                         opt_state, params, sync_axes,
                         step_guard=None, guard_state=None,
                         clip_grad_norm=None, clip_sumsq=None,
                         presynced=None):
    """The shared unscale → found_inf vote → predicated step → scale
    update tail of both scaled train steps (reference §3.2 ctx-exit:
    ``apex/amp/handle.py:119-158`` + the model-parallel found_inf
    agreement of ``apex/transformer/amp/grad_scaler.py:49,102``).

    With an engine optimizer (:class:`apex_tpu.optimizers.base
    .OptimizerBase`) the whole tail is ONE fused pass over the grad
    buckets — unscale, optional global-l2 clip, and the finite vote
    fold into the optimizer's own grad read (``update_scaled``) instead
    of three separate tree sweeps.  The ZeRO optimizers take the same
    fused route: their ``update_scaled`` folds the unscale, the clip
    (Σx² psummed over the dp shards and, via ``clip_sumsq``, the model
    axes), and the vote into the per-bucket reduce-scattered grad read.
    Optimizers without the capability (``supports_update_scaled``
    False, e.g. contrib ``FusedAdamSWA``) keep the explicit sweep
    composition.

    With a ``step_guard`` (:class:`apex_tpu.resilience.StepGuard`) the
    same agreed predicate also feeds the guard's device-side bad-step
    accounting, and the tuple grows a new guard state — ONE vote drives
    the optimizer skip, the scaler hysteresis, and the abort budget."""
    from apex_tpu.transformer.amp.grad_scaler import sync_found_inf

    if getattr(optimizer, "supports_update_scaled", False):
        # a presynced handoff (overlap_grad_sync: the bucket wires
        # already ran inside the backward, UNSCALED there) only exists
        # for ZeRO engine optimizers, whose update_scaled takes it
        kw = {} if presynced is None else {"presynced": presynced}
        new_params, new_state, finite = optimizer.update_scaled(
            grads, opt_state, params, scale=scaler_state.loss_scale,
            clip_norm=clip_grad_norm, sumsq_reduce=clip_sumsq,
            finite_sync=lambda f: sync_found_inf(f, sync_axes), **kw,
        )
    else:
        grads, finite = loss_scaler.unscale(scaler_state, grads)
        finite = sync_found_inf(finite, sync_axes)
        new_params, new_state = optimizer.update(
            grads, opt_state, params, grads_finite=finite
        )
    _stat_offer("all_finite", finite)
    new_scaler_state = loss_scaler.update(scaler_state, finite)
    if step_guard is None:
        return new_params, new_state, new_scaler_state
    return (new_params, new_state, new_scaler_state,
            step_guard.update(guard_state, finite))


def _apply_guarded_update(grads, optimizer, opt_state, params, sync_axes,
                          step_guard, guard_state, clip_grad_norm=None,
                          clip_sumsq=None, presynced=None):
    """Unscaled step-guard tail: the amp ``all_finite`` predicate alone
    (no loss scaler) gates the optimizer commit and feeds the guard —
    fp32/bf16 runs get the same survive-a-NaN-step semantics the fp16
    path has always had.  Engine optimizers fold the vote (and the
    optional clip) into the update's grad read (``scale=None`` skips
    the unscale)."""
    from apex_tpu.amp.scaler import all_finite
    from apex_tpu.transformer.amp.grad_scaler import sync_found_inf

    if getattr(optimizer, "supports_update_scaled", False):
        kw = {} if presynced is None else {"presynced": presynced}
        new_params, new_state, finite = optimizer.update_scaled(
            grads, opt_state, params, clip_norm=clip_grad_norm,
            sumsq_reduce=clip_sumsq,
            finite_sync=lambda f: sync_found_inf(f, sync_axes), **kw,
        )
    else:
        finite = sync_found_inf(all_finite(grads), sync_axes)
        new_params, new_state = optimizer.update(
            grads, opt_state, params, grads_finite=finite
        )
    _stat_offer("all_finite", finite)
    return new_params, new_state, step_guard.update(guard_state, finite)


def _telemetry_wrap(fn, n_state, has_scaler, telemetry):
    """Wrap one local-step variant with the StepStats observer: a
    :class:`~apex_tpu.observability.StepStats` pytree rides right after
    the scaler/guard states (before the data), accumulating loss, the
    grad norm the fused clip reduction already computed (captured
    through the trace-time :mod:`~apex_tpu.observability.stepstats`
    seam — never a second read of the grads), the agreed finite vote,
    the loss scale, and the param/update norms.  Stats are observers,
    never participants: the wrapped step's params/loss are the
    UNWRAPPED step's, bitwise (pinned in tests/test_observability.py),
    and the wrapper adds no collectives and no host transfers (pinned
    in tests/test_lowered_invariants.py)."""
    from apex_tpu.observability import stepstats as _st

    def wrapped(params, opt_state, *rest):
        states = rest[:n_state]
        stats, tokens, targets = rest[n_state:]
        with _st.capture() as cap:
            out = fn(params, opt_state, *states, tokens, targets)
        loss = out[-1]
        # with a scaler the NEW scaler state sits right after opt_state
        scale = out[2].loss_scale if has_scaler else None
        new_stats = telemetry.accumulate(
            stats, loss=loss, grad_norm=cap.get("grad_norm"),
            finite=cap.get("all_finite"), loss_scale=scale,
            new_params=out[0], old_params=params)
        return (*out[:-1], new_stats, loss)

    return wrapped


def _step_variant(loss_scaler, step_guard, variants, specs, sspec,
                  data_spec, telemetry=None):
    """Pick the local-step variant and its shard_map specs for a
    scaler×guard(×telemetry) combination.  ``variants`` maps
    (has_scaler, has_guard) to the local step fn; each enabled feature
    adds one replicated scalar-state arg (scaler state, then guard
    state, then the StepStats window) between the optimizer state and
    the data, and one replicated output before the loss.  Returns
    ``(fn, in_specs, out_specs, stats_argnum)`` — ``stats_argnum`` is
    the StepStats position (for donation), or None."""
    from jax.sharding import PartitionSpec as P

    fn = variants[(loss_scaler is not None, step_guard is not None)]
    n_state = int(loss_scaler is not None) + int(step_guard is not None)
    stats_argnum = None
    if telemetry is not None:
        fn = _telemetry_wrap(fn, n_state, loss_scaler is not None,
                             telemetry)
        stats_argnum = 2 + n_state
        n_state += 1
    state_specs = (P(),) * n_state
    in_specs = (specs, sspec, *state_specs, data_spec, data_spec)
    out_specs = (specs, sspec, *state_specs, P())
    return fn, in_specs, out_specs, stats_argnum


def _make_gspmd_train_step(
    config: GPTConfig,
    optimizer,
    mesh,
    tp_axis: str,
    dp_axis,
    opt_state_spec,
    donate_state: bool,
    clip_grad_norm,
    loss_scaler=None,
    step_guard=None,
    telemetry=None,
):
    """The ``spmd="auto"`` half of :func:`make_train_step`: ONE jitted
    step with ``NamedSharding`` annotations on a named mesh and not a
    single explicit collective — XLA's SPMD partitioner places them
    (SNIPPETS [3], the pjit/GSPMD route).  The param/state shardings
    are the SAME ``param_specs`` tree the shard_map builder uses, so a
    mesh reshape is a constructor argument instead of a new step
    builder, and the analyzer's sharding tier (APX206/207/208) can see
    every annotation statically.

    Numerics contract (pinned in tests/test_gpt.py): the loss is
    bitwise-equal fp32 to the shard_map oracle's per step; params track
    it to a few float32 ulps of gradient.  Strict param-bitwise across
    the two is not achievable: the tied embedding's two gradient
    contributions (lookup scatter + head dot) are all-reduced SEPARATELY
    by the partitioner but summed before the one pmean in the
    shard_map program — a summation-association difference no source
    spelling removes.  Everything else (LN param grads included — see
    ``normalization.fused_layer_norm._lead_sum``) associates
    identically."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    for knob, why in (
        (config.moe, "MoE (expert all_to_all is a shard_map program)"),
        (config.sequence_parallel, "sequence parallelism (Megatron SP "
         "is an explicit-collective layout)"),
        (config.use_flash_attention, "flash attention (a pallas_call "
         "is opaque to the SPMD partitioner; use the shard_map path)"),
        (config.fused_ce, "fused CE (the chunked/Pallas heads bypass "
         "the GSPMD-native CE twin)"),
    ):
        if knob:
            raise NotImplementedError(
                f"make_train_step(spmd='auto') does not support {why}")
    if isinstance(dp_axis, (tuple, list)):
        raise NotImplementedError(
            "spmd='auto' with a hierarchical dp split is not wired: "
            "XLA places one flat dp sync; use the shard_map path with "
            "dp_axis=(outer, inner)")
    if hasattr(optimizer, "state_partition_spec"):
        raise NotImplementedError(
            "spmd='auto' with a ZeRO optimizer is not wired (its "
            "per-bucket reduce-scatter is an explicit shard_map "
            "program); use the shard_map path")
    if dp_axis is None:
        raise ValueError("spmd='auto' shards the batch over dp_axis; "
                         "pass a mesh axis name")
    if tp_axis != "tp":
        # param_specs spells the tensor axis literally; renaming it is
        # a spec-tree feature, not a builder knob — reject loudly
        # instead of dying inside NamedSharding construction
        raise NotImplementedError(
            f"spmd='auto' requires tp_axis='tp' (got {tp_axis!r}): "
            "param_specs hard-codes the 'tp' axis name in its "
            "PartitionSpecs")
    if dp_axis not in mesh.axis_names or "tp" not in mesh.axis_names:
        raise ValueError(
            f"mesh axes {tuple(mesh.axis_names)} must include "
            f"{dp_axis!r} and 'tp' for the spmd='auto' step")
    if clip_grad_norm is not None \
            and not getattr(optimizer, "supports_update_scaled", False):
        raise ValueError(
            "clip_grad_norm needs an engine optimizer (OptimizerBase "
            "subclass) — the clip folds into its fused grad pass")

    if getattr(optimizer, "use_buckets", False):
        # Engine optimizers run their PER-LEAF path here, not the fused
        # bucket engine: packing differently-sharded leaves into one
        # flat bucket both defeats the sharding (the concat forces
        # all-gathers) and mis-partitions outright — XLA's SPMD pass
        # was observed returning zeroed pack segments for the stacked
        # tp-sharded leaves on the CPU backend (params came back as
        # ``-lr*g``).  Under GSPMD the per-leaf spelling IS the fused
        # one: XLA fuses the elementwise update chains itself.  The
        # caller's optimizer is not mutated.
        import copy

        optimizer = copy.copy(optimizer)
        optimizer.use_buckets = False

    specs = param_specs(config)
    sspec = opt_state_spec
    if sspec is None:
        from apex_tpu.optimizers.fused_adam import AdamState

        sspec = AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs,
                          master=None)

    def shard(tree):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), tree,
                            is_leaf=lambda s: isinstance(s, P))

    pshard = shard(specs)
    sshard = shard(sspec)
    dshard = NamedSharding(mesh, P(dp_axis, None))
    rshard = NamedSharding(mesh, P())

    def grads_of(params, tokens, targets, post_loss):
        loss, grads = jax.value_and_grad(
            lambda p: post_loss(gpt_loss_spmd(p, tokens, targets, config))
        )(params)
        # keep the grads on the param layout: this constraint is what
        # turns the dp batch shard into ONE all-reduce per leaf (the
        # pmean of the shard_map program) instead of a deferred gather
        grads = jax.lax.with_sharding_constraint(grads, pshard)
        return loss, grads

    def local_step(params, opt_state, tokens, targets):
        loss, grads = grads_of(params, tokens, targets, lambda l: l)
        if clip_grad_norm is not None:
            # global arrays: the plain in-optimizer sumsq IS the global
            # norm — no cross-rank sumsq_reduce hook needed
            new_params, new_state = optimizer.update(
                grads, opt_state, params, clip_norm=clip_grad_norm)
        else:
            new_params, new_state = optimizer.update(
                grads, opt_state, params)
        return new_params, new_state, loss

    # scaler/guard variants: global arrays make the finite vote a plain
    # reduction — sync_axes=() turns the shard_map tails' sync_found_inf
    # into the identity, so _apply_*_update serve both builders and the
    # scaler hysteresis / guard accounting cannot drift between them
    def guarded_local_step(params, opt_state, guard_state, tokens, targets):
        loss, grads = grads_of(params, tokens, targets, lambda l: l)
        new_params, new_state, new_guard = _apply_guarded_update(
            grads, optimizer, opt_state, params, (), step_guard,
            guard_state, clip_grad_norm=clip_grad_norm)
        return new_params, new_state, new_guard, loss

    def scaled_local_step(params, opt_state, scaler_state, tokens, targets):
        scaled_loss, grads = grads_of(
            params, tokens, targets,
            lambda l: loss_scaler.scale(scaler_state, l))
        loss = scaled_loss / scaler_state.loss_scale
        new_params, new_state, new_scaler_state = _apply_scaled_update(
            loss_scaler, scaler_state, grads, optimizer, opt_state,
            params, (), clip_grad_norm=clip_grad_norm)
        return new_params, new_state, new_scaler_state, loss

    def guarded_scaled_local_step(params, opt_state, scaler_state,
                                  guard_state, tokens, targets):
        scaled_loss, grads = grads_of(
            params, tokens, targets,
            lambda l: loss_scaler.scale(scaler_state, l))
        loss = scaled_loss / scaler_state.loss_scale
        new_params, new_state, new_scaler_state, new_guard = \
            _apply_scaled_update(
                loss_scaler, scaler_state, grads, optimizer, opt_state,
                params, (), step_guard=step_guard, guard_state=guard_state,
                clip_grad_norm=clip_grad_norm)
        return new_params, new_state, new_scaler_state, new_guard, loss

    fn = {(True, True): guarded_scaled_local_step,
          (True, False): scaled_local_step,
          (False, True): guarded_local_step,
          (False, False): local_step}[
        (loss_scaler is not None, step_guard is not None)]
    n_state = int(loss_scaler is not None) + int(step_guard is not None)
    stats_argnum = None
    if telemetry is not None:
        fn = _telemetry_wrap(fn, n_state, loss_scaler is not None,
                             telemetry)
        stats_argnum = 2 + n_state
        n_state += 1

    donate = (0, 1) if donate_state else ()
    if stats_argnum is not None:
        donate = (*donate, stats_argnum)
    return jax.jit(
        fn,
        in_shardings=(pshard, sshard, *(rshard,) * n_state, dshard, dshard),
        out_shardings=(pshard, sshard, *(rshard,) * n_state, rshard),
        donate_argnums=donate,
    )


def make_train_step(
    config: GPTConfig,
    optimizer,
    mesh,
    tp_axis: str = "tp",
    dp_axis="dp",
    cp_axis: Optional[str] = None,
    opt_state_spec=None,
    loss_scaler=None,
    donate_state: bool = False,
    step_guard=None,
    chaos=None,
    clip_grad_norm=None,
    grad_sync_dtype=None,
    telemetry=None,
    spmd: str = "shard_map",
    overlap_grad_sync: bool = False,
):
    """Build a jitted tp×dp train step over ``mesh``.

    ``overlap_grad_sync``: issue each gradient bucket's sync collective
    INSIDE the backward pass, the moment its cotangents materialize,
    instead of after the whole backward — the backward runs as three
    ``jax.vjp`` segments (head, stacked-layer scan, embedding) and the
    ready buckets' reduce-scatters (ZeRO) or quantized pmeans
    (replicated ``grad_sync_dtype``) are traced between them, so XLA's
    latency-hiding scheduler can run bucket k's collective concurrently
    with the remaining backward dots (the reference's
    ``overlap_grad_sync``/DDP-hook overlap,
    ``distributed_fused_adam.py:2158``).  The segments are the same
    functions the monolithic forward composes, so fp32 loss/params are
    BITWISE identical to the unoverlapped build (pinned in
    tests/test_distributed_optimizers.py); only collective placement
    moves.  Requires a dp grad sync to overlap (a ZeRO optimizer or
    ``grad_sync_dtype``); not wired for MoE, sequence parallelism, cp,
    or ``spmd='auto'``.

    ``spmd``: ``"shard_map"`` (default) builds the explicit-collective
    Megatron program documented below.  ``"auto"`` builds the
    GSPMD-native step instead — plain ``jit`` with ``NamedSharding``
    annotations from the same ``param_specs`` tree and ZERO explicit
    collectives; XLA's SPMD partitioner places them, so new mesh
    shapes need no new step code.  The auto path supports
    ``opt_state_spec``/``donate_state``/``clip_grad_norm`` and — since
    the finite vote needs no collectives on global arrays — the full
    ``loss_scaler``/``step_guard``/``telemetry`` tails; it rejects the
    explicitly-collective features loudly (ZeRO, hierarchical dp, cp,
    MoE, SP, flash/fused-CE kernels, chaos, grad_sync_dtype,
    overlap_grad_sync — see docs/parallelism.md for the migration
    map).  Its loss is
    bitwise-equal fp32 to this builder's per step on the same mesh
    (pinned in tests/test_gpt.py), and its lowering is pinned through
    ``analysis.lowered.assert_sharding``/``assert_spmd_collectives``.

    ``dp_axis``: one mesh axis name (flat data parallelism), ``None``,
    or the HIERARCHICAL ``(outer, inner)`` pair — the dp world split
    over a slow cross-slice axis and a fast intra-slice axis (a pod's
    DCN x ICI topology).  With the pair, the batch shards over both
    axes, the loss pmean runs over the pair, a ZeRO optimizer must be
    constructed with the same ``dp_axes=`` (its two-hop reduce-scatter
    owns the grad sync — cross-slice traffic drops to ``1/dp_inner``),
    and the replicated ``grad_sync_dtype`` knob quantizes the two-hop
    pmean (:mod:`apex_tpu.contrib.optimizers._hierarchical_sync`).

    ``telemetry``: a :class:`apex_tpu.observability.StepTelemetry` — a
    :class:`~apex_tpu.observability.StepStats` window rides the step
    right after the guard state (or scaler state, or in their place):
    ``step(params, opt_state, [scaler], [guard], stats, tokens,
    targets) -> (..., stats, loss)``.  Loss, the global grad norm
    (REUSED from the fused clip reduction — rank-local when
    ``clip_grad_norm`` is off), the finite vote, the loss scale, and
    param/update norms accumulate device-side; fetch the window
    asynchronously with :class:`~apex_tpu.observability.AsyncFetcher`
    and swap in ``telemetry.init()`` — the stats buffers are ALWAYS
    donated (rebind every call).  Telemetry adds zero collectives,
    zero host transfers, and leaves loss/params bitwise identical.

    ``grad_sync_dtype``: quantize the REPLICATED data-parallel
    gradient sync (``int8``/``float8_e4m3fn``/``float8_e5m2``): the dp
    pmean becomes a reduce-scatter + all-gather pair on the wire dtype
    with shared per-block fp32 scales
    (:func:`apex_tpu.contrib.optimizers._quantized_sync
    .quantized_pmean`).  STATELESS — the replicated step has no
    optimizer-state channel, so there is no error-feedback residual
    here; for compressed sync with feedback use a ZeRO optimizer with
    its own ``grad_sync_dtype`` (which owns the dp sync and must not
    also be quantized here — pass the knob to exactly one of the two).

    ``clip_grad_norm``: global-l2 gradient clipping (torch
    ``clip_grad_norm_`` semantics) folded into the optimizer's fused
    grad pass — with an engine optimizer the unscale, the clip norm,
    the finite vote, and the update math share one read of the grads
    instead of four sweeps.  Requires an
    :class:`apex_tpu.optimizers.base.OptimizerBase` optimizer.

    ``opt_state_spec``: PartitionSpec tree for the optimizer state; by
    default the FusedAdam state shape is assumed (m/v mirror the param
    sharding, scalars replicated) and ZeRO optimizers supply their own —
    pass this for other state shapes (e.g. ``SGDState``).

    ``donate_state``: donate the params and optimizer-state buffers to
    the step (``jax.jit`` ``donate_argnums``) — XLA otherwise holds
    input AND output copies (~3x param bytes with Adam) across the
    step.  The caller must rebind both on every call and never touch
    the previous values (the examples do; oracle tests that reuse
    params after stepping must not set this).

    ``loss_scaler``: an :class:`apex_tpu.amp.DynamicLossScaler` /
    ``StaticLossScaler`` — the flagship fp16 path (reference
    ``apex/amp/handle.py:16`` scale_loss × DDP composition).  Backward
    runs on the SCALED loss so half-precision cotangents don't
    underflow; grads are unscaled in fp32, the finite flag is agreed
    across every model-parallel axis (the TP-aware GradScaler semantics,
    reference ``apex/transformer/amp/grad_scaler.py:21-126``), the
    optimizer step is predicated on it, and the scaler state updates
    device-side.  The step then takes/returns a scaler state:
    ``step(params, opt_state, scaler_state, tokens, targets) ->
    (params, opt_state, scaler_state, loss)``.

    ``step_guard``: an :class:`apex_tpu.resilience.StepGuard` — a
    :class:`~apex_tpu.resilience.step_guard.GuardState` rides the step
    right after the scaler state (or in its place without a scaler):
    non-finite steps are skipped device-side (the existing predicated
    update) AND counted, so the loop can enforce a consecutive-bad-step
    abort budget with ``guard.check`` at its own sync cadence.  Without
    a scaler the guard brings its own ``all_finite`` vote, agreed over
    the same model-parallel axes.

    ``chaos``: an armed :class:`apex_tpu.resilience.ChaosMonkey` whose
    planned NaN-grad steps are baked (as constants) into the compiled
    step — the loss is multiplied by the plan's 1.0/NaN scalar at the
    guard's step counter, poisoning every gradient of exactly the
    planned steps with zero per-step host work.  Requires
    ``step_guard`` (the counter lives in its state).

    The TPU shape of reference §3.2's iteration: value_and_grad inside
    ``shard_map`` (TP collectives via the mappings), gradient ``pmean``
    over ``dp`` (the DDP allreduce, ``apex/parallel/distributed.py:429``),
    then the fused optimizer update on local shards.
    Without a scaler, returns
    ``step(params, opt_state, tokens, targets) -> (params, opt_state, loss)``.
    """
    if spmd not in ("shard_map", "auto"):
        raise ValueError(f"spmd must be 'shard_map' or 'auto', got {spmd!r}")
    if spmd == "auto":
        for arg, name in ((cp_axis, "cp_axis"), (chaos, "chaos"),
                          (grad_sync_dtype, "grad_sync_dtype")):
            if arg is not None:
                raise NotImplementedError(
                    f"make_train_step(spmd='auto') does not take {name} "
                    "yet; use the shard_map path (the GSPMD step is the "
                    "parity-pinned core, features migrate per "
                    "docs/parallelism.md)")
        if overlap_grad_sync:
            raise NotImplementedError(
                "make_train_step(spmd='auto') does not take "
                "overlap_grad_sync: the GSPMD path has no explicit "
                "collectives to reorder (XLA already schedules its "
                "grad all-reduces against the backward); the knob "
                "belongs to the shard_map path")
        return _make_gspmd_train_step(
            config, optimizer, mesh, tp_axis, dp_axis, opt_state_spec,
            donate_state, clip_grad_norm, loss_scaler=loss_scaler,
            step_guard=step_guard, telemetry=telemetry)

    from jax.sharding import PartitionSpec as P

    # hierarchical data parallelism: dp_axis=(outer, inner) splits the
    # dp world over two mesh axes (slow cross-slice x fast intra-slice)
    # — the loss pmean runs over the pair, a ZeRO optimizer must carry
    # the same dp_axes (its two-hop reduce-scatter owns the sync), and
    # the replicated quantized knob routes through the two-hop pmean
    dp_hier = isinstance(dp_axis, (tuple, list))
    if dp_hier:
        dp_axis = tuple(dp_axis)
        if len(dp_axis) not in (2, 3):
            raise ValueError(
                f"a hierarchical dp_axis is the (outer, inner) pair — or "
                f"the (dcn, outer, inner) triple — of mesh axes ordered "
                f"slow to fast, got {dp_axis!r}")
        if config.moe:
            raise NotImplementedError(
                "MoE expert parallelism over a hierarchical dp split is "
                "not wired (EP rides a single dp axis)")

    ep_axis = dp_axis if config.moe else None  # EP rides DP
    if ep_axis is not None:
        ep = mesh.shape[ep_axis]
        if config.moe_num_experts % ep != 0:
            raise ValueError(
                f"moe_num_experts ({config.moe_num_experts}) must be divisible "
                f"by the '{ep_axis}' mesh axis size ({ep}): experts shard over "
                "dp (EP rides DP)"
            )
    specs = param_specs(config, ep_axis=ep_axis)

    qspec = None
    if grad_sync_dtype is not None:
        from apex_tpu.contrib.optimizers import _quantized_sync

        qspec = _quantized_sync.qspec_of(grad_sync_dtype)
        if qspec is None:
            raise ValueError(
                f"grad_sync_dtype={jnp.dtype(grad_sync_dtype).name!r}: the "
                "step builder's knob quantizes the replicated dp sync and "
                "accepts int8/float8_e4m3fn/float8_e5m2 only (wide sync "
                "dtypes belong to the ZeRO optimizer's own knob)")
        if hasattr(optimizer, "state_partition_spec"):
            raise ValueError(
                "a ZeRO optimizer owns the dp grad sync: pass "
                "grad_sync_dtype to its constructor (where it gains the "
                "error-feedback residual), not to make_train_step")
        if config.moe:
            raise NotImplementedError(
                "quantized dp sync + MoE is not wired: expert grads are "
                "dp-sharded sums, not pmean'd")
        if dp_axis is None:
            raise ValueError("grad_sync_dtype quantizes the dp sync; "
                             "this step has dp_axis=None")

    def pmean_grads(grads, ax, skip_experts):
        """pmean over a data axis.  Expert grads are dp-SHARDED, not
        replicated: the all_to_all transpose already delivered every
        rank's cotangents (a sum over dp), so the mean-loss gradient is
        that sum divided by dp — never pmean'd (which would mix grads of
        *different* experts)."""
        if qspec is not None and ax == dp_axis:
            from apex_tpu.contrib.optimizers import _quantized_sync

            if dp_hier:
                from apex_tpu.contrib.optimizers import _hierarchical_sync

                # multi-hop quantized all-reduce: scatter fast to slow,
                # mirrored gathers, every payload hop at the wire
                # dtype — each slower hop carries 1/prod(faster sizes)
                plan = _hierarchical_sync.hierarchical_plan(
                    dp_axis, {a: mesh.shape[a] for a in dp_axis},
                    grad_wire_dtype=grad_sync_dtype)
                return _hierarchical_sync.quantized_multi_hop_pmean(
                    grads, plan, qspec)
            # quantized all-reduce: reduce-scatter + all-gather, both
            # on the wire dtype (the same scale machinery as ZeRO's
            # compressed sync, minus the residual — no state channel)
            return _quantized_sync.quantized_pmean(
                grads, ax, qspec, world=mesh.shape[dp_axis])
        if not (skip_experts and config.moe):
            return jax.tree.map(lambda g: jax.lax.pmean(g, ax), grads)
        from apex_tpu.transformer.expert_parallel import EXPERT_PARAM_KEYS

        inv = 1.0 / jax.lax.axis_size(ax)
        moe = grads["layers"]["moe"]
        rest = {**grads, "layers": {k: v for k, v in grads["layers"].items() if k != "moe"}}
        rest = jax.tree.map(lambda g: jax.lax.pmean(g, ax), rest)
        moe = {
            k: (v * inv if k in EXPERT_PARAM_KEYS else jax.lax.pmean(v, ax))
            for k, v in moe.items()
        }
        rest["layers"]["moe"] = moe
        return rest

    # A ZeRO optimizer (state_partition_spec present) owns the dp grad
    # sync via its per-bucket reduce-scatter; grads then stay local
    # over dp and the collectives live inside the optimizer.
    zero_opt = hasattr(optimizer, "state_partition_spec")
    if zero_opt and config.moe:
        raise NotImplementedError(
            "ZeRO + MoE expert sharding both claim the dp axis; not wired"
        )
    _check_zero_axis(zero_opt, optimizer, dp_axis)

    if overlap_grad_sync:
        for bad, why in (
            (config.moe, "MoE (expert grads are dp-sharded sums, not "
             "bucketed pmean wires)"),
            (config.sequence_parallel, "sequence parallelism "
             "(sp_grad_sync is a whole-tree pass after the backward)"),
            (cp_axis is not None, "context parallelism (cp grads need "
             "a second pmean after the backward)"),
        ):
            if bad:
                raise NotImplementedError(
                    f"overlap_grad_sync is not wired for {why}")
        if dp_axis is None:
            raise ValueError("overlap_grad_sync overlaps the dp grad "
                             "sync; this step has dp_axis=None")
        if not zero_opt and qspec is None:
            raise ValueError(
                "overlap_grad_sync needs a per-bucket dp grad sync to "
                "overlap — a ZeRO optimizer (each bucket's "
                "reduce-scatter issues as its grads materialize) or "
                "grad_sync_dtype= (per-bucket quantized pmean); the "
                "plain replicated pmean is one whole-tree sweep with "
                "nothing to interleave")

    def sync_loss_and_grads(loss, grads):
        """cp behaves as a data axis for grads: each rank differentiated
        its local-chunk loss (ring-travelled k/v cotangents included),
        so pmean over cp (and dp) recovers the global-mean-loss grads.
        With ``overlap_grad_sync`` the dp sync already happened inside
        the backward (per bucket), so only the loss pmean remains."""
        if config.sequence_parallel:
            grads = sp_grad_sync(grads, tp_axis)
        for ax in (cp_axis, dp_axis):
            if ax is not None:
                loss = jax.lax.pmean(loss, ax)
                if ax == dp_axis and (zero_opt or overlap_grad_sync):
                    continue
                grads = pmean_grads(grads, ax, skip_experts=(ax == dp_axis))
        return loss, grads

    def overlap_value_and_grads(params, tokens, targets, post_loss,
                                residuals, scale):
        """The backward-overlapped twin of ``value_and_grad(loss_fn)``:
        the forward runs as the three ``_*_segment`` functions, each
        under its own ``jax.vjp``, and the backward is their cotangent
        chain — after each segment's backward, every bucket whose
        leaves all have cotangents is packed and its sync collective
        traced IMMEDIATELY, before the next (earlier) segment's
        backward.  Gradient readiness on the scan-stacked model has
        exactly three stages: final-LN leaves after the head backward,
        every ``layers.*`` leaf after the scan backward, and the (tied)
        embedding + positions after the embed backward.

        Returns ``(scaled_loss, grads, presynced)``: with a ZeRO
        optimizer ``grads`` is None and ``presynced`` the per-bucket
        ``(shards, residuals, wires)`` handoff its ``update*`` consumes
        in place of the grad tree; on the replicated quantized path
        ``grads`` is the dp-SYNCED (still loss-scaled) grad tree and
        ``presynced`` None.  Every per-bucket operation is the same
        function the unoverlapped build calls on the same values, so
        the arithmetic is bitwise identical — only collective placement
        in the trace moves."""
        from apex_tpu.optimizers import bucketing

        t = targets.transpose(1, 0)  # (S, B)

        def seg_embed(embed_w, pos_w):
            return _embed_segment(embed_w, pos_w, tokens, config, tp_axis,
                                  cp_axis)

        def seg_layers(layers_p, x):
            return _layers_segment(layers_p, x, config, tp_axis, cp_axis,
                                   ep_axis)

        def seg_head(ln_scale, ln_bias, embed_w, x):
            h = _head_segment(x, ln_scale, ln_bias, config, tp_axis)
            return jnp.mean(lm_head_loss(h, embed_w, t, config, tp_axis))

        unknown = sorted(set(params) - set(_OVERLAP_STAGES))
        if unknown:
            raise NotImplementedError(
                f"overlap_grad_sync does not know the gradient-readiness "
                f"stage of param group(s) {unknown}")

        x0, vjp_embed = jax.vjp(seg_embed, params["embed"],
                                params.get("pos_embed"))
        (x1, ys), vjp_layers = jax.vjp(seg_layers, params["layers"], x0)
        loss, vjp_head = jax.vjp(seg_head, params["final_ln_scale"],
                                 params["final_ln_bias"], params["embed"],
                                 x1)
        scaled_loss, vjp_post = jax.vjp(post_loss, loss)

        leaves, treedef = jax.tree.flatten(params)
        idx_tree = jax.tree.unflatten(treedef, list(range(len(leaves))))
        stages = [0] * len(leaves)
        for key, sub in idx_tree.items():
            for li in jax.tree.leaves(sub):
                stages[li] = _OVERLAP_STAGES[key]
        cot = [None] * len(leaves)

        def fill(key, val):
            for li, v in zip(jax.tree.leaves(idx_tree[key]),
                             jax.tree.leaves(val)):
                cot[li] = v

        if zero_opt:
            plan = optimizer._plan_of_local(params)
            by_stage = bucketing.buckets_by_stage(plan, stages, 3)
            n = len(plan.buckets)
            g_shards, res_new, wires = [None] * n, [None] * n, [None] * n

            def wire(stage):
                for bi in by_stage[stage]:
                    res = residuals[bi] if optimizer._quantized else None
                    g_shards[bi], res_new[bi], wires[bi] = \
                        optimizer.bucket_grad_wire(
                            plan.buckets[bi], cot, scale=scale,
                            residual=res)
        else:
            # replicated quantized pmean, one bucket at a time — the
            # grads stay SCALED on the wire exactly as on the
            # unoverlapped path (the downstream update tail unscales)
            from apex_tpu.contrib.optimizers import _quantized_sync

            if dp_hier:
                from apex_tpu.contrib.optimizers import _hierarchical_sync

                hplan = _hierarchical_sync.hierarchical_plan(
                    dp_axis, {a: mesh.shape[a] for a in dp_axis},
                    grad_wire_dtype=grad_sync_dtype)
                world = 1
                for s in hplan.traced_sizes():
                    world = world * s
            else:
                hplan, world = None, mesh.shape[dp_axis]
            plan = bucketing.plan_of(params, shard_pad=world)
            by_stage = bucketing.buckets_by_stage(plan, stages, 3)
            synced = [None] * len(plan.buckets)

            def wire(stage):
                for bi in by_stage[stage]:
                    h = bucketing.pack_bucket(plan.buckets[bi], cot,
                                              jnp.float32)
                    if hplan is not None:
                        synced[bi] = (_hierarchical_sync
                                      .quantized_multi_hop_pmean_bucket(
                                          h, hplan, qspec))
                    else:
                        synced[bi] = _quantized_sync.quantized_pmean_bucket(
                            h, dp_axis, qspec, world)

        (seed,) = vjp_post(jnp.ones_like(scaled_loss))
        d_ln_scale, d_ln_bias, d_embed_head, d_x1 = vjp_head(seed)
        fill("final_ln_scale", d_ln_scale)
        fill("final_ln_bias", d_ln_bias)
        wire(0)
        d_layers, d_x0 = vjp_layers((d_x1, jax.tree.map(jnp.zeros_like,
                                                        ys)))
        fill("layers", d_layers)
        wire(1)
        d_embed_lookup, d_pos = vjp_embed(d_x0)
        fill("embed", d_embed_head + d_embed_lookup)
        if "pos_embed" in params:
            fill("pos_embed", d_pos)
        wire(2)

        if zero_opt:
            return scaled_loss, None, (tuple(g_shards), tuple(res_new),
                                       tuple(wires))
        return scaled_loss, bucketing.unpack(plan, synced), None

    def value_and_grads(params, opt_state, tokens, targets, post_loss,
                        scale=None):
        """The one grads seam all four step variants share:
        ``(scaled_loss, grads, presynced)``.  Monolithic
        ``value_and_grad`` with ``presynced=None`` normally; the
        segmented overlapped backward when ``overlap_grad_sync``."""
        if not overlap_grad_sync:
            def loss_fn(p):
                return post_loss(gpt_loss(p, tokens, targets, config,
                                          tp_axis, cp_axis, ep_axis))

            scaled_loss, grads = jax.value_and_grad(loss_fn)(params)
            return scaled_loss, grads, None
        return overlap_value_and_grads(
            params, tokens, targets, post_loss,
            getattr(opt_state, "residual", ()), scale)

    if chaos is not None and step_guard is None:
        raise ValueError("chaos NaN injection needs step_guard (the "
                         "injection step counter lives in GuardState)")

    wedge_axis = ((dp_axis[0] if dp_hier else dp_axis)
                  if dp_axis is not None else tp_axis)

    def chaos_wedge(loss, guard_step):
        """Chaos "wedge one rank's collective site": on the planned
        (rank, step) an ``io_callback`` stalls exactly that rank right
        before the loss/grad sync, so its PEERS block device-side in
        the collective waiting for it — the truthful presentation of a
        wedged all-reduce, which only the host-side step watchdog
        (:class:`apex_tpu.resilience.StepWatchdog`) can notice.  The
        callback's token is folded into the loss to order it before
        the sync; off-plan (rank, step) pairs return immediately."""
        if chaos is None or not getattr(chaos, "wedges_collective", False):
            return loss
        from jax.experimental import io_callback

        def host(s, r):
            chaos.collective_wedge_callback(s, r)
            return np.float32(0.0)

        rank = jax.lax.axis_index(wedge_axis)
        tok = io_callback(host, jax.ShapeDtypeStruct((), jnp.float32),
                          guard_step, rank)
        return loss + tok

    # the clip's global norm must agree across ranks: sharded leaves'
    # Σx² psum over exactly their spec axes, replicated leaves don't
    clip_reduce = _clip_reduce_for(optimizer, clip_grad_norm, specs)

    # tp-sharded grad shards can overflow on one rank only; with
    # ZeRO (local dp grads) or MoE (dp-sharded expert grads) the dp
    # ranks can disagree too — every such axis must join the vote
    # (pmean'd axes already agree: a nan poisons every rank's copy)
    sync_axes = [tp_axis]
    if (zero_opt or config.moe) and dp_axis is not None:
        sync_axes.extend(dp_axis if dp_hier else (dp_axis,))

    def local_step(params, opt_state, tokens, targets):
        loss, grads, presynced = value_and_grads(
            params, opt_state, tokens, targets, lambda l: l)
        loss, grads = sync_loss_and_grads(loss, grads)
        kw = {} if presynced is None else {"presynced": presynced}
        if clip_grad_norm is not None:
            new_params, new_state = optimizer.update(
                grads, opt_state, params, clip_norm=clip_grad_norm,
                sumsq_reduce=clip_reduce, **kw)
        else:
            new_params, new_state = optimizer.update(grads, opt_state,
                                                     params, **kw)
        return new_params, new_state, loss

    def guarded_local_step(params, opt_state, guard_state, tokens, targets):
        fault = chaos.grad_fault(guard_state.step) if chaos is not None else None

        def post_loss(l):
            return l * fault if fault is not None else l

        loss, grads, presynced = value_and_grads(
            params, opt_state, tokens, targets, post_loss)
        loss = chaos_wedge(loss, guard_state.step)
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_guard = _apply_guarded_update(
            grads, optimizer, opt_state, params, sync_axes,
            step_guard, guard_state, clip_grad_norm=clip_grad_norm,
            clip_sumsq=clip_reduce, presynced=presynced,
        )
        return new_params, new_state, new_guard, loss

    def scaled_local_step(params, opt_state, scaler_state, tokens, targets):
        def post_loss(l):
            return loss_scaler.scale(scaler_state, l)

        scaled_loss, grads, presynced = value_and_grads(
            params, opt_state, tokens, targets, post_loss,
            scale=scaler_state.loss_scale)
        loss = scaled_loss / scaler_state.loss_scale
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_scaler_state = _apply_scaled_update(
            loss_scaler, scaler_state, grads, optimizer, opt_state, params,
            sync_axes, clip_grad_norm=clip_grad_norm,
            clip_sumsq=clip_reduce, presynced=presynced,
        )
        return new_params, new_state, new_scaler_state, loss

    def guarded_scaled_local_step(params, opt_state, scaler_state,
                                  guard_state, tokens, targets):
        fault = chaos.grad_fault(guard_state.step) if chaos is not None else None

        def post_loss(l):
            if fault is not None:
                l = l * fault
            return loss_scaler.scale(scaler_state, l)

        scaled_loss, grads, presynced = value_and_grads(
            params, opt_state, tokens, targets, post_loss,
            scale=scaler_state.loss_scale)
        loss = scaled_loss / scaler_state.loss_scale
        loss = chaos_wedge(loss, guard_state.step)
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_scaler_state, new_guard = \
            _apply_scaled_update(
                loss_scaler, scaler_state, grads, optimizer, opt_state,
                params, sync_axes,
                step_guard=step_guard, guard_state=guard_state,
                clip_grad_norm=clip_grad_norm, clip_sumsq=clip_reduce,
                presynced=presynced,
            )
        return new_params, new_state, new_scaler_state, new_guard, loss

    # optimizer state mirrors param sharding for m/v/master; scalars replicated
    def state_spec_of(params_spec):
        from apex_tpu.optimizers.fused_adam import AdamState

        return AdamState(step=P(), exp_avg=params_spec, exp_avg_sq=params_spec, master=None)

    if opt_state_spec is not None:
        sspec = opt_state_spec
    elif zero_opt:
        sspec = optimizer.state_partition_spec()
    else:
        sspec = state_spec_of(specs)
    data_spec = P(dp_axis, cp_axis)  # batch over dp, sequence over cp

    donate = (0, 1) if donate_state else ()
    fn, in_specs, out_specs, stats_argnum = _step_variant(
        loss_scaler, step_guard,
        {(True, True): guarded_scaled_local_step,
         (True, False): scaled_local_step,
         (False, True): guarded_local_step,
         (False, False): local_step},
        specs, sspec, data_spec, telemetry=telemetry)
    if stats_argnum is not None:
        # the StepStats window is always rebound (fetch swaps in fresh
        # zeros), so its tiny buffers always donate
        donate = (*donate, stats_argnum)
    sharded = jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate)


def params_to_vpp_layout(params, pp: int, vpp: int):
    """Permute layer-stacked params from execution order to the
    stage-major layout the interleaved schedule shards.

    Execution order is virtual-stage-major: global block ``j = v·pp + s``
    (reference fwd_bwd_pipelining_with_interleaving.py:27 assigns stage s
    chunks s, s+pp, ...).  Sharding ``P("pp")`` slices axis 0 into
    contiguous per-stage blocks, so stage s's slice must hold its vpp
    chunks back to back: ``out[(s·vpp + v)·lpc + i] = in[(v·pp + s)·lpc + i]``.
    Train in this layout (element-wise optimizers are layout-blind);
    invert with :func:`params_from_vpp_layout` for canonical checkpoints.
    """
    def perm(a):
        L = a.shape[0]
        lpc = L // (pp * vpp)
        return (
            a.reshape(vpp, pp, lpc, *a.shape[1:])
            .transpose(1, 0, *range(2, a.ndim + 2))
            .reshape(a.shape)
        )

    out = dict(params)
    out["layers"] = jax.tree.map(perm, params["layers"])
    return out


def params_from_vpp_layout(params, pp: int, vpp: int):
    """Inverse of :func:`params_to_vpp_layout`."""
    def unperm(a):
        L = a.shape[0]
        lpc = L // (pp * vpp)
        return (
            a.reshape(pp, vpp, lpc, *a.shape[1:])
            .transpose(1, 0, *range(2, a.ndim + 2))
            .reshape(a.shape)
        )

    out = dict(params)
    out["layers"] = jax.tree.map(unperm, params["layers"])
    return out


def make_pp_train_step(
    config: GPTConfig,
    optimizer,
    mesh,
    num_microbatches: int,
    tp_axis: str = "tp",
    pp_axis: str = "pp",
    dp_axis: Optional[str] = "dp",
    virtual_pipeline_size: int = 1,
    opt_state_spec=None,
    cp_axis: Optional[str] = None,
    loss_scaler=None,
    donate_state: bool = False,
    step_guard=None,
    chaos=None,
    clip_grad_norm=None,
    telemetry=None,
):
    """3D-parallel (tp × pp × dp) train step via the pipeline schedule.

    ``telemetry``: same contract as :func:`make_train_step` — a
    :class:`~apex_tpu.observability.StepStats` window rides after the
    scaler/guard states, accumulated device-side, always donated,
    never a participant in the update.

    ``clip_grad_norm``: global-l2 grad clip folded into the engine
    optimizer's fused grad pass (see :func:`make_train_step`).

    ``opt_state_spec`` overrides the optimizer-state PartitionSpec tree
    (default: FusedAdam state shape; ZeRO optimizers supply their own).

    ``loss_scaler``: fp16 dynamic loss scaling through the pipeline
    (see :func:`make_train_step`): the schedule's backward seed is the
    SCALED loss, found_inf is pmax-agreed over tp AND pp (every stage
    must skip together — the reference's model-parallel GradScaler,
    ``apex/transformer/amp/grad_scaler.py:21-126``), and the step
    signature grows a scaler state:
    ``step(params, opt_state, scaler_state, tokens, targets)``.

    ``cp_axis``: context parallelism inside every stage — the sequence
    shards over the axis and each layer's attention is ring attention
    (4D tp × pp × dp × cp).  All stages run the ring's ppermutes in
    lockstep per tick, so the collectives stay consistent.

    Layer-stacked params shard over ``pp`` on their leading axis and over
    ``tp`` on their weight axes (the layout of reference §3.4: each
    pipeline stage owns L/pp layers, each TP rank a weight shard).  The
    batch splits into ``num_microbatches`` microbatches driven through
    the 1F1B schedule, or the interleaved schedule when
    ``virtual_pipeline_size > 1`` — in that case ``params["layers"]``
    (and the matching optimizer state) must be in the stage-major vpp
    layout from :func:`params_to_vpp_layout`.

    ``step_guard``/``chaos``: same contract as :func:`make_train_step`
    — a guard state rides after the scaler state (or in its place),
    the skip vote is pmax-agreed over tp AND pp (every stage skips
    together), and chaos NaN injection scales the schedule's backward
    seed so the poisoned step is skipped pipeline-wide.
    Returns ``step(params, opt_state, tokens, targets) -> (params,
    opt_state, loss)`` (jitted).
    """
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
    )

    if isinstance(dp_axis, (tuple, list)):
        raise NotImplementedError(
            "hierarchical dp (dp_axis=(outer, inner)) is wired into "
            "make_train_step only; the pipeline step's dp sync is flat")

    # MoE composes: experts shard over dp (EP rides DP) inside each
    # pipeline stage; every (dp, pp, tp) rank executes the tick program
    # in lockstep, so the per-layer all_to_all stays collective-safe.
    ep_axis = dp_axis if config.moe else None
    if config.moe and dp_axis is None:
        raise ValueError("MoE in the pipeline step needs a dp axis (EP rides DP)")
    if cp_axis is not None and config.sequence_parallel:
        raise ValueError("sequence_parallel (tp) and context parallelism both "
                         "shard the sequence; enable one")
    H = config.hidden_size
    tp = mesh.shape[tp_axis]
    n_local_heads = config.num_attention_heads // tp
    sp = config.sequence_parallel
    vpp = virtual_pipeline_size
    if vpp > 1:
        if config.num_layers % (mesh.shape[pp_axis] * vpp) != 0:
            raise ValueError(
                f"num_layers ({config.num_layers}) must divide into "
                f"pp ({mesh.shape[pp_axis]}) x vpp ({vpp}) chunks"
            )
        if num_microbatches % mesh.shape[pp_axis] != 0:
            # the interleaved slot decode pads M up to a multiple of pp and
            # masks the padding — every padding slot still costs a full
            # tick, so reject rather than silently burn pipeline throughput
            # (the reference's interleaved schedule has the same constraint)
            raise ValueError(
                f"num_microbatches ({num_microbatches}) must be a multiple of "
                f"pp ({mesh.shape[pp_axis]}) when virtual_pipeline_size > 1"
            )

    base = param_specs(config, ep_axis=ep_axis)

    def pp_spec(spec):
        # prepend pp sharding on the stacked-layer axis
        return P(pp_axis, *spec[1:])

    specs = dict(base)
    specs["layers"] = jax.tree.map(
        pp_spec, base["layers"], is_leaf=lambda s: isinstance(s, P)
    )
    # stage-stacked leaves are pp-sharded (their spec leads with pp), so
    # the clip's global norm psums their Σx² over pp (+tp for sharded
    # weights); replicated embeds/norms stay local — the reduce reads
    # all of that off the specs
    clip_reduce = _clip_reduce_for(optimizer, clip_grad_norm, specs)

    def pre_fn(shared, mb):
        tokens = mb["tokens"]
        B, S = tokens.shape
        emb = vocab_parallel_embedding(tokens, shared["embed"], axis_name=tp_axis)
        x = _add_pos_embed(emb.transpose(1, 0, 2), shared.get("pos_embed"),
                           config, cp_axis)
        x = x.astype(config.compute_dtype)
        if sp:
            from apex_tpu.transformer.tensor_parallel.mappings import (
                scatter_to_sequence_parallel_region,
            )

            x = scatter_to_sequence_parallel_region(x, tp_axis)
        return x

    def stage_fn(stage_params, x):
        layer = partial(_layer, config=config, axis_name=tp_axis,
                        n_local_heads=n_local_heads, ep_axis=ep_axis,
                        cp_axis=cp_axis)
        if config.checkpoint_layers:
            layer = remat_layer(layer, config.remat_policy)
        out, aux = jax.lax.scan(lambda c, lp: layer(c, lp), x, stage_params)
        if config.moe:
            # pre-weight the load-balancing aux; the schedule adds it to
            # the loss per (stage, microbatch) unit and seeds its vjp
            return out, config.moe_aux_coef * jnp.sum(aux)
        return out

    def post_fn(shared, x, mb):
        if sp:
            from apex_tpu.transformer.tensor_parallel.mappings import (
                gather_from_sequence_parallel_region,
            )

            x = gather_from_sequence_parallel_region(x, tp_axis, False)
        x = fused_layer_norm_affine(
            x, shared["final_ln_scale"], shared["final_ln_bias"], (H,), config.layernorm_eps
        )
        from apex_tpu.transformer.tensor_parallel.mappings import (
            copy_to_tensor_model_parallel_region,
        )

        x = copy_to_tensor_model_parallel_region(x, tp_axis)
        t = mb["targets"].transpose(1, 0)
        return jnp.mean(lm_head_loss(x, shared["embed"], t, config, tp_axis))

    def run_schedule(params, tokens, targets, stage_fn_, post_fn_):
        shared = {k: v for k, v in params.items() if k != "layers"}
        stages = params["layers"]
        B = tokens.shape[0]
        mb = {
            "tokens": tokens.reshape(num_microbatches, B // num_microbatches, -1),
            "targets": targets.reshape(num_microbatches, B // num_microbatches, -1),
        }
        if vpp > 1:
            loss, (g_shared, g_stage) = forward_backward_pipelining_with_interleaving(
                pre_fn, stage_fn_, post_fn_, shared, stages, mb,
                virtual_pipeline_model_parallel_size=vpp, axis_name=pp_axis,
                stage_has_aux=config.moe,
            )
        else:
            loss, (g_shared, g_stage) = forward_backward_pipelining_without_interleaving(
                pre_fn, stage_fn_, post_fn_, shared, stages, mb, axis_name=pp_axis,
                stage_has_aux=config.moe,
            )
        return loss, {**g_shared, "layers": g_stage}

    def sync_loss_and_grads(loss, grads):
        if sp:
            grads = sp_grad_sync(grads, tp_axis)
        if cp_axis is not None:
            # each cp rank's loss/grads cover its local sequence chunk
            loss = jax.lax.pmean(loss, cp_axis)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, cp_axis), grads)
        if dp_axis is not None:
            loss = jax.lax.pmean(loss, dp_axis)
            if not zero_opt:
                if config.moe:
                    # expert grads are dp-SHARDED (the all_to_all already
                    # delivered the dp-summed cotangents): divide, never
                    # pmean (which would mix different experts' grads)
                    from apex_tpu.transformer.expert_parallel import EXPERT_PARAM_KEYS

                    inv = 1.0 / jax.lax.axis_size(dp_axis)
                    moe_g = {
                        k: (v * inv if k in EXPERT_PARAM_KEYS
                            else jax.lax.pmean(v, dp_axis))
                        for k, v in grads["layers"]["moe"].items()
                    }
                    rest = {**grads, "layers": {k: v for k, v in grads["layers"].items() if k != "moe"}}
                    grads = jax.tree.map(lambda g: jax.lax.pmean(g, dp_axis), rest)
                    grads["layers"]["moe"] = moe_g
                else:
                    grads = jax.tree.map(lambda g: jax.lax.pmean(g, dp_axis), grads)
        # ZeRO: grads stay LOCAL — the optimizer's per-bucket
        # psum_scatter over dp IS the gradient sync (one reduce-scatter
        # per dtype bucket in grad_sync_dtype, fused with the update)
        return loss, grads

    if chaos is not None and step_guard is None:
        raise ValueError("chaos NaN injection needs step_guard (the "
                         "injection step counter lives in GuardState)")

    def _scaled_fns(factor):
        """(stage_fn, post_fn) with every backward seed scaled by
        ``factor`` — the loss-scale multiply, the chaos fault, or both
        folded into one scalar (the schedule seeds backward from
        post_fn's output, so scaling HERE scales every cotangent in the
        pipeline; the MoE aux loss enters inside the schedule and must
        ride the same scaled backward)."""
        def post_scaled(shared, x, mb_):
            return post_fn(shared, x, mb_) * factor

        if config.moe:
            def stage_scaled(stage_params, x):
                out, aux = stage_fn(stage_params, x)
                return out, aux * factor
        else:
            stage_scaled = stage_fn
        return stage_scaled, post_scaled

    def local_step(params, opt_state, tokens, targets):
        loss, grads = run_schedule(params, tokens, targets, stage_fn, post_fn)
        loss, grads = sync_loss_and_grads(loss, grads)
        if clip_grad_norm is not None:
            new_params, new_state = optimizer.update(
                grads, opt_state, params, clip_norm=clip_grad_norm,
                sumsq_reduce=clip_reduce)
        else:
            new_params, new_state = optimizer.update(grads, opt_state, params)
        return new_params, new_state, loss

    def guarded_local_step(params, opt_state, guard_state, tokens, targets):
        fault = chaos.grad_fault(guard_state.step) if chaos is not None else None
        if fault is not None:
            stage, post = _scaled_fns(fault)
        else:
            stage, post = stage_fn, post_fn
        loss, grads = run_schedule(params, tokens, targets, stage, post)
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_guard = _apply_guarded_update(
            grads, optimizer, opt_state, params, guard_sync_axes,
            step_guard, guard_state, clip_grad_norm=clip_grad_norm,
            clip_sumsq=clip_reduce,
        )
        return new_params, new_state, new_guard, loss

    def scaled_local_step(params, opt_state, scaler_state, tokens, targets):
        scale = scaler_state.loss_scale
        stage_scaled, post_scaled = _scaled_fns(scale)
        scaled_loss, grads = run_schedule(
            params, tokens, targets, stage_scaled, post_scaled
        )
        loss = scaled_loss / scale
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_scaler_state = _apply_scaled_update(
            loss_scaler, scaler_state, grads, optimizer, opt_state, params,
            guard_sync_axes, clip_grad_norm=clip_grad_norm,
            clip_sumsq=clip_reduce,
        )
        return new_params, new_state, new_scaler_state, loss

    def guarded_scaled_local_step(params, opt_state, scaler_state,
                                  guard_state, tokens, targets):
        scale = scaler_state.loss_scale
        fault = chaos.grad_fault(guard_state.step) if chaos is not None else None
        factor = scale * fault if fault is not None else scale
        stage_scaled, post_scaled = _scaled_fns(factor)
        scaled_loss, grads = run_schedule(
            params, tokens, targets, stage_scaled, post_scaled
        )
        loss = scaled_loss / scale
        loss, grads = sync_loss_and_grads(loss, grads)
        new_params, new_state, new_scaler_state, new_guard = \
            _apply_scaled_update(
                loss_scaler, scaler_state, grads, optimizer, opt_state,
                params, guard_sync_axes,
                step_guard=step_guard, guard_state=guard_state,
                clip_grad_norm=clip_grad_norm, clip_sumsq=clip_reduce,
            )
        return new_params, new_state, new_scaler_state, new_guard, loss

    from apex_tpu.optimizers.fused_adam import AdamState

    # A ZeRO optimizer (DistributedFusedAdam/LAMB) brings its own flat
    # state sharding; call its init with param_specs=specs and
    # axis_sizes={tp:..., pp:...} so the state is sized for the local
    # (pp, tp) param shard and sharded over (model axes, dp).
    zero_opt = hasattr(optimizer, "state_partition_spec")
    if zero_opt and config.moe:
        raise NotImplementedError(
            "ZeRO + MoE expert sharding both claim the dp axis; not wired"
        )
    _check_zero_axis(zero_opt, optimizer, dp_axis)
    # stage-sharded (pp) and tp-sharded grads can overflow on one rank
    # only — every such axis must agree on the skip decision; ZeRO
    # (local dp grads) and MoE (dp-sharded expert grads) add dp
    guard_sync_axes = [tp_axis, pp_axis]
    if (zero_opt or config.moe) and dp_axis is not None:
        guard_sync_axes.append(dp_axis)
    if opt_state_spec is not None:
        sspec = opt_state_spec
    elif zero_opt:
        sspec = optimizer.state_partition_spec()
    else:
        sspec = AdamState(step=P(), exp_avg=specs, exp_avg_sq=specs, master=None)
    data_spec = P(dp_axis, cp_axis) if dp_axis is not None else P(None, cp_axis)

    donate = (0, 1) if donate_state else ()
    fn, in_specs, out_specs, stats_argnum = _step_variant(
        loss_scaler, step_guard,
        {(True, True): guarded_scaled_local_step,
         (True, False): scaled_local_step,
         (False, True): guarded_local_step,
         (False, False): local_step},
        specs, sspec, data_spec, telemetry=telemetry)
    if stats_argnum is not None:
        donate = (*donate, stats_argnum)
    sharded = jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donate)


def gpt_loss(
    params, tokens, targets, config: GPTConfig, axis_name: Optional[str] = None,
    cp_axis: Optional[str] = None, ep_axis: Optional[str] = None,
):
    """Mean causal-LM cross entropy (+ MoE aux loss when enabled).
    Uses vocab-parallel CE on a mesh.  With ``cp_axis`` the mean is over
    the LOCAL sequence chunk — combine across chunks with a pmean (the
    data-axis gradient calculus)."""
    t = targets.transpose(1, 0)  # (S, B)
    out = gpt_forward(params, tokens, config, axis_name, cp_axis, ep_axis,
                      return_aux=config.moe, return_hidden=True)
    hidden, aux = out if config.moe else (out, None)
    loss = lm_head_loss(hidden, params["embed"], t, config, axis_name)
    loss = jnp.mean(loss)
    if aux is not None:
        loss = loss + config.moe_aux_coef * aux
    return loss
