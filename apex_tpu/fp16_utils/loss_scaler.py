"""Standalone loss scalers with the fp16_utils API names.

Reference: ``apex/fp16_utils/loss_scaler.py`` — ``LossScaler`` (:10,
static) and ``DynamicLossScaler`` (:49, 2x down on overflow, 2x up per
1000 clean iterations).  Functional re-exports of the amp scalers with
the reference's historical defaults.
"""

from apex_tpu.amp.scaler import DynamicLossScaler as _Dynamic
from apex_tpu.amp.scaler import StaticLossScaler as _Static


class LossScaler(_Static):
    def __init__(self, scale=1.0):
        super().__init__(scale)


class DynamicLossScaler(_Dynamic):
    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0, scale_window=1000):
        super().__init__(
            init_scale=init_scale,
            growth_factor=scale_factor,
            backoff_factor=1.0 / scale_factor,
            growth_interval=scale_window,
        )
