"""Manual mixed-precision utilities (reference: ``apex/fp16_utils``).

The pre-amp API: explicit half conversion, master-weight bookkeeping,
and a wrapping ``FP16_Optimizer``.  On TPU these are thin functional
forms over the same machinery :mod:`apex_tpu.amp` and the fused
optimizers already use.
"""

from apex_tpu.fp16_utils.fp16util import (
    BN_convert_float,
    FP16Model,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    tofp16,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler

__all__ = [
    "BN_convert_float",
    "FP16Model",
    "network_to_half",
    "convert_network",
    "tofp16",
    "prep_param_lists",
    "model_grads_to_master_grads",
    "master_params_to_model_params",
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
]
