"""Conversion + master-param helpers (reference: ``apex/fp16_utils/fp16util.py``)."""

from typing import Any, Tuple

import jax
import jax.numpy as jnp


def _is_norm(path: str) -> bool:
    p = path.lower()
    return any(k in p for k in ("batchnorm", "bn", "layernorm", "layer_norm", "norm"))


def tofp16(tree, half_dtype=jnp.bfloat16):
    """Cast all float leaves to half (reference fp16util.py:25 tofp16)."""
    return jax.tree.map(
        lambda x: x.astype(half_dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree
    )


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Half-cast params, keeping norm layers fp32 (fp16util.py:35 — BN
    exemption via convert_network)."""
    return convert_network(params, half_dtype)


def _map_norm_leaves(params, norm_fn, other_fn):
    """Apply ``norm_fn`` to float leaves on norm-layer paths and
    ``other_fn`` to the remaining float leaves; non-floats pass through."""
    flat = jax.tree_util.tree_flatten_with_path(params)

    def one(kp, x):
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        return norm_fn(x) if _is_norm(jax.tree_util.keystr(kp)) else other_fn(x)

    return jax.tree_util.tree_unflatten(flat[1], [one(kp, x) for kp, x in flat[0]])


def convert_network(params, dtype=jnp.bfloat16):
    """Reference fp16util.py:60: cast all but _BatchNorm-style params."""
    return _map_norm_leaves(
        params, lambda x: x.astype(jnp.float32), lambda x: x.astype(dtype)
    )


def prep_param_lists(params, flat_master: bool = False) -> Tuple[Any, Any]:
    """Half model params + fp32 master copy (fp16util.py:92).

    ``flat_master=True`` concatenates the master into one flat vector
    (the reference's single-tensor option).
    """
    master = jax.tree.map(lambda p: p.astype(jnp.float32), params)
    if flat_master:
        leaves = jax.tree.leaves(master)
        flat = jnp.concatenate([l.reshape(-1) for l in leaves])
        return params, flat
    return params, master


def model_grads_to_master_grads(model_grads, master_grads=None):
    """fp16 grads → fp32 grads (fp16util.py:138)."""
    return jax.tree.map(lambda g: g.astype(jnp.float32), model_grads)


def master_params_to_model_params(model_params, master_params):
    """Copy master values into model dtype (fp16util.py:160) — the
    post-step sync of the O2 flow."""
    return jax.tree.map(lambda p, m: m.astype(p.dtype), model_params, master_params)


def BN_convert_float(params):
    """Re-promote norm-layer params to fp32 in an already-half tree
    (reference fp16util.py:22 — legacy helper behind network_to_half)."""
    return _map_norm_leaves(params, lambda x: x.astype(jnp.float32), lambda x: x)


class FP16Model:
    """Reference fp16util.py:73 — wrap an apply fn + params so inputs
    and params run in half (norm params fp32) while outputs keep the fn's
    dtype.  Functional form: ``FP16Model(apply_fn, params)(x)``."""

    def __init__(self, apply_fn, params, half_dtype=jnp.bfloat16):
        from apex_tpu import deprecated_warning

        deprecated_warning(
            "fp16_utils is a legacy API (deprecated in the reference); "
            "prefer apex_tpu.amp policies."
        )
        self.apply_fn = apply_fn
        self.half_dtype = half_dtype
        self.params = convert_network(params, half_dtype)

    def __call__(self, *inputs):
        cast = tuple(
            x.astype(self.half_dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x
            for x in inputs
        )
        return self.apply_fn(self.params, *cast)
