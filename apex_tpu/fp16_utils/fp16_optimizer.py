"""FP16_Optimizer — master-weight wrapper around any optimizer.

Reference: ``apex/fp16_utils/fp16_optimizer.py:13`` — holds fp32 master
params, scales the loss, unscales grads into the master, optionally
clips, steps the wrapped optimizer on the master, and copies back to the
fp16 model params, skipping on overflow.

Functional form: state = (inner_state, scaler_state); ``step`` does the
whole reference sequence in one jittable call.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import DynamicLossScaler, StaticLossScaler
from apex_tpu.contrib.clip_grad import clip_grad_norm_


class FP16OptimizerState(NamedTuple):
    inner: Any
    scaler: Any


class FP16_Optimizer:
    def __init__(
        self,
        init_optimizer,
        static_loss_scale: float = 1.0,
        dynamic_loss_scale: bool = False,
        dynamic_loss_args: Optional[dict] = None,
        verbose: bool = False,
    ):
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = StaticLossScaler(static_loss_scale)

    def init(self, params) -> FP16OptimizerState:
        # force master weights in the inner optimizer
        self.optimizer.master_weights = True
        inner = self.optimizer.init(params)
        if inner.master is None:
            # copy=True: astype aliases already-fp32 leaves, and a
            # master aliasing its param double-donates (base.make_master)
            inner = inner._replace(
                master=jax.tree.map(
                    lambda p: jnp.array(p, jnp.float32, copy=True), params)
            )
        return FP16OptimizerState(inner=inner, scaler=self.loss_scaler.init())

    def scale_loss(self, state: FP16OptimizerState, loss):
        """Use instead of the reference's ``optimizer.backward(loss)``."""
        return self.loss_scaler.scale(state.scaler, loss)

    def step(self, grads, state: FP16OptimizerState, params, max_grad_norm: Optional[float] = None):
        """unscale → (clip) → inner step on master → copy to model dtype,
        with the whole commit predicated on grad finiteness."""
        g32, finite = self.loss_scaler.unscale(state.scaler, grads)
        if max_grad_norm is not None:
            g32, _ = clip_grad_norm_(g32, max_grad_norm)
        new_params, new_inner = self.optimizer.update(
            g32, state.inner, params, grads_finite=finite
        )
        new_scaler = self.loss_scaler.update(state.scaler, finite)
        return new_params, FP16OptimizerState(inner=new_inner, scaler=new_scaler), finite

    # ----- state dict parity (fp16_optimizer.py state_dict/load_state_dict)
    def state_dict(self, state: FP16OptimizerState):
        import numpy as np

        return {
            "loss_scaler": self.loss_scaler.state_dict(state.scaler),
            "inner": jax.tree.map(
                lambda x: np.asarray(x) if x is not None else None, state.inner
            ),
        }

    def load_state_dict(self, d) -> FP16OptimizerState:
        inner = jax.tree.map(
            lambda x: jnp.asarray(x) if x is not None else None, d["inner"]
        )
        return FP16OptimizerState(
            inner=inner, scaler=self.loss_scaler.load_state_dict(d["loss_scaler"])
        )
