"""Pallas TPU kernels for fused LayerNorm/RMSNorm.

Reference: ``csrc/layer_norm_cuda_kernel.cu`` (1,286 LoC of Welford
row-stat kernels).  TPU version: the row dimension is blocked over the
grid; each program loads a ``(BLOCK_R, H)`` tile into VMEM, computes
row statistics on the VPU in fp32, and writes the normalized tile — one
HBM round trip for the whole op (the fusion the CUDA kernel exists for).

The backward kernel computes ``dx`` per tile plus *per-block partial*
``dw``/``db`` (grid-indexed rows of a partials buffer) that are summed
by XLA afterwards — the Pallas analog of the CUDA kernel's two-stage
part-reduction (``layer_norm_cuda_kernel.cu`` cuComputePartGradGammaBeta).

Used by :mod:`apex_tpu.normalization` when running on TPU with
lane-aligned hidden sizes; the jnp path remains the universal fallback
and the numerics specification.
"""


import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_R = 256


def _pick_block_r(R, H, block_r):
    """Block rows sized to the ~16MB VMEM budget: the bwd kernel holds
    roughly 6-8 fp32 (br, H) live tiles, so keep br*H*32B ≤ 4MB."""
    budget = max(8, (4 * 1024 * 1024) // (32 * H) * 8 // 8)
    br = min(block_r, budget, R)
    br = max(8, (br // 8) * 8) if R % 8 == 0 else br
    while R % br:
        br -= 1
    return br


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, affine, rms):
    x = x_ref[:].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(x * x, axis=1, keepdims=True)
    else:
        mean = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    if affine:
        y = y * w_ref[:].astype(jnp.float32)
        if b_ref is not None:
            y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def layer_norm_fwd_pallas(x2, weight, bias, eps, rms=False, block_r=DEFAULT_BLOCK_R, interpret=False):
    """x2: (R, H) pre-flattened.  Returns (y, mean (R,1), rstd (R,1))."""
    R, H = x2.shape
    br = _pick_block_r(R, H, block_r)
    grid = (R // br,)
    affine = weight is not None

    w2 = weight.reshape(1, H) if affine else None
    b2 = bias.reshape(1, H) if bias is not None else None

    in_specs = [pl.BlockSpec((br, H), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    args = [x2]
    if affine:
        in_specs.append(pl.BlockSpec((1, H), lambda i: (0, 0), memory_space=pltpu.VMEM))
        args.append(w2)
    if b2 is not None:
        in_specs.append(pl.BlockSpec((1, H), lambda i: (0, 0), memory_space=pltpu.VMEM))
        args.append(b2)

    def kernel(*refs):
        if affine and b2 is not None:
            x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref = refs
        elif affine:
            x_ref, w_ref, y_ref, mean_ref, rstd_ref = refs
            b_ref = None
        else:
            x_ref, y_ref, mean_ref, rstd_ref = refs
            w_ref = b_ref = None
        _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, eps=eps, affine=affine, rms=rms)

    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((br, H), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((R, H), x2.dtype),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
            jax.ShapeDtypeStruct((R, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return y, mean, rstd


def _ln_bwd_kernel(x_ref, w_ref, dy_ref, mean_ref, rstd_ref, dx_ref, dw_ref, db_ref, *, affine, rms):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    xhat = (x - mean) * rstd
    gw = dy * w_ref[:].astype(jnp.float32) if affine else dy

    if rms:
        m2 = jnp.mean(gw * xhat, axis=1, keepdims=True)
        dx = (gw - xhat * m2) * rstd
    else:
        m1 = jnp.mean(gw, axis=1, keepdims=True)
        m2 = jnp.mean(gw * xhat, axis=1, keepdims=True)
        dx = (gw - m1 - xhat * m2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)
    if affine:
        # TPU grid steps run sequentially on a core, so accumulating into
        # one (8, H) buffer is race-free (8 rows for sublane alignment;
        # row 0 carries the value).
        @pl.when(pl.program_id(0) == 0)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            if db_ref is not None:
                db_ref[:] = jnp.zeros_like(db_ref)

        dw_ref[0:1, :] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        if db_ref is not None:
            db_ref[0:1, :] += jnp.sum(dy, axis=0, keepdims=True)


def layer_norm_bwd_pallas(
    x2, weight, dy2, mean, rstd, rms=False, with_bias=True, block_r=DEFAULT_BLOCK_R, interpret=False
):
    """Returns (dx (R,H), dw_acc, db_acc) — accumulators shaped (8, H)
    with the value in row 0 (rows 1-7 zero); callers ``sum(0)``."""
    R, H = x2.shape
    br = _pick_block_r(R, H, block_r)
    grid = (R // br,)
    affine = weight is not None

    in_specs = [pl.BlockSpec((br, H), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    args = [x2]
    if affine:
        in_specs.append(pl.BlockSpec((1, H), lambda i: (0, 0), memory_space=pltpu.VMEM))
        args.append(weight.reshape(1, H))
    in_specs += [
        pl.BlockSpec((br, H), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((br, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    ]
    args += [dy2, mean, rstd]

    out_specs = [pl.BlockSpec((br, H), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((R, H), x2.dtype)]
    if affine:
        out_specs.append(pl.BlockSpec((8, H), lambda i: (0, 0), memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((8, H), jnp.float32))
        if with_bias:
            out_specs.append(pl.BlockSpec((8, H), lambda i: (0, 0), memory_space=pltpu.VMEM))
            out_shape.append(jax.ShapeDtypeStruct((8, H), jnp.float32))

    def kernel(*refs):
        if affine and with_bias:
            x_ref, w_ref, dy_ref, mean_ref, rstd_ref, dx_ref, dw_ref, db_ref = refs
        elif affine:
            x_ref, w_ref, dy_ref, mean_ref, rstd_ref, dx_ref, dw_ref = refs
            db_ref = None
        else:
            x_ref, dy_ref, mean_ref, rstd_ref, dx_ref = refs
            w_ref = dw_ref = db_ref = None
        _ln_bwd_kernel(x_ref, w_ref, dy_ref, mean_ref, rstd_ref, dx_ref, dw_ref, db_ref, affine=affine, rms=rms)

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    if not affine:
        return outs[0], None, None
    if with_bias:
        return outs[0], outs[1], outs[2]
    return outs[0], outs[1], None


def pallas_available(x2, normalized_size: int) -> bool:
    """Use the kernels on real TPU with lane-aligned hidden sizes.
    Disable with APEX_TPU_PALLAS_NORM=0 (XLA's fusion of the jnp path is
    the fallback and is equally memory-bound)."""
    import os

    if os.environ.get("APEX_TPU_PALLAS_NORM", "1") == "0":
        return False
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        return False
    return on_tpu and normalized_size % 128 == 0 and x2.dtype in (jnp.float32, jnp.bfloat16)
