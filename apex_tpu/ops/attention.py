"""Flash (blockwise, online-softmax) attention.

Reference: ``apex/contrib/fmha`` (flash-style fused MHA for BERT,
seqlen ≤ 512, ``fmhalib``) and ``apex/contrib/multihead_attn`` (fused
self/enc-dec attention kernels).  The reference kernels exist to avoid
materializing the (sq, sk) score matrix in HBM; this implementation does
the same thing TPU-style: k-blockwise ``lax.scan`` with online softmax
(running max + running sum), O(seq) activation memory, and a custom
blockwise backward (the flash-attention recompute recipe) — all shapes
static so XLA tiles every block matmul onto the MXU.

Layout: ``(batch, heads, seq, head_dim)``.  No seqlen-512 limit.

Returns optionally the per-row logsumexp so ring attention
(:mod:`apex_tpu.transformer.context_parallel`) can merge partial results
across devices.
"""

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


def _block_sizes(sk, block_k):
    bk = min(block_k, sk)
    while sk % bk:
        bk -= 1
    return bk


def padding_bias(kv_mask):
    """(B, Sk) bool key-validity mask (True = valid) → f32 additive score
    bias (B, Sk): 0 for valid keys, NEG_INF for padded ones."""
    return jnp.where(kv_mask, 0.0, NEG_INF).astype(jnp.float32)


def repeat_kv_heads(q, k, v):
    """Grouped-query attention on paths that want full-width kv: repeat
    each kv head across its q-head group (identity when the head counts
    already match).  dk/dv cotangents through the repeat sum over the
    group — the GQA backward semantics — via ``jnp.repeat``'s transpose."""
    H, Hkv = q.shape[1], k.shape[1]
    if Hkv == H:
        return k, v
    if H % Hkv != 0:
        raise ValueError(f"q heads ({H}) not divisible by kv heads ({Hkv})")
    g = H // Hkv
    return jnp.repeat(k, g, axis=1), jnp.repeat(v, g, axis=1)


def _bias_blocks(kv_bias, B, nblocks, bk):
    """Split an additive score bias into k-blocks for the scan.

    Accepts (B, Sk) key-only bias or a broadcastable 4D bias
    (B or 1, H or 1, Sq or 1, Sk); returns a scan input whose element is
    broadcastable against the (B, H, Sq, bk) score block."""
    if kv_bias.ndim == 2:
        kv_bias = kv_bias[:, None, None, :]
    b0, h0, q0, Sk = kv_bias.shape
    blocks = kv_bias.reshape(b0, h0, q0, nblocks, bk)
    return jnp.moveaxis(blocks, 3, 0)  # (nblocks, b0, h0, q0, bk)


def _attend_fwd_scan(q, k, v, scale, causal, q_offset, k_offset, block_k,
                     kv_bias=None):
    """Online-softmax forward.  q: (B,H,Sq,D), k/v: (B,H,Sk,D).
    ``kv_bias``: optional (B, Sk) f32 additive key bias (padding masks).
    Returns (out, lse) with lse = log Σ exp(s·scale) per row."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bk = _block_sizes(Sk, block_k)
    nblocks = Sk // bk

    kb = k.reshape(B, H, nblocks, bk, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, bk, D).transpose(2, 0, 1, 3, 4)

    q_pos = q_offset + jnp.arange(Sq)
    remask = causal or kv_bias is not None

    def body(carry, inp):
        m, l, acc = carry
        if kv_bias is None:
            kblk, vblk, blk_idx = inp
            bblk = None
        else:
            kblk, vblk, blk_idx, bblk = inp
        k_pos = k_offset + blk_idx * bk + jnp.arange(bk)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk) * scale
        if bblk is not None:
            s = s + bblk
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # exp(NEG_INF - NEG_INF) = 1 would give fully-masked rows (ring
        # warmup blocks, fully-padded batch entries) a spurious uniform
        # distribution; re-mask.
        p = jnp.exp(s - m_new[..., None])
        if remask:
            p = jnp.where(s > NEG_INF / 2, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vblk)
        return (m_new, l_new, acc_new), None

    xs = (kb.astype(jnp.float32), vb.astype(jnp.float32), jnp.arange(nblocks))
    if kv_bias is not None:
        xs = xs + (_bias_blocks(kv_bias, B, nblocks, bk),)
    m0 = jnp.full((B, H, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sq), jnp.float32)
    acc0 = jnp.zeros((B, H, Sq, D), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0), xs)
    l = jnp.maximum(l, 1e-30)  # fully-masked rows (causal ring blocks)
    out = acc / l[..., None]
    lse = m + jnp.log(l)
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kv_bias, scale, causal, q_offset, k_offset, block_k):
    out, _ = _attend_fwd_scan(q, k, v, scale, causal, q_offset, k_offset,
                              block_k, kv_bias=kv_bias)
    return out.astype(q.dtype)


def _flash_fwd(q, k, v, kv_bias, scale, causal, q_offset, k_offset, block_k):
    out, lse = _attend_fwd_scan(q, k, v, scale, causal, q_offset, k_offset,
                                block_k, kv_bias=kv_bias)
    return out.astype(q.dtype), (q, k, v, kv_bias, out, lse)


def flash_bwd_from_lse(q, k, v, g, lse, delta, scale, causal, q_offset=0,
                       k_offset=0, block_k=256, kv_bias=None):
    """Blockwise flash backward from (lse, delta): dV = PᵀdO;
    dS = P∘(dOVᵀ − Δ); dQ = dS·K·scale; dK = dSᵀ·Q·scale with
    Δ = rowsum(dO∘O) over the FULL row — pass it in when this call sees
    only a slice of the keys (ring attention's per-chunk backward).
    Returns f32 (dq, dk, dv); memory O(Sq·block_k)."""
    B, H, Sq, Dd = q.shape
    Sk = k.shape[2]
    bk = _block_sizes(Sk, block_k)
    nblocks = Sk // bk

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(Sq)
    remask = causal or kv_bias is not None

    kb = k.reshape(B, H, nblocks, bk, Dd).transpose(2, 0, 1, 3, 4).astype(jnp.float32)
    vb = v.reshape(B, H, nblocks, bk, Dd).transpose(2, 0, 1, 3, 4).astype(jnp.float32)

    if kv_bias is not None:
        bias4 = kv_bias if kv_bias.ndim == 4 else kv_bias[:, None, None, :]
        # d_bias = dS reduced over the dims the bias broadcast along
        bias_reduce = tuple(i for i in range(3) if bias4.shape[i] == 1)

    def body(dq, inp):
        if kv_bias is None:
            kblk, vblk, blk_idx = inp
            bblk = None
        else:
            kblk, vblk, blk_idx, bblk = inp
        k_pos = k_offset + blk_idx * bk + jnp.arange(bk)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk) * scale
        if bblk is not None:
            s = s + bblk
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])  # (B,H,Sq,bk)
        if remask:  # fully-masked rows have lse == NEG_INF: exp(0) = 1
            p = jnp.where(s > NEG_INF / 2, p, 0.0)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vblk)
        ds = p * (dp - delta[..., None])
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk) * scale
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * scale
        if bblk is None:
            return dq, (dk, dv)
        dbias = jnp.sum(ds, axis=bias_reduce, keepdims=True) if bias_reduce else ds
        return dq, (dk, dv, dbias)

    xs = (kb, vb, jnp.arange(nblocks))
    if kv_bias is not None:
        xs = xs + (_bias_blocks(kv_bias, B, nblocks, bk),)
    dq0 = jnp.zeros_like(qf)
    if kv_bias is None:
        dq, (dks, dvs) = jax.lax.scan(body, dq0, xs)
    else:
        dq, (dks, dvs, dbs) = jax.lax.scan(body, dq0, xs)
    dk = dks.transpose(1, 2, 0, 3, 4).reshape(B, H, Sk, Dd)
    dv = dvs.transpose(1, 2, 0, 3, 4).reshape(B, H, Sk, Dd)
    if kv_bias is None:
        return dq, dk, dv
    # assemble d_bias: (nblocks, b0, h0, q0, bk) -> (b0, h0, q0, Sk) -> bias shape
    db = jnp.moveaxis(dbs, 0, 3).reshape(*bias4.shape[:3], Sk)
    if kv_bias.ndim == 2:
        db = db[:, 0, 0, :]
    return dq, dk, dv, db


def _flash_bwd(scale, causal, q_offset, k_offset, block_k, res, g):
    q, k, v, kv_bias, out, lse = res
    delta = jnp.sum(g.astype(jnp.float32) * out, axis=-1)  # (B,H,Sq)
    outs = flash_bwd_from_lse(
        q, k, v, g, lse, delta, scale, causal, q_offset, k_offset, block_k,
        kv_bias=kv_bias,
    )
    if kv_bias is None:
        dq, dk, dv = outs
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), None
    dq, dk, dv, db = outs
    # a trained bias (OpenFold pair bias) gets its real cotangent; a
    # padding-mask bias's consumer (jnp.where over a bool mask) drops it
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            db.astype(kv_bias.dtype))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_k: Optional[int] = None,
    q_offset: int = 0,
    k_offset: int = 0,
    impl: str = "auto",
    block_q: Optional[int] = None,
    kv_mask: Optional[jnp.ndarray] = None,
    attn_bias: Optional[jnp.ndarray] = None,
):
    """Memory-efficient attention, (B, H, S, D) layout.

    ``q_offset``/``k_offset`` give the global sequence positions of the
    local blocks (used by ring attention for cross-device causal masks).

    ``kv_mask``: optional (B, Sk) bool key-validity mask, True = valid —
    padded keys are excluded from every row's softmax (the varlen/
    padding support of ``apex/contrib/fmha/fmha.py:33-60``, expressed as
    a dense mask instead of cu_seqlens because packed ragged layouts are
    hostile to XLA's static shapes).

    ``attn_bias``: optional additive score bias broadcastable as
    (B|1, H|1, Sq|1, Sk) — OpenFold-style pair bias
    (``apex/contrib/openfold_triton/mha.py``); differentiable (its
    cotangent is dS reduced over the broadcast dims).  Runs on the scan
    path (the bias tensor already exists at (…, Sk) granularity, so the
    kernel's HBM saving does not apply to it).

    ``impl``: "pallas" (TPU kernel), "scan" (lax.scan composite), or
    "auto" — the Pallas kernel on TPU with kernel-friendly shapes, the
    scan path everywhere else.  ``block_q``/``block_k`` default to each
    implementation's tuned tile size (scan: 256; pallas: 1024 fwd).

    Grouped-query attention: k/v may carry fewer heads than q (H_kv
    divides H).  The Pallas kernels read the group-shared kv blocks
    directly; the scan path repeats kv heads (its backward sums the
    group — the same semantics).
    """
    if impl not in ("auto", "pallas", "scan"):
        raise ValueError(f"impl must be 'auto', 'pallas', or 'scan'; got {impl!r}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(q.shape[-1])

    def scan_impl(q=q, k=k, v=v, attn_bias=attn_bias):
        k, v = repeat_kv_heads(q, k, v)
        bias = None
        if attn_bias is not None:
            while attn_bias.ndim < 4:
                attn_bias = attn_bias[None]
            bias = attn_bias.astype(jnp.float32)
        if kv_mask is not None:
            pad = padding_bias(kv_mask)
            bias = pad if bias is None else bias + pad[:, None, None, :]
        return _flash(q, k, v, bias, scale, causal, q_offset, k_offset,
                      block_k or 256)

    if impl != "scan" and attn_bias is None:
        from apex_tpu.ops.flash_attention_pallas import (
            flash_attention_pallas,
            pallas_flash_available,
        )

        if impl == "pallas" or pallas_flash_available(q, k):
            # the scan composite is the numerics specification, so a
            # Mosaic/launch failure degrades through the fallback
            # registry (one structured warning) instead of killing the
            # run (apex_tpu.resilience.fallback) — unless the caller
            # FORCED impl="pallas", which must fail loudly (a silent
            # degrade would turn kernel-vs-oracle tests and pallas-vs-
            # scan benchmarks into the reference checking itself)
            from apex_tpu.resilience.fallback import (
                get_registry,
                registry_engaged,
            )

            def kernel_impl():
                return flash_attention_pallas(
                    q, k, v, causal=causal, softmax_scale=scale,
                    q_offset=q_offset, k_offset=k_offset,
                    block_q=block_q, block_k=block_k, kv_mask=kv_mask,
                )

            if registry_engaged(forced=(impl == "pallas")):
                return get_registry().call(
                    "flash_attention", kernel_impl, scan_impl)
            return kernel_impl()
    return scan_impl()


def flash_attention_with_lse(
    q, k, v, causal=True, softmax_scale=None, block_k: int = 256, q_offset=0, k_offset=0
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Forward returning (out, lse) for cross-device merging (no vjp —
    ring attention differentiates through its own scan)."""
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, lse = _attend_fwd_scan(q, k, v, scale, causal, q_offset, k_offset, block_k)
    return out, lse


def mha_reference(q, k, v, causal=True, softmax_scale=None, kv_mask=None):
    """Naive O(S²)-memory oracle for tests (GQA via head repeat)."""
    k, v = repeat_kv_heads(q, k, v)
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
        s = jnp.where(mask, s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
