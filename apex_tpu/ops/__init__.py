"""Kernel/ops layer: pytree multi-tensor primitives, Pallas kernels, and
fused composites.  Reference: ``csrc/`` (see SURVEY.md §2.2)."""

from apex_tpu.ops.fused_ce import fused_lm_head_ce
from apex_tpu.ops.multi_tensor import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_norm_blend,
    multi_tensor_scale,
    tree_not_finite,
    tree_where,
)

__all__ = [
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "multi_tensor_norm_blend",
    "tree_not_finite",
    "tree_where",
    "fused_lm_head_ce",
]
