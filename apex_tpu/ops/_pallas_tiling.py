"""Shared Mosaic tiling facts for the Pallas kernels.

One copy of the hardware contract: Mosaic lays VMEM blocks out in
dtype-dependent (sublane, 128-lane) tiles — fp32 (8, 128), bf16/fp16
(16, 128), int8/fp8 (32, 128).  Both kernel families
(``fused_ce_pallas``, ``flash_attention_pallas``) size their row blocks
from this table; keeping it in one place is exactly the per-dtype drift
the analyzer's APX302 rule polices at the call sites.
"""

import jax.numpy as jnp

LANES = 128

#: per-``pallas_call`` VMEM budget (bytes) — the analyzer's APX304
#: default (~16 MiB/core); block pickers clamp candidates against it
#: instead of discovering the overflow when Mosaic first compiles the
#: kernel on the chip.
VMEM_BUDGET = 16 * 2 ** 20


def sublane(dtype) -> int:
    """The dtype's sublane tile.  Unknown itemsizes (f64 under
    jax_enable_x64 in CPU/interpret numerics checks — no TPU tile
    exists) fall back to the minimum 8 rather than crashing."""
    return {4: 8, 2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 8)


def flash_vmem_bytes(block_q: int, block_k: int, head_dim: int,
                     phase: str = "fwd") -> int:
    """APX304-style lower-bound VMEM footprint (bytes) of one flash
    attention ``pallas_call`` at ``(block_q, block_k)``.

    The same pricing the analyzer applies: BlockSpec elements at
    4 B/element, f32 scratch at 4 B — plus the score-sized (bq, bk) f32
    temporaries the kernel body keeps live (2 in the forward: s, p;
    ~4 in each backward kernel: s, p, dp, ds), which dominate at large
    blocks.  ``phase="bwd"`` prices the larger of the dq / dkv calls.
    Shared between ``flash_attention_pallas._pick_block`` (clamping
    candidates up front) and the tests that pin the clamp.
    """
    bq, bk, d = int(block_q), int(block_k), int(head_dim)
    if phase == "fwd":
        # blocks: q, out (bq·d each), k, v (bk·d each), lse (bq·1);
        # scratch: m, l (bq·LANES each), acc (bq·d) — all f32
        blocks = 2 * bq * d + 2 * bk * d + bq
        scratch = 2 * bq * LANES + bq * d
        temps = 2 * bq * bk
        return 4 * (blocks + scratch + temps)
    if phase != "bwd":
        raise ValueError(f"phase must be 'fwd' or 'bwd', got {phase!r}")
    # dq call: q, do, dq out, acc scratch (bq·d each), k, v (bk·d each),
    # lse, delta (bq·1 each); dkv call: q, do (bq·d), k, v, dk, dv outs
    # and two accumulators (bk·d each), lse, delta (bq·1 each)
    dq_call = 4 * bq * d + 2 * bk * d + 2 * bq
    dkv_call = 2 * bq * d + 6 * bk * d + 2 * bq
    temps = 4 * bq * bk
    return 4 * (max(dq_call, dkv_call) + temps)
