"""Shared Mosaic tiling facts for the Pallas kernels.

One copy of the hardware contract: Mosaic lays VMEM blocks out in
dtype-dependent (sublane, 128-lane) tiles — fp32 (8, 128), bf16/fp16
(16, 128), int8/fp8 (32, 128).  Both kernel families
(``fused_ce_pallas``, ``flash_attention_pallas``) size their row blocks
from this table; keeping it in one place is exactly the per-dtype drift
the analyzer's APX302 rule polices at the call sites.
"""

import jax.numpy as jnp

LANES = 128


def sublane(dtype) -> int:
    """The dtype's sublane tile.  Unknown itemsizes (f64 under
    jax_enable_x64 in CPU/interpret numerics checks — no TPU tile
    exists) fall back to the minimum 8 rather than crashing."""
    return {4: 8, 2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 8)
