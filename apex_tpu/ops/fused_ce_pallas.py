"""Pallas TPU kernels for the fused LM-head + cross-entropy.

Why a kernel when ``ops/fused_ce.py`` already chunks: XLA materializes
each chunk's fp32 logits in HBM between the head matmul and the
reductions that consume them — chunking bounds the PEAK but not the
TRAFFIC (still ~write+read of the full (N, V) fp32 logits each way).
These kernels keep every logits tile in VMEM, flash-attention-style:

- **forward** (grid rows × vocab-tiles, vocab sequential): per tile,
  ``s = x_blk @ e_blkᵀ`` on the MXU, online max/sum-exp update in f32
  scratch, target logit picked up by an in-tile one-hot reduction.
  HBM traffic ≈ one read of x + one read of embed + O(N) outputs —
  the (N, V) logits never exist.
- **backward**: two kernels, mirroring the flash dq/dkv split (one
  output dim must own the sequential revisit, so dx and dembed cannot
  share a grid): each recomputes its tiles' logits, forms
  ``(softmax − onehot)·g`` in-register, and contracts immediately —
  ``dx`` accumulating over vocab tiles in scratch, ``dembed`` over row
  tiles.

MXU dots run with inputs cast to ``dot_dtype`` (bf16 by default) and
f32 accumulation — the same arithmetic XLA's default-precision f32
matmul performs on TPU, so numerics track the unfused head.

Layout: rows are flattened (S·B); the public wrapper in
``ops/fused_ce.py`` handles (S, B, ·) reshapes, tp psum composition,
and the scan fallback off-TPU.  Reference for the semantics being
accelerated: ``apex/transformer/tensor_parallel/cross_entropy.py``
(whose CUDA kernel also never gathers the full vocab row).
"""

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._pallas_tiling import LANES as _LANES
from apex_tpu.ops._pallas_tiling import sublane as _sublane

NEG_INF = -1e30


def _default_dot_dtype():
    """bf16 MXU dots with f32 accumulation — the same arithmetic XLA's
    default-precision f32 matmul uses on TPU, so the kernel tracks the
    unfused head.  APEX_TPU_FUSED_CE_DOT=float32 forces exact f32
    (CPU interpret parity tests; ~4x slower on the MXU)."""
    return jnp.dtype(os.environ.get("APEX_TPU_FUSED_CE_DOT", "bfloat16"))

_DIMSEM_FWD = pltpu.CompilerParams(
    dimension_semantics=("parallel", "arbitrary"))
_DIMSEM_DX = _DIMSEM_FWD
_DIMSEM_DE = pltpu.CompilerParams(
    dimension_semantics=("parallel", "arbitrary"))


def _ceil_block(n, target, align):
    """Aligned block for a ceil-grid: ``target`` when n is big enough,
    else n rounded up to ``align`` (the dtype's sublane tile from
    ``_sublane`` for row blocks, the 128-lane unit for vocab blocks).
    Unlike the flash kernels' divisor search, blocks here need NOT
    divide the array — realistic tp vocab shards (e.g. 50304/8 = 6288 =
    2^4·3·131) have no lane-aligned divisor at all, and a 393-wide tile
    would fail Mosaic's sublane tiling.  Edge tiles overrun the array
    and the kernels mask them (out-of-bounds reads are garbage by the
    Pallas contract)."""
    if n >= target:
        return target
    return -(-n // align) * align


def _grid(n, block):
    return -(-n // block)


# ------------------------------------------------------------------ forward
def _masked_rows(vals, tile_idx, block, limit):
    """Zero an edge tile's overrun rows.  Selecting AFTER a contraction
    is not enough when the garbage is an operand: 0 × NaN = NaN inside
    the dot, so any tensor that feeds the MXU with possibly-OOB rows
    must be cleaned first."""
    rows = jax.lax.broadcasted_iota(jnp.int32, vals.shape, 0)
    return jnp.where(tile_idx * block + rows < limit, vals, 0)


def _masked_scores(x_ref, e_ref, j, bv, V, dot_dtype):
    """This tile's logits with edge-tile overrun columns at NEG_INF
    (Pallas fills out-of-bounds block reads with garbage — every kernel
    must neutralize them before any cross-column reduction)."""
    e = _masked_rows(e_ref[:].astype(dot_dtype), j, bv, V)
    s = jax.lax.dot_general(
        x_ref[:].astype(dot_dtype), e,
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (bn, bv)
    cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    valid = j * bv + cols < V
    s = jnp.where(valid, s, NEG_INF)
    return s, cols, valid, e


def _fwd_kernel(x_ref, e_ref, t_ref, m_out, l_out, tgt_out,
                m_ref, l_ref, tgt_ref, *, bv, nv, V, dot_dtype):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        tgt_ref[:] = jnp.zeros_like(tgt_ref)

    s, cols, valid, _ = _masked_scores(x_ref, e_ref, j, bv, V, dot_dtype)
    m_prev = m_ref[:, 0:1]
    l_prev = l_ref[:, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(jnp.exp(s - m_new), axis=-1, keepdims=True)
    # raw target logit via in-tile one-hot, gated on column VALIDITY:
    # with ceil tiles an out-of-shard local id (tp rows whose target
    # lives on another shard) can land in the padded region where s is
    # the NEG_INF mask — an ungated hit there would accumulate -1e30
    # instead of the 0 the psum contract upstream expects
    local = t_ref[:, 0:1] - j * bv
    hit = (cols == local) & valid
    tgt_new = tgt_ref[:, 0:1] + jnp.sum(
        jnp.where(hit, s, 0.0), axis=-1, keepdims=True)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
    tgt_ref[:] = jnp.broadcast_to(tgt_new, tgt_ref.shape)

    @pl.when(j == nv - 1)
    def _finalize():
        m_out[:] = m_ref[:, 0:1]
        l_out[:] = l_ref[:, 0:1]
        tgt_out[:] = tgt_ref[:, 0:1]


def fused_ce_fwd_pallas(x2, embed, t, dot_dtype=None,
                        block_n=256, block_v=512, interpret=False):
    """x2 (N, H), embed (V, H), t (N,) int32 (shard-LOCAL ids in tp).

    Returns (m, l, tgt) each (N,): running max, sum-exp at that max,
    and the raw target logit (0 where t lands outside [0, V)).  The
    caller combines — ``lse = m + log l`` dense, or pmax/psum first
    under tp."""
    dot_dtype = dot_dtype or _default_dot_dtype()
    N, H = x2.shape
    V = embed.shape[0]
    bn = _ceil_block(N, block_n, align=_sublane(x2.dtype))
    bv = _ceil_block(V, block_v, align=_LANES)
    nn, nv = _grid(N, bn), _grid(V, bv)

    kernel = functools.partial(_fwd_kernel, bv=bv, nv=nv, V=V,
                               dot_dtype=dot_dtype)
    m, l, tgt = pl.pallas_call(
        kernel,
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((N, 1), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((bn, _LANES), jnp.float32)] * 3,
        compiler_params=_DIMSEM_FWD,
        interpret=interpret,
    )(x2, embed, t.reshape(N, 1).astype(jnp.int32))
    return m[:, 0], l[:, 0], tgt[:, 0]


# ------------------------------------------------------------- backward: dx
def _dx_kernel(x_ref, e_ref, t_ref, lse_ref, g_ref, dx_out,
               acc_ref, *, bv, nv, V, dot_dtype):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # masked s -> p = 0 at overrun columns, and the cleaned (zeroed)
    # embed rows keep 0 x garbage out of the second contraction
    s, cols, valid, e_clean = _masked_scores(x_ref, e_ref, j, bv, V, dot_dtype)
    p = jnp.exp(s - lse_ref[:, 0:1])
    local = t_ref[:, 0:1] - j * bv
    d = (p - ((cols == local) & valid).astype(jnp.float32)) * g_ref[:, 0:1]
    acc_ref[:] += jax.lax.dot_general(
        d.astype(dot_dtype), e_clean,
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (bn, H)

    @pl.when(j == nv - 1)
    def _finalize():
        dx_out[:] = acc_ref[:].astype(dx_out.dtype)


# --------------------------------------------------------- backward: dembed
def _dembed_kernel(x_ref, e_ref, t_ref, lse_ref, g_ref, de_out,
                   acc_ref, *, bn, bv, nn, N, V, dot_dtype):
    # grid is (v-tiles, row-tiles): i owns the output tile, j sweeps rows
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s, cols, valid, _ = _masked_scores(x_ref, e_ref, i, bv, V, dot_dtype)
    p = jnp.exp(s - lse_ref[:, 0:1])
    local = t_ref[:, 0:1] - i * bv
    d = (p - ((cols == local) & valid).astype(jnp.float32)) * g_ref[:, 0:1]
    # rows mix here (dᵀ @ x) — unlike the row-local fwd/dx kernels an
    # overrun ROW's garbage (possibly NaN: 0 x NaN = NaN in the dot)
    # would contaminate every vocab row: mask d's rows by select AND
    # zero x's overrun rows before they touch the MXU
    d = _masked_rows(d, j, bn, N)
    x_clean = _masked_rows(x_ref[:].astype(dot_dtype), j, bn, N)
    acc_ref[:] += jax.lax.dot_general(
        d.astype(dot_dtype), x_clean,
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (bv, H)

    @pl.when(j == nn - 1)
    def _finalize():
        de_out[:] = acc_ref[:]


def fused_ce_bwd_pallas(x2, embed, t, lse, g, dot_dtype=None,
                        block_n=256, block_v=512, interpret=False):
    """Gradients of ``sum(g * (lse - tgt))`` wrt x2 and embed.

    ``lse`` must be the GLOBAL logsumexp (already pmax/psum-combined in
    tp) so ``exp(s - lse)`` is the global softmax; dx comes back
    shard-local (the caller's copy-to-region psums it) and dembed is
    this shard's slice — the same contract as the scan path."""
    dot_dtype = dot_dtype or _default_dot_dtype()
    N, H = x2.shape
    V = embed.shape[0]
    bn = _ceil_block(N, block_n, align=_sublane(x2.dtype))
    bv = _ceil_block(V, block_v, align=_LANES)
    nn, nv = _grid(N, bn), _grid(V, bv)
    t2 = t.reshape(N, 1).astype(jnp.int32)
    lse2 = lse.reshape(N, 1).astype(jnp.float32)
    g2 = g.reshape(N, 1).astype(jnp.float32)

    row_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                            memory_space=pltpu.VMEM)
    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=bv, nv=nv, V=V,
                          dot_dtype=dot_dtype),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            row_spec, row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec((bn, H), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, H), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bn, H), jnp.float32)],
        compiler_params=_DIMSEM_DX,
        interpret=interpret,
    )(x2, embed, t2, lse2, g2)

    vrow_spec = pl.BlockSpec((bn, 1), lambda i, j: (j, 0),
                             memory_space=pltpu.VMEM)
    dembed = pl.pallas_call(
        functools.partial(_dembed_kernel, bn=bn, bv=bv, nn=nn, N=N, V=V,
                          dot_dtype=dot_dtype),
        grid=(nv, nn),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bv, H), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            vrow_spec, vrow_spec, vrow_spec,
        ],
        out_specs=pl.BlockSpec((bv, H), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((V, H), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bv, H), jnp.float32)],
        compiler_params=_DIMSEM_DE,
        interpret=interpret,
    )(x2, embed, t2, lse2, g2)
    return dx, dembed
