"""Pallas TPU fused sampling head: hidden → sampled token, no HBM logits.

The decode-side sibling of :mod:`apex_tpu.ops.fused_ce_pallas`, and the
second fusion the operation-fusion paper calls out for small-batch
decode (arxiv 2502.17728): the LM head matmul, temperature, top-k
restriction, and the categorical draw collapse into ONE kernel over
vocab tiles — the (B, V) fp32 logits (200 KB/row at 50k vocab) are
never written to HBM, let alone the softmax over them.

Sampling is the **Gumbel-max trick**: ``argmax_v(logits_v / T + g_v)``
with ``g_v`` i.i.d. standard Gumbel draws a token from exactly
``softmax(logits / T)`` — an online argmax reduction, which streams
over vocab tiles the way the fused-CE forward streams its logsumexp.
The Gumbel noise comes from a **counter-based hash** of (per-row seed,
vocab column) — pure uint32 vector math, identical in the kernel and
the XLA reference, so the two implementations draw the SAME token for
the same seed (bitwise parity is testable, unlike a kernel-side PRNG).

Top-k runs as a first sweep over the same tiles: a per-row running
top-K scratch (K <= 128, one lane row) is merged with each tile by a
K-step select-extract loop; the k-th largest (the min of the scratch)
then thresholds the sampling sweep.  The grid is
``(row_tiles, sweeps * vocab_tiles)`` with the vocab dimension
sequential, so the whole head is still one kernel launch.

The XLA reference :func:`fused_sample_xla` materializes the logits and
is the numerics specification; kernel failures degrade to it once via
:mod:`apex_tpu.resilience.fallback` ("decode_sampling").
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._pallas_tiling import LANES as _LANES
from apex_tpu.ops._pallas_tiling import sublane as _sublane
from apex_tpu.ops.fused_ce_pallas import (
    NEG_INF, _ceil_block, _grid, _masked_scores,
)

#: the kernel's running top-K scratch is one (sublane, lane) tile row
#: per sequence row — K beyond the 128-lane tile would need a second
#: lane row and a cross-lane merge; the dispatch falls back to XLA
MAX_KERNEL_TOP_K = 128


# ------------------------------------------------------------ shared noise
def _hash_u32(z):
    """Counter-based uint32 mix (splitmix-style avalanche).  Pure
    vector integer ops so the kernel and the XLA reference compute the
    IDENTICAL stream — the property the sampling parity tests pin."""
    z = z * jnp.uint32(2654435761)
    z = z ^ (z >> 16)
    z = z * jnp.uint32(0x45D9F3B)
    z = z ^ (z >> 16)
    z = z * jnp.uint32(0x45D9F3B)
    z = z ^ (z >> 16)
    return z


def gumbel_from_seed(seeds, cols):
    """Standard Gumbel noise for (row seed, vocab column) pairs.

    ``seeds`` uint32 broadcastable against int32 ``cols``; the uniform
    is built from the hash's top 24 bits at odd half-steps
    (``(bits + 0.5) / 2^24``), so it lives in the OPEN interval (0, 1)
    and the double log never hits an infinity."""
    z = _hash_u32(seeds.astype(jnp.uint32)
                  ^ (cols.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)))
    u = ((z >> 8).astype(jnp.float32) + 0.5) * jnp.float32(1.0 / (1 << 24))
    return -jnp.log(-jnp.log(u))


# ---------------------------------------------------------------- reference
def fused_sample_xla(x2, embed, seeds, temperature=1.0, top_k=0):
    """Sample one token per row from the tied LM head, in XLA.

    ``x2`` (N, H) pre-head activations; ``embed`` (V, H); ``seeds``
    (N,) uint32.  ``temperature == 0`` is greedy argmax; ``top_k > 0``
    restricts the draw to the k largest logits (ties at the k-th value
    are INCLUDED — the same ``>=`` semantics as the kernel's threshold).
    Returns (N,) int32 token ids.  Materializes the (N, V) fp32 logits
    — this is the specification and the degrade target, not the fast
    path."""
    logits = jnp.matmul(x2.astype(jnp.float32),
                        embed.T.astype(jnp.float32))
    N, V = logits.shape
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    cand = logits / jnp.float32(temperature)
    cols = jnp.arange(V, dtype=jnp.int32)
    cand = cand + gumbel_from_seed(seeds[:, None], cols[None, :])
    if top_k and top_k < V:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        cand = jnp.where(logits >= kth, cand, NEG_INF)
    return jnp.argmax(cand, axis=-1).astype(jnp.int32)


# ------------------------------------------------------------------ kernel
def _merge_top_k(running, s, k):
    """Merge one tile's scores into the running per-row top-K values:
    K iterations of (argmax, extract, mask-one) over the concatenated
    candidates — no sort primitive, so Mosaic only needs max/argmax.
    ``running``/result: (bn, LANES) f32 with columns >= k at -inf."""
    cur = jnp.concatenate([running, s], axis=1)
    out0 = jnp.full_like(running, NEG_INF)

    def body(i, carry):
        cur, out = carry
        m = jnp.max(cur, axis=1, keepdims=True)
        am = jnp.argmax(cur, axis=1)
        oh = (jax.lax.broadcasted_iota(jnp.int32, cur.shape, 1)
              == am[:, None])
        out = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, out.shape, 1) == i, m, out)
        return jnp.where(oh, NEG_INF, cur), out

    _, out = jax.lax.fori_loop(0, k, body, (cur, out0))
    return out


def _sample_kernel(x_ref, e_ref, seed_ref, tok_out,
                   topk_ref, best_v, best_i, *,
                   bv, nv, V, dot_dtype, temperature, top_k, sweeps):
    j = pl.program_id(1)
    jj = j % nv

    @pl.when(j == 0)
    def _init():
        topk_ref[:] = jnp.full_like(topk_ref, NEG_INF)
        best_v[:] = jnp.full_like(best_v, NEG_INF)
        best_i[:] = jnp.zeros_like(best_i)

    s, cols, valid, _ = _masked_scores(x_ref, e_ref, jj, bv, V, dot_dtype)

    if sweeps == 2:
        @pl.when(j < nv)
        def _threshold_sweep():
            topk_ref[:] = _merge_top_k(topk_ref[:], s, top_k)

    @pl.when(j >= (nv if sweeps == 2 else 0))
    def _sample_sweep():
        elig = valid
        if sweeps == 2:
            lane = jax.lax.broadcasted_iota(jnp.int32, topk_ref.shape, 1)
            tau = jnp.min(jnp.where(lane < top_k, topk_ref[:], jnp.inf),
                          axis=1, keepdims=True)
            elig = elig & (s >= tau)
        if temperature > 0.0:
            gcols = jj * bv + cols
            g = gumbel_from_seed(seed_ref[:, 0:1].astype(jnp.uint32), gcols)
            cand = s / jnp.float32(temperature) + g
        else:
            cand = s
        cand = jnp.where(elig, cand, NEG_INF)
        m = jnp.max(cand, axis=1, keepdims=True)
        idx = (jnp.argmax(cand, axis=1).astype(jnp.int32)
               + jj * bv)[:, None]
        # strict > : on an exact cross-tile tie the EARLIER tile wins,
        # matching jnp.argmax's first-hit semantics in the reference
        better = m > best_v[:, 0:1]
        best_i[:] = jnp.broadcast_to(
            jnp.where(better, idx, best_i[:, 0:1]), best_i.shape)
        best_v[:] = jnp.broadcast_to(
            jnp.where(better, m, best_v[:, 0:1]), best_v.shape)

    @pl.when(j == sweeps * nv - 1)
    def _finalize():
        tok_out[:] = best_i[:, 0:1]


def fused_sample_pallas(x2, embed, seeds, temperature=1.0, top_k=0,
                        dot_dtype=None, block_n=256, block_v=512,
                        interpret=False):
    """The fused sampling-head launcher (see module doc).  Shapes and
    semantics as :func:`fused_sample_xla`; ``dot_dtype`` as in the
    fused-CE kernels (bf16 MXU dots with f32 accumulation by default,
    f32 for exact-parity tests)."""
    from apex_tpu.ops.fused_ce_pallas import _default_dot_dtype

    dot_dtype = dot_dtype or _default_dot_dtype()
    N, H = x2.shape
    V = embed.shape[0]
    greedy = temperature <= 0.0
    sweeps = 2 if (top_k and top_k < V and not greedy) else 1
    if sweeps == 2 and top_k > MAX_KERNEL_TOP_K:
        raise ValueError(
            f"the kernel's running top-k scratch holds one lane tile "
            f"({MAX_KERNEL_TOP_K}); top_k={top_k} must take the XLA path")
    bn = _ceil_block(N, block_n, align=_sublane(x2.dtype))
    bv = _ceil_block(V, block_v, align=_LANES)
    nn, nv = _grid(N, bn), _grid(V, bv)

    tok = pl.pallas_call(
        functools.partial(
            _sample_kernel, bv=bv, nv=nv, V=V, dot_dtype=dot_dtype,
            temperature=float(temperature),
            top_k=int(top_k) if sweeps == 2 else 0, sweeps=sweeps,
        ),
        grid=(nn, sweeps * nv),
        in_specs=[
            pl.BlockSpec((bn, H), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            # the sampling sweep revisits the vocab tiles: j % nv maps
            # both sweeps onto the same embed block sequence
            pl.BlockSpec((bv, H), lambda i, j: (j % nv, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((N, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.float32),
            pltpu.VMEM((bn, _LANES), jnp.int32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x2, embed, seeds.reshape(N, 1).astype(jnp.uint32))
    return tok[:, 0]


# ---------------------------------------------------------------- dispatch
def pallas_sample_available(x2, embed, top_k) -> bool:
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        return False
    return (on_tpu and (not top_k or top_k <= MAX_KERNEL_TOP_K)
            and x2.dtype in (jnp.float32, jnp.bfloat16))


def fused_sample(x2, embed, seeds, temperature=1.0, top_k=0,
                 impl="auto", dot_dtype=None):
    """hidden (N, H) → sampled token ids (N,): the ONE dispatch between
    the fused Pallas sampling head and the materialize-then-sample XLA
    reference.  ``impl`` as in
    :func:`apex_tpu.ops.decode_attention_pallas.decode_attention`;
    chosen kernel use degrades once through the fallback registry
    ("decode_sampling")."""
    if impl not in ("auto", "pallas", "interpret", "xla"):
        raise ValueError(
            f"impl must be 'auto', 'pallas', 'interpret', or 'xla'; "
            f"got {impl!r}")

    def xla_impl():
        return fused_sample_xla(x2, embed, seeds, temperature=temperature,
                                top_k=top_k)

    if impl == "xla":
        return xla_impl()
    forced = impl in ("pallas", "interpret")
    if not forced and not pallas_sample_available(x2, embed, top_k):
        return xla_impl()

    def kernel_impl():
        return fused_sample_pallas(
            x2, embed, seeds, temperature=temperature, top_k=top_k,
            dot_dtype=dot_dtype, interpret=(impl == "interpret"))

    from apex_tpu.resilience.fallback import get_registry, registry_engaged

    if registry_engaged(forced=forced):
        return get_registry().call("decode_sampling", kernel_impl, xla_impl)
    return kernel_impl()
