"""Pallas TPU paged single-query decode attention.

The decode-side sibling of :mod:`apex_tpu.ops.flash_attention_pallas`:
one generated token per sequence attends over that sequence's KV cache,
which lives as fixed-size *pages* scattered through a preallocated pool
(:mod:`apex_tpu.inference.kv_cache`).  Small-batch decode is dominated
by the softmax reductions and per-op launch overheads around a tiny
matmul (PAPERS.md: "LLM Inference Acceleration via Efficient Operation
Fusion", arxiv 2502.17728), so the whole per-head attention — page
gather, scores, online softmax, weighted sum — runs as ONE kernel:

- grid ``(batch, kv_heads, pages_per_seq)``, pages sequential;
- the page table rides as a **scalar-prefetch** operand
  (``pltpu.PrefetchScalarGridSpec``), so each k/v BlockSpec index map
  dereferences ``page_table[b, p]`` and the DMA fetches exactly that
  page out of the pool — the gathered (B, S_max, H_kv, D) key tensor
  the XLA reference materializes in HBM never exists here;
- grouped-query attention reads the group-shared kv page ONCE per kv
  head and scores all ``H // H_kv`` q heads of the group against it
  (no ``repeat_kv_heads`` materialization, same as the flash kernels);
- the per-sequence length masks both granularities: whole pages past
  the length are skipped via ``pl.when`` (no wasted MXU work on a
  fresh sequence in a long-cache-shaped step), and the tail page is
  masked per position.

The XLA reference :func:`decode_attention_xla` is the numerics
specification: it mirrors the TRAINING attention expression
(scores / sqrt(D), ``-10000.0`` mask fill, fp32 softmax — the
``scaled_upper_triang_masked_softmax`` semantics) exactly, so
token-by-token decode logits can be pinned against the full-sequence
training forward bitwise in fp32 (tests/test_inference.py).  Kernel
failures degrade to it once through
:mod:`apex_tpu.resilience.fallback` ("decode_attention").
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._pallas_tiling import LANES as _LANES
from apex_tpu.transformer.functional.fused_softmax import MASK_FILL_VALUE

NEG_INF = -1e30

_DIM_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


# ---------------------------------------------------------------- reference
def decode_attention_xla(q, k_pool, v_pool, page_table, lengths,
                         softmax_scale=None, width=1):
    """Single-query attention over a paged KV cache, in XLA.

    ``q``: (B, H, D) — one query per sequence (the current token's
    heads).  ``k_pool``/``v_pool``: (num_pages, page_size, H_kv, D)
    one layer's page pool.  ``page_table``: (B, P) int32 page ids,
    CLAMPED into the pool before the gather (a stale/garbage entry
    reads the reserved garbage page instead of wrapping).  ``lengths``:
    (B,) int32 valid cache positions per sequence (0 = inactive slot —
    every position masks out and the output row is 0).

    ``width`` > 1 is the verify/chunk layout: q rows come in groups of
    ``width`` CONSECUTIVE positions of one sequence (speculative
    verification, a prefill chunk), so ``q``/``lengths`` are
    (B * width, ...) while ``page_table`` stays (B, P) — the pool pages
    are gathered ONCE per sequence and scored against all of its
    ``width`` queries, each under its own length mask.

    Returns (B, H, D) in ``v_pool``'s dtype.  The expression mirrors
    the training attention row-for-row (division by sqrt(D), -1e4 mask
    fill, fp32 softmax, probs cast to v's dtype before the weighted
    sum) so decode logits can be compared bitwise against the training
    forward in fp32.
    """
    Bq, H, D = q.shape
    num_pages, page_size, h_kv, _ = k_pool.shape
    B, P = page_table.shape
    group = H // h_kv
    if B * width != Bq:
        raise ValueError(
            f"q rows ({Bq}) must equal page-table rows ({B}) x width "
            f"({width})")
    pt = jnp.clip(page_table, 0, num_pages - 1)
    # (B, P, page, H_kv, D) -> (B, H_kv, S_max, D)
    k = k_pool[pt].reshape(B, P * page_size, h_kv, D).transpose(0, 2, 1, 3)
    v = v_pool[pt].reshape(B, P * page_size, h_kv, D).transpose(0, 2, 1, 3)
    if group > 1:
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    # the storage dtype may be narrower than the scores' f32: widen the
    # cache reads explicitly at the seam (the APX306 contract)
    kf = k.astype(jnp.float32)
    t = jnp.arange(P * page_size, dtype=jnp.int32)
    if width > 1:
        qf = q.astype(jnp.float32).reshape(B, width, H, D)
        if softmax_scale is None:
            scores = jnp.einsum("bwhd,bhtd->bwht", qf, kf) / np.sqrt(D)
        else:
            scores = jnp.einsum("bwhd,bhtd->bwht", qf, kf) * softmax_scale
        lw = lengths.reshape(B, width)
        valid = t[None, None, None, :] < lw[:, :, None, None]
        scores = jnp.where(valid, scores, MASK_FILL_VALUE)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bwht,bhtd->bwhd", probs.astype(v.dtype), v)
        ctx = jnp.where(lw[:, :, None, None] > 0, ctx,
                        jnp.zeros_like(ctx))
        return ctx.reshape(Bq, H, D)
    qf = q.astype(jnp.float32)
    if softmax_scale is None:
        scores = jnp.einsum("bhd,bhtd->bht", qf, kf) / np.sqrt(D)
    else:
        scores = jnp.einsum("bhd,bhtd->bht", qf, kf) * softmax_scale
    valid = t[None, None, :] < lengths[:, None, None]
    scores = jnp.where(valid, scores, MASK_FILL_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bht,bhtd->bhd", probs.astype(v.dtype), v)
    # an ALL-masked row (inactive slot, length 0) softmaxes to a
    # uniform distribution over garbage pages; pin it to the kernel's
    # semantic (zero output).  Active rows always have >= 1 valid
    # position, so the training-parity expression above is untouched.
    return jnp.where(lengths[:, None, None] > 0, ctx,
                     jnp.zeros_like(ctx))


# ------------------------------------------------------------------ kernel
def _decode_attn_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                        m_ref, l_ref, acc_ref, *,
                        page_size, pages_per_seq, denom, scale):
    """One (sequence, kv-head) pair; the sequential grid dim walks that
    sequence's pages through VMEM.  Online softmax exactly as the flash
    forward: running max/sum/accumulator in f32 scratch, finalize on
    the last page."""
    b, p = pl.program_id(0), pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[b]

    # whole pages at/after the length hold no valid position: skip the
    # dots entirely (a freshly-admitted sequence costs page-1 work even
    # when the step shape is sized for the longest resident cache)
    @pl.when(p * page_size < length)
    def _compute():
        q = q_ref[0, 0]          # (group, D)
        k = k_ref[0, :, 0, :]    # (page, D) — group-shared GQA page
        v = v_ref[0, :, 0, :]
        if k.dtype != q.dtype:
            # bf16 (or narrower) cache with an f32 query: widen the
            # cache read rather than rounding q down (APX306)
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        s = s / denom if scale is None else s * scale
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(s > NEG_INF / 2, pexp, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(pexp, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0:1], 1e-30)  # inactive rows: l == 0
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def paged_decode_attention_pallas(q, k_pool, v_pool, page_table, lengths,
                                  softmax_scale=None, width=1,
                                  interpret=False):
    """The Pallas paged decode-attention launcher (see module doc).

    Shapes as :func:`decode_attention_xla`.  The flattened page table
    and the lengths ride as scalar-prefetch operands so the k/v
    BlockSpec index maps can dereference them — each grid step DMAs
    exactly one (page_size, D) page of the group-shared kv head out of
    the pool.  With ``width`` > 1 (the verify/chunk layout: q rows in
    groups of ``width`` consecutive positions of one sequence) the
    index maps fold the row back onto its sequence's table row —
    ``pt[(b // width) * P + p]`` — so the table is prefetched once per
    SEQUENCE, not once per query row; ``width`` is static, one compile
    per verify width.
    """
    B, H, D = q.shape
    num_pages, page_size, h_kv, _ = k_pool.shape
    n_seq, P = page_table.shape
    if H % h_kv != 0:
        raise ValueError(f"q heads ({H}) not divisible by kv heads ({h_kv})")
    if n_seq * width != B:
        raise ValueError(
            f"q rows ({B}) must equal page-table rows ({n_seq}) x width "
            f"({width})")
    group = H // h_kv
    qg = q.reshape(B, h_kv, group, D)
    # clamp BEFORE prefetch: the index map output becomes a DMA source
    # address, where a garbage entry must hit the reserved garbage page,
    # never wrap (APX107's contract for page-table gathers)
    pt = jnp.clip(page_table, 0, num_pages - 1) \
        .reshape(n_seq * P).astype(jnp.int32)

    kv_spec = pl.BlockSpec(
        (1, page_size, 1, D),
        lambda b, g, p, pt_ref, len_ref: (pt_ref[(b // width) * P + p],
                                          0, g, 0),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, h_kv, P),
        in_specs=[
            pl.BlockSpec((1, 1, group, D),
                         lambda b, g, p, pt_ref, len_ref: (b, g, 0, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, group, D),
                               lambda b, g, p, pt_ref, len_ref: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_attn_kernel, page_size=page_size, pages_per_seq=P,
            denom=float(np.sqrt(D)), scale=softmax_scale,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h_kv, group, D), v_pool.dtype),
        compiler_params=_DIM_SEMANTICS,
        interpret=interpret,
    )(pt, lengths.astype(jnp.int32), qg, k_pool, v_pool)
    return out.reshape(B, H, D)


# ---------------------------------------------------------------- dispatch
def pallas_decode_attn_available(q, k_pool) -> bool:
    """Kernel path: real TPU, MXU-friendly head dim, sublane-aligned
    pages.  (No env-var override — thread ``attn_impl`` through
    :class:`apex_tpu.inference.DecodeConfig` instead; APX101/102.)"""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:
        return False
    return (on_tpu and q.shape[-1] % 8 == 0 and k_pool.shape[1] % 8 == 0
            and q.dtype in (jnp.float32, jnp.bfloat16))


def decode_attention(q, k_pool, v_pool, page_table, lengths,
                     impl="auto", softmax_scale=None, width=1):
    """Paged single-query decode attention — the ONE dispatch between
    the Pallas kernel and the XLA reference.

    ``impl``: "auto" (kernel on TPU, reference elsewhere), "pallas"
    (force the kernel, fail loudly), "interpret" (kernel via the Pallas
    interpreter — the CPU test path), or "xla".  ``width`` > 1 scores
    groups of consecutive positions per sequence against one shared
    page-table row (speculative verification / chunked prefill — see
    :func:`decode_attention_xla`).  Chosen (non-forced) kernel use
    routes through the resilience fallback registry
    ("decode_attention"): the first Mosaic/launch failure degrades this
    process to the reference once, with one structured warning, instead
    of killing the serve loop (:mod:`apex_tpu.resilience.fallback`).
    """
    if impl not in ("auto", "pallas", "interpret", "xla"):
        raise ValueError(
            f"impl must be 'auto', 'pallas', 'interpret', or 'xla'; "
            f"got {impl!r}")

    def xla_impl():
        return decode_attention_xla(q, k_pool, v_pool, page_table, lengths,
                                    softmax_scale=softmax_scale, width=width)

    if impl == "xla":
        return xla_impl()
    forced = impl in ("pallas", "interpret")
    if not forced and not pallas_decode_attn_available(q, k_pool):
        return xla_impl()

    def kernel_impl():
        return paged_decode_attention_pallas(
            q, k_pool, v_pool, page_table, lengths,
            softmax_scale=softmax_scale, width=width,
            interpret=(impl == "interpret"))

    from apex_tpu.resilience.fallback import get_registry, registry_engaged

    if registry_engaged(forced=forced):
        return get_registry().call("decode_attention", kernel_impl, xla_impl)
    return kernel_impl()
