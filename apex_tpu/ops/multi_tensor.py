"""Multi-tensor primitives over pytrees.

Reference: ``csrc/multi_tensor_apply.cuh`` + the ``amp_C`` kernel family
(``csrc/multi_tensor_scale_kernel.cu``, ``..._axpby_kernel.cu``,
``..._l2norm_kernel.cu``).  The reference packs ≤110 tensor pointers and a
chunk table into kernel launch metadata because CUDA needs one launch to
cover many tensors.  Under XLA there is no launch-per-tensor problem —
the whole update is one compiled program and XLA fuses the elementwise
work — so the TPU-native design is simply *tree-level math in one jit
region*.  The ``noop_flag`` output buffer becomes a returned boolean
(non-finite detected), and the early-exit-on-noop semantics become a
``jnp.where`` predication at the caller.

These functions are the building blocks for :mod:`apex_tpu.optimizers`
and :mod:`apex_tpu.amp`.

Bucket views: every op here also accepts a
:class:`apex_tpu.optimizers.bucketing.Buckets` (the multi-tensor
engine's flat dtype-bucket form) anywhere a pytree is accepted —
``Buckets`` is a registered pytree whose leaves are the 1-D bucket
buffers, so the elementwise ops (``scale``/``axpby``) map over the
buffers directly and return ``Buckets`` of the same plan, and the
reductions (``l2norm`` per-tensor, ``norm_blend``) slice the buffers
back into per-leaf views via the plan so their results match the tree
form leaf for leaf.  Padding is zero-filled by ``bucketing.pack``, so
the finite votes and L2 sums over a bucket equal the votes/sums over
its leaves.
"""

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

Tree = Any


def _bucket_view(tree):
    """``(plan, arrays)`` when ``tree`` is a Buckets, else ``None`` —
    lazy import so ``ops`` does not import ``optimizers`` at package
    init (bucketing imports ``ops._pallas_tiling``)."""
    from apex_tpu.optimizers.bucketing import Buckets

    if isinstance(tree, Buckets):
        return tree.plan, tree.arrays
    return None


def tree_not_finite(tree: Tree) -> jnp.ndarray:
    """True if ANY element anywhere in the tree is inf/nan (noop_flag=1).
    On a ``Buckets`` the vote is over the bucket buffers — pad regions
    are zero-filled, so the vote equals the per-leaf vote."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.bool_(False)
    return ~jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()


def multi_tensor_scale(src: Tree, scale, out_dtype=None) -> Tuple[Tree, jnp.ndarray]:
    """``out = src * scale`` with inf/nan detection.

    Reference: ``csrc/multi_tensor_scale_kernel.cu`` (ScaleFunctor) — used
    by the amp unscale path and master↔model param copies.  Returns
    ``(out_tree, found_inf)``.
    """

    def one(x):
        y = x.astype(jnp.float32) * scale
        return y.astype(out_dtype or x.dtype)

    out = jax.tree.map(one, src)
    return out, tree_not_finite(out)


def multi_tensor_axpby(a, x_tree: Tree, b, y_tree: Tree, out_dtype=None) -> Tuple[Tree, jnp.ndarray]:
    """``out = a*x + b*y`` elementwise over matching trees.

    Reference: ``csrc/multi_tensor_axpby_kernel.cu`` (AxpbyFunctor) — used
    by amp's add_scaled paths.
    """

    def one(x, y):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        return r.astype(out_dtype or x.dtype)

    out = jax.tree.map(one, x_tree, y_tree)
    return out, tree_not_finite(out)


def multi_tensor_l2norm(tree: Tree, per_tensor: bool = False):
    """Global L2 norm over all leaves, optionally per-leaf norms too.

    Reference: ``csrc/multi_tensor_l2norm_kernel.cu`` — used by FusedLAMB,
    clip_grad, and DistributedFusedAdam/LAMB.  Math in fp32.
    Returns ``global_norm`` or ``(global_norm, [per_leaf_norms])``.

    On a ``Buckets`` the per-tensor norms are per ORIGINAL LEAF (the
    plan's offset table slices each leaf back out of its bucket), not
    per bucket buffer — same list, same order, as the tree form.
    """
    bv = _bucket_view(tree)
    if bv is not None:
        from apex_tpu.optimizers.bucketing import per_leaf_reduce

        plan, arrays = bv
        sq = per_leaf_reduce(
            plan, [a.astype(jnp.float32) for a in arrays],
            lambda x: jnp.sum(jnp.square(x)))
    else:
        sq = [jnp.sum(jnp.square(x.astype(jnp.float32)))
              for x in jax.tree.leaves(tree)]
    if not sq:
        z = jnp.float32(0)
        return (z, []) if per_tensor else z
    total = jnp.sqrt(jnp.stack(sq).sum())
    if per_tensor:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def multi_tensor_norm_blend(old_norms: Sequence[jnp.ndarray], tree: Tree, a: float, b: float, norm_type: int = 2):
    """Blend per-leaf norms with fresh norms of ``tree``.

    Reference: ``multi_tensor_norm_out_cuda`` in
    ``csrc/multi_tensor_novograd.cu:160-164``:
    L2:   ``gn = sqrt(a*gn^2 + b*n^2)``;  L-inf: ``gn = a*gn + b*n``.
    ``old_norms`` is per ORIGINAL LEAF; on a ``Buckets`` the fresh
    norms are taken over the plan's per-leaf slices to match.
    """
    bv = _bucket_view(tree)
    if bv is not None:
        from apex_tpu.optimizers.bucketing import per_leaf_reduce

        plan, arrays = bv
        leaves = per_leaf_reduce(plan, arrays, lambda x: x)
    else:
        leaves = jax.tree.leaves(tree)
    out = []
    for gn, x in zip(old_norms, leaves):
        x32 = x.astype(jnp.float32)
        if norm_type == 2:
            n2 = jnp.sum(jnp.square(x32))
            out.append(jnp.sqrt(a * jnp.square(gn) + b * n2))
        elif norm_type == 0:
            n = jnp.max(jnp.abs(x32))
            out.append(a * gn + b * n)
        else:
            raise ValueError("norm_type must be 0 (L-inf) or 2 (L2)")
    return out


def tree_where(pred, true_tree: Tree, false_tree: Tree) -> Tree:
    """Leafwise ``jnp.where(pred, a, b)`` — the XLA form of the reference's
    early-exit ``if (*noop_gmem) return;`` predication."""
    return jax.tree.map(lambda t, f: jnp.where(pred, t, f.astype(t.dtype)), true_tree, false_tree)
